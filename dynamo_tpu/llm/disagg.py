"""Disaggregated prefill/decode orchestration.

The reference's headline feature (`docs/architecture/disagg_serving.md:
12-64`): long prefills run on dedicated prefill workers so decode batches
never stall behind them; KV crosses workers on the data plane.  The
components, mapped onto our runtime:

- **DisaggRouter** (decode-side admission) — the conditional local/remote
  decision with a control-plane-watched threshold, the analog of
  `lib/llm/src/disagg_router.rs:25-50` (`DisaggRouterConf
  {max_local_prefill_length}` read + hot-reloaded from etcd).
- **Prefill queue** — an acked work queue on the control plane (the
  reference's NATS JetStream `NatsQueue`, `transports/nats.rs:360`):
  at-least-once, so a prefill worker dying mid-job redelivers rather than
  losing the request.
- **prefill_worker_loop** — pops jobs, runs the prompt through the local
  engine (one token, discarded), which seals + registers the prompt's KV
  blocks; then announces completion with its RPC address.
- **DisaggDecodeClient** — decode-side EngineClient wrapper: long prompts
  are enqueued for remote prefill, completion is awaited, the sealed
  blocks are pulled over the kv_blocks data plane
  (block_manager/transfer.py `pull_prefix`), and only then does the local
  engine run — whose prefix-cache match skips everything but the last
  partial block.  Remote failure (timeout, dead prefill worker) falls
  back to local prefill: disagg is an optimisation, never a correctness
  dependency (the reference decode handler behaves the same,
  `components/backends/vllm/src/dynamo/vllm/handlers.py:113-146`).

Streaming TTFT is preserved: the decode worker's stream opens immediately;
the first token arrives after remote-prefill + pull, which replaces the
(longer) local prefill the client would otherwise wait on.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import AsyncIterator, Dict, Optional

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.llm.block_manager.transfer import pull_prefix
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.rpc import RpcClient, RpcError

logger = logging.getLogger(__name__)

PREFILL_DONE_SUBJECT = "prefill_done"


def prefill_queue_name(namespace: str) -> str:
    return f"{namespace}/prefill_queue"


def disagg_config_key(namespace: str) -> str:
    return f"disagg/{namespace}/config"


@dataclass
class DisaggConfig:
    """`max_local_prefill_length`: prompts longer than this (in tokens)
    prefill remotely; None disables disagg (reference DisaggRouterConf)."""

    max_local_prefill_length: Optional[int] = None

    @staticmethod
    def from_dict(d: Optional[dict]) -> "DisaggConfig":
        if not d:
            return DisaggConfig()
        return DisaggConfig(
            max_local_prefill_length=d.get("max_local_prefill_length"))


class DisaggRouter:
    """Decode-side local/remote prefill decision, hot-reloaded from the
    control plane (the reference watches the etcd key,
    `disagg_router.rs:38-60`)."""

    def __init__(self, cp, namespace: str) -> None:
        self.cp = cp
        self.namespace = namespace
        self.config = DisaggConfig()
        self._watch = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        key = disagg_config_key(self.namespace)
        self.config = DisaggConfig.from_dict(await self.cp.get(key))
        self._watch = await self.cp.watch_prefix(key)
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._watch:
            self._watch.cancel()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        async for ev in self._watch:
            self.config = DisaggConfig.from_dict(
                ev.value if ev.kind == "put" else None)
            logger.info("disagg config now %s", self.config)

    def prefill_remotely(self, prompt_len: int) -> bool:
        limit = self.config.max_local_prefill_length
        return limit is not None and prompt_len > limit


async def prefill_worker_loop(cp, namespace: str, engine_client,
                              address: str, *,
                              visibility_timeout: float = 60.0) -> None:
    """The prefill worker's service loop (role=prefill).

    Pop → prefill (max_tokens=1, output discarded; the engine seals and
    registers every full prompt block) → announce → ack.  Ack comes LAST:
    a crash mid-prefill redelivers the job to a surviving prefill worker
    (at-least-once; re-prefilling an already-sealed prompt is a cheap
    prefix-cache hit)."""
    queue = prefill_queue_name(namespace)
    while True:
        # The whole iteration is guarded: an unhandled exception here
        # (control-plane hiccup during pop/publish/ack) would silently
        # kill the create_task'd loop and orphan the queue forever.
        try:
            msg_id, job = await cp.queue_pop(queue, visibility_timeout)
            rid = job["request_id"]
            t0 = time.monotonic()
            try:
                req = PreprocessedRequest(
                    request_id=f"prefill-{rid}",
                    model=job.get("model", ""),
                    token_ids=list(job["token_ids"]),
                    sampling=SamplingParams(max_tokens=1),
                )
                async for _ in engine_client.generate(req):
                    pass
            except Exception:
                logger.exception("prefill job %s failed (will redeliver)",
                                 rid)
                continue  # no ack: redelivery after visibility timeout
            await cp.publish(PREFILL_DONE_SUBJECT, {
                "request_id": rid,
                "address": address,
                "prefill_s": time.monotonic() - t0,
            })
            await cp.queue_ack(queue, msg_id)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("prefill loop: control-plane error; retrying")
            await asyncio.sleep(1.0)


class DisaggDecodeClient:
    """EngineClient for a decode-role worker: remote-prefill admission in
    front of the local engine."""

    def __init__(self, inner, engine, cp, namespace: str,
                 block_size: int, *,
                 prefill_timeout: float = 120.0,
                 transfer_plane=None, request_metrics=None) -> None:
        """`inner`: the local EngineClient; `engine`: the InferenceEngine
        (import_blocks side of the data plane); `transfer_plane`: the
        device-direct KvTransferPlane when this worker runs one — blocks
        then cross device-to-device, the host-staged pull remaining the
        fallback.  `request_metrics`: a runtime.metrics.RequestMetrics —
        KV-transfer time lands in its kv_transfer_seconds histogram."""
        self.inner = inner
        self.engine = engine
        self.cp = cp
        self.namespace = namespace
        self.block_size = block_size
        self.prefill_timeout = prefill_timeout
        self.transfer_plane = transfer_plane
        self.request_metrics = request_metrics
        self.device_pulls = 0
        self._waiters: Dict[str, asyncio.Future] = {}
        self._rpc_clients: Dict[str, RpcClient] = {}
        self._sub = None
        self._task: Optional[asyncio.Task] = None
        self.router = DisaggRouter(cp, namespace)
        # Observability: how disagg admission went (metrics + tests).
        self.remote_prefills = 0
        self.local_fallbacks = 0
        self.tokens_onboarded = 0

    async def start(self) -> None:
        await self.router.start()
        self._sub = await self.cp.subscribe(PREFILL_DONE_SUBJECT)
        self._task = asyncio.create_task(self._done_loop())

    async def stop(self) -> None:
        await self.router.stop()
        if self._sub:
            self._sub.cancel()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        for c in self._rpc_clients.values():
            await c.close()

    async def _done_loop(self) -> None:
        async for msg in self._sub:
            fut = self._waiters.pop(msg.get("request_id", ""), None)
            if fut and not fut.done():
                fut.set_result(msg)

    def _rpc(self, address: str) -> RpcClient:
        client = self._rpc_clients.get(address)
        if client is None:
            client = self._rpc_clients[address] = RpcClient(address)
        return client

    async def _remote_prefill(self, request: PreprocessedRequest) -> None:
        rid = request.request_id
        fut = asyncio.get_running_loop().create_future()
        self._waiters[rid] = fut
        try:
            # `with` makes the span current for the whole admission:
            # kv.pull_prefix / device-pull spans and their RPC children
            # nest under this one.
            with tracing.get_tracer().start_span(
                    "disagg.remote_prefill",
                    attrs={"request_id": rid,
                           "prompt_tokens": len(request.token_ids)}) as span:
                await self._remote_prefill_traced(request, rid, fut, span)
        finally:
            self._waiters.pop(rid, None)

    async def _remote_prefill_traced(self, request, rid, fut, span) -> None:
        try:
            await self.cp.queue_push(prefill_queue_name(self.namespace), {
                "request_id": rid,
                "model": request.model,
                "token_ids": list(request.token_ids),
            })
            done = await asyncio.wait_for(fut, self.prefill_timeout)
            span.set_attr(prefill_s=round(done.get("prefill_s", 0.0), 4),
                          prefill_worker=done.get("address"))
            t_pull = time.monotonic()
            onboarded = 0
            path = "host-staged"
            if self.transfer_plane is not None:
                # Device-direct first (NIXL-analog pull, no host hop);
                # any failure falls through to the host-staged plane.
                from dynamo_tpu.llm.block_manager.device_transfer import (
                    pull_prefix_device)

                try:
                    onboarded = await pull_prefix_device(
                        self.engine, self.transfer_plane,
                        self._rpc(done["address"]),
                        list(request.token_ids), self.block_size)
                except (ConnectionError, OSError, RpcError,
                        RuntimeError) as e:
                    logger.warning("device-direct pull %s failed (%s); "
                                   "using host-staged plane", rid, e)
                if onboarded:
                    self.device_pulls += 1
                    path = "device-direct"
            sealed = (len(request.token_ids) // self.block_size
                      * self.block_size)
            if onboarded < sealed:
                # Host-staged plane covers what the device pull didn't:
                # blocks offloaded to G2/G3 live host-side anyway (and a
                # failed device pull covers nothing).  import skips the
                # already-onboarded prefix.
                onboarded = await pull_prefix(
                    self.engine, self._rpc(done["address"]),
                    list(request.token_ids), self.block_size,
                    covered_tokens=onboarded)
            self.remote_prefills += 1
            self.tokens_onboarded += onboarded
            transfer_s = time.monotonic() - t_pull
            if self.request_metrics is not None:
                self.request_metrics.kv_transfer.observe(
                    transfer_s, labels={"path": path})
            span.set_attr(tokens_onboarded=onboarded, path=path,
                          kv_transfer_s=round(transfer_s, 4))
            logger.info("remote prefill %s: %d tokens onboarded from %s "
                        "(%s)", rid, onboarded, done["address"], path)
        except (asyncio.TimeoutError, ConnectionError, OSError,
                RpcError) as e:
            # RpcError: the peer's kv_blocks handler failed (e.g. blocks
            # evicted between announce and pull) — disagg is an
            # optimisation, never a correctness dependency.
            self.local_fallbacks += 1
            span.set_attr(fallback="local", error=type(e).__name__)
            logger.warning("remote prefill %s failed (%s); prefilling "
                           "locally", rid, e)

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        if self.router.prefill_remotely(len(request.token_ids)):
            await self._remote_prefill(request)
        async for delta in self.inner.generate(request):
            yield delta
