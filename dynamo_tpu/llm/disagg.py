"""Disaggregated prefill/decode orchestration.

The reference's headline feature (`docs/architecture/disagg_serving.md:
12-64`): long prefills run on dedicated prefill workers so decode batches
never stall behind them; KV crosses workers on the data plane.  The
components, mapped onto our runtime:

- **DisaggRouter** (decode-side admission) — the conditional local/remote
  decision with a control-plane-watched threshold, the analog of
  `lib/llm/src/disagg_router.rs:25-50` (`DisaggRouterConf
  {max_local_prefill_length}` read + hot-reloaded from etcd).
- **Prefill queue** — an acked work queue on the control plane (the
  reference's NATS JetStream `NatsQueue`, `transports/nats.rs:360`):
  at-least-once, so a prefill worker dying mid-job redelivers rather than
  losing the request.
- **prefill_worker_loop** — pops jobs, runs the prompt through the local
  engine (one token, discarded), which seals + registers the prompt's KV
  blocks.  While the prompt prefills, it watches the engine's
  seal-progress stream (`InferenceEngine.watch_seals`) and publishes
  incremental announcements — rid, its RPC address, the sealed-hash
  high-water mark — then announces completion.
- **DisaggDecodeClient** — decode-side EngineClient wrapper: long prompts
  are enqueued for remote prefill, and an **EagerPuller**
  (block_manager/eager.py) streams sealed blocks over the kv_blocks data
  plane WHILE remote prefill runs, so at the done message only the
  residual tail is pulled — disagg TTFT ≈ max(prefill, transfer) + tail
  instead of prefill + full_transfer (the reference overlaps its NIXL
  transfer with prefill compute the same way, layer-wise;
  `disagg_serving.md:70-99`).  Then the local engine runs — its
  prefix-cache match skips everything but the last partial block.
  Remote failure (timeout, dead prefill worker — including MID-STREAM)
  falls back to local prefill seeded with whatever contiguous prefix
  already landed: disagg is an optimisation, never a correctness
  dependency (the reference decode handler behaves the same,
  `components/backends/vllm/src/dynamo/vllm/handlers.py:113-146`).

Streaming TTFT is preserved: the decode worker's stream opens immediately;
the first token arrives after remote-prefill + residual pull, which
replaces the (longer) local prefill the client would otherwise wait on.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import AsyncIterator, Dict, Optional

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.llm.block_manager.transfer import pull_prefix
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.rpc import RpcClient, RpcError

logger = logging.getLogger(__name__)

PREFILL_DONE_SUBJECT = "prefill_done"
PREFILL_PROGRESS_SUBJECT = "prefill_progress"


class _KvModeRefused(Exception):
    """A pull's inject refused the peer's blocks (kv-quant-mode
    mismatch, engine `_validate_block`).  Raised ONLY from the pull
    call sites — a bare ValueError elsewhere in remote-prefill is a
    real bug and must propagate, not read as config skew."""


def prefill_queue_name(namespace: str) -> str:
    return f"{namespace}/prefill_queue"


def disagg_config_key(namespace: str) -> str:
    return f"disagg/{namespace}/config"


@dataclass
class DisaggConfig:
    """`max_local_prefill_length`: prompts longer than this (in tokens)
    prefill remotely; None disables disagg (reference DisaggRouterConf)."""

    max_local_prefill_length: Optional[int] = None

    @staticmethod
    def from_dict(d: Optional[dict]) -> "DisaggConfig":
        if not d:
            return DisaggConfig()
        return DisaggConfig(
            max_local_prefill_length=d.get("max_local_prefill_length"))


class DisaggRouter:
    """Decode-side local/remote prefill decision, hot-reloaded from the
    control plane (the reference watches the etcd key,
    `disagg_router.rs:38-60`)."""

    def __init__(self, cp, namespace: str) -> None:
        self.cp = cp
        self.namespace = namespace
        self.config = DisaggConfig()
        self._watch = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        key = disagg_config_key(self.namespace)
        self.config = DisaggConfig.from_dict(await self.cp.get(key))
        self._watch = await self.cp.watch_prefix(key)
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._watch:
            self._watch.cancel()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        async for ev in self._watch:
            self.config = DisaggConfig.from_dict(
                ev.value if ev.kind == "put" else None)
            logger.info("disagg config now %s", self.config)

    def prefill_remotely(self, prompt_len: int) -> bool:
        limit = self.config.max_local_prefill_length
        return limit is not None and prompt_len > limit


async def _publish_progress(cp, rid: str, address: str,
                            seal_q: "asyncio.Queue") -> None:
    """Forward a prefilling prompt's seal high-water marks to the control
    plane as incremental progress announcements.  Bursts coalesce (only
    the latest mark publishes); cancellation is silent — prefill
    finished, and the done message is the final word."""
    hwm = 0
    while True:
        sealed = await seal_q.get()
        while not seal_q.empty():
            sealed = max(sealed, seal_q.get_nowait())
        if sealed <= hwm:
            continue
        hwm = sealed
        await cp.publish(PREFILL_PROGRESS_SUBJECT, {
            "request_id": rid,
            "address": address,
            "sealed_blocks": hwm,
        })


async def prefill_worker_loop(cp, namespace: str, engine_client,
                              address: str, *,
                              visibility_timeout: float = 60.0) -> None:
    """The prefill worker's service loop (role=prefill).

    Pop → prefill (max_tokens=1, output discarded; the engine seals and
    registers every full prompt block) → announce → ack.  Ack comes LAST:
    a crash mid-prefill redelivers the job to a surviving prefill worker
    (at-least-once; re-prefilling an already-sealed prompt is a cheap
    prefix-cache hit).

    Eager KV streaming: while the prompt prefills, the engine's
    seal-progress stream feeds incremental PREFILL_PROGRESS announcements
    (rid → sealed-hash high-water mark + this worker's RPC address) so
    decode-side EagerPullers start pulling sealed blocks before the done
    message.  Engines without a seal stream (no `watch_seals`) simply
    skip the announcements — the done message alone reproduces the
    serial protocol."""
    queue = prefill_queue_name(namespace)
    # The seal stream lives on the InferenceEngine behind the client
    # (LocalEngineClient wraps it as `_engine`); duck-typed so wrapped or
    # bare engines both work and anything else degrades to done-only.
    seal_engine = getattr(engine_client, "_engine", engine_client)
    if not hasattr(seal_engine, "watch_seals"):
        seal_engine = None
    while True:
        # The whole iteration is guarded: an unhandled exception here
        # (control-plane hiccup during pop/publish/ack) would silently
        # kill the create_task'd loop and orphan the queue forever.
        try:
            msg_id, job = await cp.queue_pop(queue, visibility_timeout)
            rid = job["request_id"]
            prid = f"prefill-{rid}"
            t0 = time.monotonic()
            progress: Optional[asyncio.Task] = None
            if seal_engine is not None:
                progress = asyncio.create_task(_publish_progress(
                    cp, rid, address, seal_engine.watch_seals(prid)))
            try:
                req = PreprocessedRequest(
                    request_id=prid,
                    model=job.get("model", ""),
                    token_ids=list(job["token_ids"]),
                    sampling=SamplingParams(max_tokens=1),
                )
                async for _ in engine_client.generate(req):
                    pass
            except Exception:
                logger.exception("prefill job %s failed (will redeliver)",
                                 rid)
                continue  # no ack: redelivery after visibility timeout
            finally:
                if seal_engine is not None:
                    seal_engine.unwatch_seals(prid)
                if progress is not None:
                    progress.cancel()
                    # gather(return_exceptions=True) absorbs the child's
                    # CancelledError / errors but still propagates OUR
                    # OWN cancellation — a bare `await progress` here
                    # could swallow the loop's shutdown cancel.
                    await asyncio.gather(progress, return_exceptions=True)
            await cp.publish(PREFILL_DONE_SUBJECT, {
                "request_id": rid,
                "address": address,
                "prefill_s": time.monotonic() - t0,
            })
            await cp.queue_ack(queue, msg_id)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("prefill loop: control-plane error; retrying")
            await asyncio.sleep(1.0)


class DisaggDecodeClient:
    """EngineClient for a decode-role worker: remote-prefill admission in
    front of the local engine."""

    def __init__(self, inner, engine, cp, namespace: str,
                 block_size: int, *,
                 prefill_timeout: float = 120.0,
                 transfer_plane=None, request_metrics=None,
                 eager: bool = True, eager_inflight: int = 2,
                 eager_batch_blocks: int = 8) -> None:
        """`inner`: the local EngineClient; `engine`: the InferenceEngine
        (import_blocks side of the data plane); `transfer_plane`: the
        device-direct KvTransferPlane when this worker runs one — blocks
        then cross device-to-device, the host-staged pull remaining the
        fallback.  `request_metrics`: a runtime.metrics.RequestMetrics —
        KV-transfer time lands in its kv_transfer_seconds histogram and
        the eager-streaming overlap in kv_transfer_overlap.

        `eager`: stream sealed blocks WHILE remote prefill runs
        (EagerPuller per pending rid, driven by the PREFILL_PROGRESS
        subscription).  With a transfer_plane the stream rides the
        DEVICE plane — each batch is an offer → device pull → ack round,
        overlapped with prefill exactly like the host stream — and the
        host-staged wire remains the per-request fallback.  Without
        eager, a transfer_plane still pulls the whole prefix
        device-direct at prefill-done (the pre-streaming protocol)."""
        self.inner = inner
        self.engine = engine
        self.cp = cp
        self.namespace = namespace
        self.block_size = block_size
        self.prefill_timeout = prefill_timeout
        self.transfer_plane = transfer_plane
        self.request_metrics = request_metrics
        self.eager = eager
        self.eager_inflight = eager_inflight
        self.eager_batch_blocks = eager_batch_blocks
        self.device_pulls = 0
        self._waiters: Dict[str, asyncio.Future] = {}
        self._pullers: Dict[str, object] = {}   # rid → EagerPuller
        self._rpc_clients: Dict[str, RpcClient] = {}
        self._sub = None
        self._progress_sub = None
        self._task: Optional[asyncio.Task] = None
        self._progress_task: Optional[asyncio.Task] = None
        self.router = DisaggRouter(cp, namespace)
        # Observability: how disagg admission went (metrics + tests).
        self.remote_prefills = 0
        self.local_fallbacks = 0
        self.tokens_onboarded = 0
        self.tokens_streamed = 0        # pulled BEFORE prefill-done
        self.last_overlap_ratio = 0.0

    async def start(self) -> None:
        await self.router.start()
        self._sub = await self.cp.subscribe(PREFILL_DONE_SUBJECT)
        self._task = asyncio.create_task(self._done_loop())
        self._progress_sub = await self.cp.subscribe(
            PREFILL_PROGRESS_SUBJECT)
        self._progress_task = asyncio.create_task(self._progress_loop())

    async def stop(self) -> None:
        await self.router.stop()
        for sub in (self._sub, self._progress_sub):
            if sub:
                sub.cancel()
        for task in (self._task, self._progress_task):
            if task:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        for c in self._rpc_clients.values():
            await c.close()

    async def _done_loop(self) -> None:
        async for msg in self._sub:
            fut = self._waiters.pop(msg.get("request_id", ""), None)
            if fut and not fut.done():
                fut.set_result(msg)

    async def _progress_loop(self) -> None:
        """Route incremental prefill announcements to the pending rid's
        EagerPuller — unknown rids (another decode worker's request, or
        one that already completed) cost a dict miss."""
        try:
            async for msg in self._progress_sub:
                try:
                    puller = self._pullers.get(msg.get("request_id", ""))
                    if puller is not None:
                        puller.on_progress(msg.get("sealed_blocks", 0),
                                           msg.get("address", ""))
                except Exception:
                    # One malformed announcement (version-skewed peer)
                    # must not kill streaming for every future request.
                    logger.exception("bad prefill-progress message: %r",
                                     msg)
        except ConnectionError:
            # Control plane gone (shutdown / restart): progress simply
            # stops flowing; pending pulls degrade to done-only, and the
            # done waiter times out into local fallback on its own.
            logger.warning("prefill-progress subscription lost")

    def _rpc(self, address: str) -> RpcClient:
        client = self._rpc_clients.get(address)
        if client is None:
            client = self._rpc_clients[address] = RpcClient(address)
        return client

    async def _remote_prefill(self, request: PreprocessedRequest) -> None:
        rid = request.request_id
        fut = asyncio.get_running_loop().create_future()
        self._waiters[rid] = fut
        try:
            # `with` makes the span current for the whole admission:
            # kv.pull_prefix / device-pull spans and their RPC children
            # nest under this one.
            with tracing.get_tracer().start_span(
                    "disagg.remote_prefill",
                    attrs={"request_id": rid,
                           "prompt_tokens": len(request.token_ids)}) as span:
                await self._remote_prefill_traced(request, rid, fut, span)
        finally:
            self._waiters.pop(rid, None)

    async def _remote_prefill_traced(self, request, rid, fut, span) -> None:
        from dynamo_tpu.llm.block_manager.device_transfer import note_plane
        from dynamo_tpu.runtime.ledger import ledger_of

        led = ledger_of(request)
        t_wait = time.monotonic()
        puller = None
        if self.eager:
            from dynamo_tpu.llm.block_manager.eager import EagerPuller

            # Registered BEFORE the queue push: a fast prefill worker's
            # first progress announcement must find its puller.  The
            # stream rides the device plane when this worker runs one
            # (ISSUE 13 tentpole: eager × device compose).
            puller = EagerPuller(
                self.engine, self._rpc, list(request.token_ids),
                self.block_size, max_inflight=self.eager_inflight,
                batch_blocks=self.eager_batch_blocks,
                plane=self.transfer_plane)
            self._pullers[rid] = puller
        settled = False   # success OR handled fallback reached abort()
        try:
            await self.cp.queue_push(prefill_queue_name(self.namespace), {
                "request_id": rid,
                "model": request.model,
                "token_ids": list(request.token_ids),
            })
            done = await asyncio.wait_for(fut, self.prefill_timeout)
            span.set_attr(prefill_s=round(done.get("prefill_s", 0.0), 4),
                          prefill_worker=done.get("address"))
            t_pull = time.monotonic()
            bytes0 = (self.transfer_plane.pulled_bytes
                      if self.transfer_plane is not None else 0)
            if led is not None:
                # Decode-side wait for the remote prefill worker: queue
                # push → done announcement (the eager stream overlaps it).
                led.stamp("prefill_remote", dur=t_pull - t_wait,
                          prefill_s=round(done.get("prefill_s", 0.0), 4),
                          worker=str(done.get("address", "")))
            onboarded = 0
            path = "host-staged"
            if puller is not None:
                # Eager path: whatever streamed during prefill is already
                # injected; finish() drains in-flight pulls and fetches
                # only the residual tail.
                streamed = puller.streamed_blocks * self.block_size
                try:
                    onboarded = await puller.finish(done["address"])
                except ValueError as e:
                    raise _KvModeRefused(e) from e
                if puller.device_blocks:
                    self.device_pulls += 1
                    path = "device-stream"
                elif streamed:
                    path = "eager-stream"
                overlap = puller.overlap_ratio
                self.tokens_streamed += streamed
                self.last_overlap_ratio = overlap
                if self.request_metrics is not None:
                    self.request_metrics.kv_transfer_overlap.observe(
                        overlap)
                span.set_attr(overlap_ratio=round(overlap, 4),
                              tokens_streamed=streamed)
            else:
                if self.transfer_plane is not None:
                    # Device-direct first (NIXL-analog pull, no host
                    # hop); any transport failure falls through to the
                    # host-staged plane.  A kv-quant ValueError
                    # propagates to the local-prefill fallback below —
                    # the host wire would refuse identically.
                    from dynamo_tpu.llm.block_manager.device_transfer import (
                        pull_prefix_device)

                    try:
                        onboarded = await pull_prefix_device(
                            self.engine, self.transfer_plane,
                            self._rpc(done["address"]),
                            list(request.token_ids), self.block_size)
                    except ValueError as e:
                        raise _KvModeRefused(e) from e
                    except (ConnectionError, OSError, RpcError,
                            RuntimeError) as e:
                        note_plane("host", "pull_failed")
                        logger.warning("device-direct pull %s failed (%s); "
                                       "using host-staged plane", rid, e)
                    if onboarded:
                        self.device_pulls += 1
                        path = "device-direct"
                sealed = (len(request.token_ids) // self.block_size
                          * self.block_size)
                if onboarded < sealed:
                    # Host-staged plane covers what the device pull
                    # didn't: blocks offloaded to G2/G3 live host-side
                    # anyway (and a failed device pull covers nothing).
                    # import skips the already-onboarded prefix.
                    before = onboarded
                    try:
                        onboarded = await pull_prefix(
                            self.engine, self._rpc(done["address"]),
                            list(request.token_ids), self.block_size,
                            covered_tokens=onboarded)
                    except ValueError as e:
                        raise _KvModeRefused(e) from e
                    if onboarded > before:
                        # Count the host traffic with its cause, so the
                        # PLANE split reflects where bytes actually
                        # moved (device refusals inside
                        # pull_prefix_device record their own reason).
                        note_plane(
                            "host",
                            "no_plane" if self.transfer_plane is None
                            else "residual")
            self.remote_prefills += 1
            self.tokens_onboarded += onboarded
            settled = True
            transfer_s = time.monotonic() - t_pull
            if self.request_metrics is not None:
                self.request_metrics.kv_transfer.observe(
                    transfer_s, labels={"path": path})
            span.set_attr(tokens_onboarded=onboarded, path=path,
                          kv_transfer_s=round(transfer_s, 4))
            if led is not None:
                dev_bytes = (self.transfer_plane.pulled_bytes - bytes0
                             if self.transfer_plane is not None else 0)
                led.stamp(
                    "kv_transfer", dur=transfer_s, reason="disagg",
                    plane=("device" if path.startswith("device")
                           else "host"),
                    path=path, blocks=onboarded // self.block_size,
                    tokens=onboarded, device_bytes=dev_bytes)
            logger.info("remote prefill %s: %d tokens onboarded from %s "
                        "(%s)", rid, onboarded, done["address"], path)
        except (asyncio.TimeoutError, ConnectionError, OSError,
                RpcError, _KvModeRefused) as e:
            # RpcError: the peer's kv_blocks handler failed (e.g. blocks
            # evicted between announce and pull) — disagg is an
            # optimisation, never a correctness dependency.
            # _KvModeRefused: the peer's blocks are un-injectable here
            # (kv-quant-mode mismatch) — retrying over the host wire
            # would refuse identically, so the request prefills locally.
            # A mid-stream death keeps the landed contiguous prefix: the
            # local prefill below prefix-matches it and recomputes only
            # the rest.
            self.local_fallbacks += 1
            if isinstance(e, _KvModeRefused):
                note_plane("host", "quant_mismatch")
            landed = 0
            if puller is not None:
                landed = await puller.abort()
                self.tokens_onboarded += landed
            settled = True
            span.set_attr(fallback="local", error=type(e).__name__,
                          landed_tokens=landed)
            if led is not None:
                led.stamp("prefill_remote", dur=time.monotonic() - t_wait,
                          fallback="local", error=type(e).__name__,
                          landed_tokens=landed)
            logger.warning(
                "remote prefill %s failed (%s); prefilling locally"
                "%s", rid, e,
                f" (reusing {landed} landed tokens)" if landed else "")
        finally:
            self._pullers.pop(rid, None)
            if puller is not None and not settled:
                # Unwinding through an unhandled path (cancellation,
                # unexpected error): the in-flight pull tasks must not
                # outlive their owner.
                try:
                    await puller.abort()
                except Exception:
                    logger.exception("eager puller cleanup failed (%s)",
                                     rid)

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        if self.router.prefill_remotely(len(request.token_ids)):
            await self._remote_prefill(request)
        async for delta in self.inner.generate(request):
            yield delta
