"""Dynamic model discovery: register_llm + ModelWatcher + remote clients.

The reference flow (`discovery/watcher.rs:39`, `rust/lib.rs:136
register_llm`): a worker serves its engine endpoint, then writes a
ModelEntry under `models/` in etcd; every frontend watches that prefix and
builds/tears down routed pipelines as entries come and go.  Same here,
over our control plane.

Wire protocol engine-side (`PreprocessedRequest` ↔ dict, `TokenDelta` ↔
dict) lives in this module so worker and frontend agree by construction.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Callable, Dict, Optional

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import FinishReason
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor, PreprocessedRequest
from dynamo_tpu.llm.service import ModelHandle, ModelManager
from dynamo_tpu.runtime.distributed import (
    MODEL_ROOT,
    Client,
    DistributedRuntime,
    Endpoint,
    Instance,
)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Wire codecs


def request_to_wire(req: PreprocessedRequest) -> dict:
    s = req.sampling
    d = {
        "request_id": req.request_id,
        "model": req.model,
        "token_ids": list(req.token_ids),
        "sampling": {
            "temperature": s.temperature, "top_k": s.top_k, "top_p": s.top_p,
            "max_tokens": s.max_tokens,
            "stop_token_ids": list(s.stop_token_ids), "seed": s.seed,
            "logprobs": s.logprobs,
            "seed_offset": s.seed_offset,
        },
        "stop_sequences": list(req.stop_sequences),
        "annotations": dict(req.annotations),
    }
    if req.prompt_embeds is not None:
        # Multimodal embeddings ride the request frame as raw f32 bytes
        # (msgpack bin) — the frontend→worker leg of the reference's
        # encode→prefill embedding transfer (multimodal_v1/components).
        import numpy as np

        emb = np.ascontiguousarray(np.asarray(req.prompt_embeds,
                                              dtype=np.float32))
        d["prompt_embeds"] = emb.tobytes()
        d["prompt_embeds_shape"] = list(emb.shape)
    return d


def request_from_wire(d: dict) -> PreprocessedRequest:
    s = d.get("sampling", {})
    embeds = None
    if d.get("prompt_embeds") is not None:
        import numpy as np

        embeds = np.frombuffer(d["prompt_embeds"], dtype=np.float32) \
            .reshape(d["prompt_embeds_shape"]).copy()
    return PreprocessedRequest(
        request_id=d["request_id"], model=d.get("model", ""),
        token_ids=list(d["token_ids"]),
        sampling=SamplingParams(
            temperature=s.get("temperature", 0.0),
            top_k=s.get("top_k", 0), top_p=s.get("top_p", 1.0),
            max_tokens=s.get("max_tokens", 16),
            stop_token_ids=tuple(s.get("stop_token_ids", ())),
            seed=s.get("seed"),
            logprobs=bool(s.get("logprobs", False)),
            seed_offset=int(s.get("seed_offset", 0))),
        stop_sequences=list(d.get("stop_sequences", [])),
        annotations=dict(d.get("annotations", {})),
        prompt_embeds=embeds,
    )


def delta_to_wire(delta: TokenDelta) -> dict:
    d = {
        "token_ids": list(delta.token_ids),
        "finished": delta.finished,
        "finish_reason": delta.finish_reason.value if delta.finish_reason else None,
    }
    if delta.logprobs is not None:
        d["logprobs"] = list(delta.logprobs)
    if delta.migrate is not None:
        # Drain handoff marker (llm/drain.py): old frontends simply
        # never see it set; old workers never set it.
        d["migrate"] = dict(delta.migrate)
    if getattr(delta, "ledger", None) is not None:
        # Request-ledger return leg (runtime/ledger.py): the worker
        # hop's phase stamps ride the final/migrate delta.  Same
        # old-peer contract as `migrate`; garbage on the receiving side
        # is dropped, never the request.
        d["ledger"] = delta.ledger
    return d


def delta_from_wire(d: dict) -> TokenDelta:
    fr = d.get("finish_reason")
    lp = d.get("logprobs")
    mig = d.get("migrate")
    return TokenDelta(
        request_id="", token_ids=list(d.get("token_ids", [])),
        finished=bool(d.get("finished")),
        finish_reason=FinishReason(fr) if fr else None,
        logprobs=list(lp) if lp is not None else None,
        migrate=dict(mig) if mig is not None else None,
        # Carried raw: runtime/ledger.decode_wire validates (and warns,
        # rate-limited) at the merge point so a malformed payload drops
        # the LEDGER, never the delta.
        ledger=d.get("ledger"))


EMBED_ENDPOINT = "embed"
CLEAR_KV_ENDPOINT = "clear_kv"


def engine_wire_handler(engine_client, request_metrics=None) -> Callable:
    """Wrap any EngineClient as an RPC handler (worker side).

    `request_metrics` (runtime/metrics.RequestMetrics): when provided,
    the handler observes worker-side TTFT / TPOT histograms and terminal
    outcomes — the worker's own SLO-objective sources, measured at the
    RPC boundary (excludes frontend queueing, includes engine admission
    wait).  A few monotonic reads per delta on the event loop; nothing
    touches the engine thread."""

    async def handler(payload: dict) -> AsyncIterator[dict]:
        import time as _time

        from dynamo_tpu.runtime import ledger as ledger_mod
        from dynamo_tpu.runtime import tracing

        req = request_from_wire(payload)
        # Per-hop request ledger (runtime/ledger.py): created when this
        # worker has the plane enabled AND the request opted in via its
        # annotation marker.  Inner serving stages (disagg, prefix-share,
        # LocalEngineClient) stamp it; the completed hop rides back on
        # the final — or migrate — delta's `ledger` key.
        hop_ledger = ledger_mod.begin_hop(req)
        # Trace context: the frontend's request id arrives in the RPC
        # frame; logging it here gives one grep-able id across frontend
        # and worker logs (reference `logging.rs:73-79`).  The RPC server
        # span (runtime/rpc.py) is this task's current span; binding it
        # to the request id lets the ENGINE THREAD parent its
        # admission→first-token spans under this hop.
        logger.info("request %s: %d prompt tokens, max_tokens=%d",
                    req.request_id, len(req.token_ids),
                    req.sampling.max_tokens)
        tracer = tracing.get_tracer()
        span = tracing.current_span()
        if span is not None:
            tracer.bind(req.request_id, span.ctx)
        n_out = 0
        start = _time.monotonic()
        last_t = None
        finished_ok = None
        observe = True
        try:
            async for delta in engine_client.generate(req):
                if getattr(delta, "migrate", None) is not None:
                    # Drain handoff: the PEER serves (and observes) the
                    # remainder of this stream — one request, one
                    # outcome.
                    observe = False
                if request_metrics is not None and delta.token_ids:
                    now = _time.monotonic()
                    if last_t is None:
                        request_metrics.ttft.observe(now - start)
                    else:
                        request_metrics.tpot.observe(now - last_t)
                    last_t = now
                if delta.finished:
                    finished_ok = delta.finish_reason is not FinishReason.ERROR
                n_out += len(delta.token_ids)
                if hop_ledger is not None and (
                        delta.finished
                        or getattr(delta, "migrate", None) is not None):
                    # Hop ledger return leg: the stream's last delta out
                    # of this worker carries every stamp the hop made —
                    # a drain migrate delta too, so hop-1 stamps survive
                    # the handoff to the resuming peer.
                    delta.ledger = hop_ledger.to_wire()
                yield delta_to_wire(delta)
        except (GeneratorExit, asyncio.CancelledError):
            raise  # client disconnect / teardown: not an engine failure
        except Exception as e:
            from dynamo_tpu.llm.drain import DRAIN_REFUSAL

            if DRAIN_REFUSAL in str(e):
                # Draining worker refusing an admission: the retryable
                # marker re-routes the request to a peer, which serves
                # and OBSERVES it — counting an outcome here would
                # double-count the request (and burn error budget on a
                # request that succeeds).
                observe = False
            else:
                # A raising generate() (dead disagg peer, engine fault)
                # IS a served-request failure — it must burn error-rate
                # budget even though no ERROR delta was yielded.
                finished_ok = False
            raise
        finally:
            tracer.unbind(req.request_id)
            if request_metrics is not None and observe:
                # A stream torn down without a terminal delta (client
                # disconnect mid-generation) is not an engine failure.
                request_metrics.observe_outcome(
                    ok=finished_ok if finished_ok is not None else True)
        logger.info("request %s: finished, %d tokens", req.request_id, n_out)

    return handler


def clear_kv_wire_handler(engine_client) -> Callable:
    """Worker-side `clear_kv` admin endpoint."""

    async def handler(payload: dict) -> AsyncIterator[dict]:
        n = await engine_client.clear_kv_blocks()
        yield {"cleared": int(n)}

    return handler


def embed_wire_handler(engine_client) -> Callable:
    """Worker-side `embed` RPC endpoint: one delta per input row."""

    async def handler(payload: dict) -> AsyncIterator[dict]:
        vecs = await engine_client.embed(payload["token_lists"])
        for i, vec in enumerate(vecs):
            yield {"index": i, "embedding": [float(x) for x in vec]}

    return handler


class RemoteEngineClient:
    """EngineClient over a runtime Client (frontend side)."""

    def __init__(self, client: Client) -> None:
        self.client = client

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        from dynamo_tpu.runtime import ledger as ledger_mod

        async for d in self.client.generate(request_to_wire(request)):
            delta = delta_from_wire(d)
            delta.request_id = request.request_id
            # Fold a returned worker-hop ledger (final/migrate delta)
            # into the frontend's live one; malformed payloads drop the
            # ledger with a rate-limited warn, never the delta.
            ledger_mod.absorb_delta(request, delta, where="remote_client")
            yield delta

    async def clear_kv_blocks(self) -> int:
        """Flush every live instance's reusable KV blocks — including the
        sibling prefill component's workers in a disaggregated deployment
        (their warm caches would otherwise survive the flush).  Errors
        are per-instance (a worker without the endpoint doesn't abort the
        fleet flush)."""
        from dynamo_tpu.runtime.rpc import RpcError

        runtime = self.client.endpoint.runtime
        ep = self.client.endpoint
        addresses = [inst.address for inst in self.client.instances()]
        prefill_prefix = (f"instances/{ep.namespace}/"
                          f"{ep.component}-prefill/")
        for entry in (await runtime.cp.get_prefix(prefill_prefix)).values():
            addr = entry.get("address")
            if addr:
                addresses.append(addr)
        total = 0
        unreachable = []
        for address in addresses:
            rpc = runtime.client_for(address)
            try:
                async for d in rpc.call(CLEAR_KV_ENDPOINT, {}):
                    total += int(d.get("cleared", 0))
            except RpcError:
                continue  # endpoint absent on this worker (e.g. mocker)
            except ConnectionError:
                await runtime.evict_client(address)
                unreachable.append(address)
        if unreachable:
            # A partial flush must be loud: the operator flushing before a
            # benchmark (or after a privacy incident) needs to know which
            # workers kept their warm caches.
            raise ConnectionError(
                f"flushed {total} blocks but {len(unreachable)} instances "
                f"were unreachable: {', '.join(unreachable)}")
        return total

    async def embed(self, token_lists):
        """Forward to a worker's `embed` RPC endpoint (round-robin over
        live instances)."""
        import numpy as np

        inst = self.client._pick()
        rpc = self.client.endpoint.runtime.client_for(inst.address)
        rows = {}
        try:
            async for d in rpc.call(
                    EMBED_ENDPOINT,
                    {"token_lists": [list(t) for t in token_lists]}):
                rows[d["index"]] = d["embedding"]
        except ConnectionError:
            # Mirror Client.generate's fault handling: evict the cached
            # client so the next attempt reconnects/re-picks.
            await self.client.endpoint.runtime.evict_client(inst.address)
            raise
        if len(rows) != len(token_lists):
            raise ConnectionError(
                f"embed stream ended early: {len(rows)}/{len(token_lists)} "
                "rows (worker died mid-request?)")
        return np.asarray([rows[i] for i in range(len(token_lists))],
                          dtype=np.float32)


# ---------------------------------------------------------------------------
# Registration (worker side)


def model_key(name: str, instance_id: int) -> str:
    return f"{MODEL_ROOT}/{name}/{instance_id}"


async def register_llm(
    endpoint: Endpoint,
    instance: Instance,
    card: ModelDeploymentCard,
) -> None:
    """Publish the model entry bound to this instance's lease: when the
    worker dies, the entry dies with it (reference ModelEntry under
    MODEL_ROOT_PATH + lease semantics).  The instance record's published
    SliceSpec (ISSUE 16, `fleet.topology`) rides along so frontends see
    a worker's mesh/role/HBM without a second lookup."""
    entry = {
        "card": card.to_dict(),
        "namespace": endpoint.namespace,
        "component": endpoint.component,
        "endpoint": endpoint.name,
        "instance_id": instance.instance_id,
    }
    slice_spec = (instance.metadata or {}).get("slice")
    if slice_spec is not None:
        entry["slice"] = slice_spec

    async def _put():
        # Bound to the endpoint's CURRENT lease so a control-plane
        # restart replays the model entry too (Endpoint re-registration).
        await endpoint.runtime.cp.put(
            model_key(card.name, instance.instance_id), entry,
            lease=endpoint._lease)

    await _put()
    endpoint.add_registration_put(_put)


# ---------------------------------------------------------------------------
# ModelWatcher (frontend side)


class ModelWatcher:
    """Watches `models/`; maintains the frontend's ModelManager."""

    def __init__(self, runtime: DistributedRuntime,
                 manager: ModelManager,
                 router_mode: str = "round_robin",
                 migration_limit: int = 3,
                 registry=None) -> None:
        self.runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        self.migration_limit = migration_limit
        # Frontend MetricsRegistry: router-side series (e.g. the
        # remote-prefix route counter) land on the frontend's /metrics.
        self.registry = registry
        self._instances: Dict[str, set] = {}       # model → instance ids
        self._clients: Dict[str, Client] = {}
        self._kv_clients: Dict[str, object] = {}   # model → KvRoutedEngineClient
        self._task: Optional[asyncio.Task] = None
        self._watch = None

    async def start(self) -> None:
        self._watch = await self.runtime.cp.watch_prefix(f"{MODEL_ROOT}/")
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._watch:
            self._watch.cancel()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        for c in self._clients.values():
            await c.stop()
        for kv in self._kv_clients.values():
            await kv.stop()

    async def wait_for_model(self, name: str, timeout: float = 10.0) -> None:
        async def poll():
            while self.manager.get(name) is None:
                await asyncio.sleep(0.01)
        await asyncio.wait_for(poll(), timeout)

    async def _loop(self) -> None:
        while True:
            try:
                async for ev in self._watch:
                    try:
                        if ev.kind == "put" and ev.value:
                            await self._on_put(ev.key, ev.value)
                        elif ev.kind == "delete":
                            await self._on_delete(ev.key)
                    except Exception:
                        logger.exception("model watcher event failed: %s",
                                         ev.key)
                return
            except ConnectionError:
                # One poison per control-plane outage; the client's
                # reconnect re-registers the watch and replays state
                # into the same queue — resume consuming (stop()
                # cancels this task at shutdown).  Unhandled, this was
                # "Task exception was never retrieved" teardown noise.
                logger.debug("model watcher: control plane connection "
                             "lost; resuming on replay")
                continue

    async def _on_put(self, key: str, entry: dict) -> None:
        card = ModelDeploymentCard.from_dict(entry["card"])
        name = card.name
        ids = self._instances.setdefault(name, set())
        ids.add(entry["instance_id"])
        if self.manager.get(name) is not None:
            return  # additional replica of a known model
        endpoint = (self.runtime.namespace(entry["namespace"])
                    .component(entry["component"])
                    .endpoint(entry["endpoint"]))
        client = await endpoint.client(
            "round_robin" if self.router_mode == "kv" else self.router_mode)
        self._clients[name] = client
        tokenizer = card.build_tokenizer()
        # Declarative operator pipeline (runtime/pipeline.py; reference
        # build_routed_pipeline, `entrypoint/input/common.rs:213`):
        # Migration (retry across worker death) wraps the router
        # (KV-aware or plain round-robin), which wraps the instance set.
        from dynamo_tpu.runtime.pipeline import (
            KvRouterOp, MigrationOp, Pipeline, RemoteOp)

        router_op = (KvRouterOp(self.runtime,
                                block_size=card.kv_block_size,
                                registry=self.registry)
                     if self.router_mode == "kv" else RemoteOp())
        pipeline = Pipeline([
            MigrationOp(limit=self.migration_limit, registry=self.registry),
            router_op,
        ])
        engine_client = await pipeline.attach(client)
        if self.router_mode == "kv":
            from dynamo_tpu.llm.kv_router.client import KvRoutedEngineClient

            self._kv_clients[name] = pipeline.stage_of(KvRoutedEngineClient)
        # Multimodal: every dynamic model gets the attach hook pointed at
        # the namespace's encoder endpoint (`encoder/encode`); requests
        # without image parts never touch it, and requests with them get
        # a clear 502 when no encode worker is live.
        from dynamo_tpu.llm.multimodal import MultimodalAttach

        mm = MultimodalAttach(
            endpoint=(self.runtime.namespace(entry["namespace"])
                      .component("encoder").endpoint("encode")))
        self.manager.register(ModelHandle(
            name=name, tokenizer=tokenizer,
            preprocessor=OpenAIPreprocessor(
                tokenizer, chat_template=card.chat_template,
                default_max_tokens=card.default_max_tokens),
            client=engine_client,
            max_context=card.max_context,
            multimodal=mm))
        logger.info("model %r registered (instance %d)", name,
                    entry["instance_id"])

    async def _on_delete(self, key: str) -> None:
        # models/{name}/{instance_id}
        _, name, iid = key.rsplit("/", 2)
        ids = self._instances.get(name)
        if ids is None:
            return
        ids.discard(int(iid))
        if not ids:
            self.manager.remove(name)
            client = self._clients.pop(name, None)
            if client:
                await client.stop()
            kv = self._kv_clients.pop(name, None)
            if kv:
                await kv.stop()
            logger.info("model %r removed (no instances left)", name)
