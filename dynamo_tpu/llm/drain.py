"""Worker-side drain: hand in-flight streams to peers WITH their KV.

The elasticity gap this closes (ROADMAP item 5): a planner scale-down
used to SIGTERM a worker and wait for its longest stream to finish — or,
worse, drop it and let the frontend's MigrationClient re-prefill the
whole prompt on a peer, throwing away every KV byte the dying worker
already paid for.  `DrainableService` is the worker's outermost serving
wrapper (directly under `engine_wire_handler`); on drain it

1. refuses new admissions with the `DRAIN_REFUSAL` marker (retryable —
   the frontend re-routes; the instance record is leaving anyway),
2. interrupts each in-flight stream and ends it with a `migrate` delta
   naming this worker's RPC address (its kv_blocks donor endpoint) and
   the stream's sealed-token high-water mark,
3. stays alive serving `kv_blocks` until the peers' pulls finish (the
   worker main bounds that wait), so the handed-off KV actually moves.

The frontend's MigrationClient (llm/migration.py) consumes the migrate
delta: it re-issues prompt+generated to a peer with a `migrate_kv`
annotation, and the peer's PrefixShareClient pulls the sealed prefix
over the kv_blocks/device plane before admission.  Cancelling the local
request releases its pages, but every SEALED block stays registered in
the tiered cache (inactive → exportable), which is exactly what the
donor pull reads.

Drain triggers (worker/main.py): SIGTERM with `--drain on` (default),
or the control-plane command key `drain/{pid}` / `drain/instance/{id}`
(`ControlPlane.put` from an operator or the planner).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.runtime import flight_recorder
from dynamo_tpu.runtime.contracts import never_engine_thread

logger = logging.getLogger(__name__)

# Keep in sync with llm/migration.DRAIN_REFUSAL (string-matched across
# the RPC error relay; defined in both modules so neither frontend nor
# worker pulls the other's import graph).
DRAIN_REFUSAL = "worker-draining"

# Control-plane drain command prefix: `drain/{pid}` or
# `drain/instance/{instance_id}` (value is free-form metadata).
DRAIN_PREFIX = "drain/"


def drain_key_pid(pid: int) -> str:
    return f"{DRAIN_PREFIX}{pid}"


def drain_key_instance(instance_id: int) -> str:
    return f"{DRAIN_PREFIX}instance/{instance_id}"


class WorkerDrainingError(RuntimeError):
    """New admission refused mid-drain; the message carries the marker
    the frontend's MigrationClient retries on."""

    def __init__(self) -> None:
        super().__init__(DRAIN_REFUSAL)


class DrainableService:
    """EngineClient wrapper that can hand its in-flight streams off.

    `kv_address`: this worker's RPC address (where peers pull kv_blocks
    from); None for engines with no exportable KV (mocker) — handoffs
    then carry no hint and the peer re-prefills (the pre-ISSUE-15
    ladder rung, still zero failed requests).
    """

    def __init__(self, inner, *, kv_address: Optional[str] = None,
                 block_size: int = 64) -> None:
        self.inner = inner
        self.kv_address = kv_address
        self.block_size = block_size
        self.draining = False
        self.migrated_out = 0          # streams handed off with KV hints
        self._active: Dict[str, asyncio.Event] = {}
        self.flight = flight_recorder.get_recorder()

    @property
    def active_requests(self) -> int:
        return len(self._active)

    @never_engine_thread
    async def generate(self, request):
        if self.draining:
            raise WorkerDrainingError()
        rid = request.request_id
        drain_ev = asyncio.Event()
        self._active[rid] = drain_ev
        emitted = 0
        q: asyncio.Queue = asyncio.Queue()
        _DONE = object()

        async def pump():
            # Inner stream consumed on its own task so the outer loop can
            # race deltas against the drain signal; exceptions cross the
            # queue and re-raise in the caller's context.
            try:
                async for d in self.inner.generate(request):
                    await q.put(d)
                await q.put(_DONE)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                await q.put(e)

        task = asyncio.create_task(pump())
        ev_wait = asyncio.create_task(drain_ev.wait())
        get: Optional[asyncio.Task] = None
        try:
            while True:
                get = asyncio.create_task(q.get())
                done, _ = await asyncio.wait(
                    {get, ev_wait}, return_when=asyncio.FIRST_COMPLETED)
                if ev_wait not in done:
                    item = get.result()
                    get = None
                    if item is _DONE:
                        return
                    if isinstance(item, BaseException):
                        raise item
                    emitted += len(item.token_ids)
                    yield item
                    if item.finished:
                        return
                    continue
                # Drain signalled — PREFERRED over any deltas still
                # queued (they were never delivered, so the peer simply
                # regenerates them; `emitted` counts delivered tokens
                # only).  Cancel the local request (pages free; sealed
                # blocks stay registered → exportable) and end the
                # stream with the handoff marker.
                get.cancel()
                get = None
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    # dynamo-lint: disable=DL003 stream already torn down
                    pass  # the handoff below is the outcome either way
                total = len(request.token_ids) + emitted
                covered = (total // self.block_size) * self.block_size
                migrate = {"reason": "drain",
                           "covered_tokens": int(covered)}
                if self.kv_address and covered > 0:
                    migrate["address"] = self.kv_address
                self.migrated_out += 1
                fl = self.flight
                if fl.enabled:
                    fl.record("migrate_out", rid=rid, emitted=emitted,
                              covered=covered)
                from dynamo_tpu.runtime.ledger import ledger_of

                led = ledger_of(request)
                if led is not None:
                    # Rides home on this very migrate delta (the wire
                    # handler attaches the hop ledger to it).
                    led.stamp("drain_handoff", covered_tokens=int(covered),
                              emitted=emitted)
                logger.info("drain: handing off %s (%d tokens emitted, "
                            "%d KV tokens offered)", rid, emitted, covered)
                yield TokenDelta(request_id=rid, token_ids=[],
                                 finished=False, migrate=migrate)
                return
        finally:
            if get is not None:
                get.cancel()
            ev_wait.cancel()
            task.cancel()
            self._active.pop(rid, None)

    @never_engine_thread
    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop admitting, hand every in-flight stream off, and wait for
        the handoffs to flush (bounded).  Returns True when every stream
        was handed off inside the budget."""
        self.draining = True
        fl = self.flight
        if fl.enabled:
            fl.record("drain", inflight=len(self._active),
                      kv=bool(self.kv_address))
        logger.info("drain: %d in-flight stream(s) to hand off",
                    len(self._active))
        for ev in list(self._active.values()):
            ev.set()
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(0.0, timeout_s)
        while self._active and loop.time() < deadline:
            await asyncio.sleep(0.02)
        if self._active:
            logger.warning("drain: %d stream(s) still open at timeout",
                           len(self._active))
        return not self._active
