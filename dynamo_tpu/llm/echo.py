"""Echo engine: the pipeline-testing backend.

Role of the reference's `EchoEngineCore`/`EchoEngineFull`
(`lib/llm/src/engines.rs:71,113`, selectable as `dynamo-run out=echo`):
an EngineClient that streams the prompt's own tokens back at a fixed
cadence — every frontend/router/migration behavior is testable with zero
model weights and deterministic output.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.engine.scheduler import FinishReason
from dynamo_tpu.llm.preprocessor import PreprocessedRequest


class EchoEngine:
    """Streams the prompt back, one token per `delay_ms`, capped by
    max_tokens; finish_reason mirrors the cap semantics."""

    def __init__(self, delay_ms: float = 1.0) -> None:
        self.delay_ms = delay_ms

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        rid = request.request_id
        budget = request.sampling.max_tokens
        out = list(request.token_ids)[:budget]
        for i, tok in enumerate(out):
            await asyncio.sleep(self.delay_ms / 1000.0)
            last = i == len(out) - 1
            yield TokenDelta(
                request_id=rid, token_ids=[tok], finished=last,
                finish_reason=(FinishReason.LENGTH if last else None))
        if not out:
            yield TokenDelta(request_id=rid, token_ids=[], finished=True,
                             finish_reason=FinishReason.LENGTH)
