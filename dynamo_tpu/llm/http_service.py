"""OpenAI-compatible HTTP service (aiohttp).

Role of the reference's axum server (`lib/llm/src/http/service/openai.rs`):
/v1/chat/completions, /v1/completions, /v1/models with SSE streaming,
client-disconnect cancellation (`disconnect.rs` — here: the request
generator is closed when aiohttp detects the peer went away, which
cancels the engine request), request metrics incl. TTFT/ITL histograms
(`metrics.rs`), /metrics exposition, and /health & /live endpoints
(reference `system_status_server.rs`).
"""

from __future__ import annotations

import asyncio
import base64
import logging
import time
import uuid
from typing import Optional

from aiohttp import web

from dynamo_tpu.engine.scheduler import FinishReason
from dynamo_tpu.llm.backend import StreamDetokenizer, wire_finish_reason
from dynamo_tpu.llm.protocols import openai as oai
from dynamo_tpu.llm.service import ModelHandle, ModelManager
from dynamo_tpu.runtime import ledger as ledger_mod
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.metrics import (
    FrontendMetrics, MetricsRegistry, RequestMetrics)

logger = logging.getLogger(__name__)



class HttpService:
    def __init__(
        self,
        models: ModelManager,
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[tracing.Tracer] = None,
    ) -> None:
        self.models = models
        self.registry = registry or MetricsRegistry()
        self.metrics = FrontendMetrics(self.registry)
        # Per-request lifecycle histograms (dynamo_request_*): TTFT /
        # TPOT / queue wait, always on (cheap); spans ride the tracer.
        self.request_metrics = RequestMetrics(self.registry)
        # SLO burn-rate monitor (runtime/slo.py), installed by the
        # embedding process (frontend main) when --slo-* flags configure
        # objectives; None → /debug/slo reports enabled=false.
        self.slo_monitor = None
        # Request-ledger fold point (ISSUE 18): completed per-request
        # phase ledgers land here — dynamo_request_phase_seconds{phase=},
        # the goodput counter pair, /debug/requests, and the dominant-
        # phase attribution SloMonitor and `dynamo top` read.  Frontend
        # main sets slo_ttft/slo_tpot from the --slo-* flags.
        self.ledger_sink = ledger_mod.LedgerSink(self.registry)
        self.tracer = tracer or tracing.get_tracer()
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self.chat_completions)
        self.app.router.add_post("/v1/completions", self.completions)
        self.app.router.add_post("/v1/embeddings", self.embeddings)
        self.app.router.add_post("/v1/responses", self.responses)
        self.app.router.add_post("/clear_kv_blocks", self.clear_kv_blocks)
        self.app.router.add_get("/v1/models", self.list_models)
        self.app.router.add_get("/metrics", self.prometheus)
        self.app.router.add_get("/debug/traces", self.debug_traces)
        self.app.router.add_get("/debug/requests", self.debug_requests)
        self.app.router.add_get("/debug/slo", self.debug_slo)
        self.app.router.add_get("/debug/flightrecorder",
                                self.debug_flightrecorder)
        self.app.router.add_get("/debug/deviceprofile",
                                self.debug_deviceprofile)
        self.app.router.add_get("/health", self.health)
        self.app.router.add_get("/live", self.live)
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    # -- lifecycle --------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Bind and serve; returns the bound port (0 → ephemeral)."""
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        logger.info("HTTP service on %s:%s", host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _error(status: int, message: str, type_: str = "invalid_request_error"):
        body = oai.ErrorResponse(
            error=oai.ErrorDetail(message=message, type=type_))
        return web.json_response(body.model_dump(exclude_none=True),
                                 status=status)

    def _lookup(self, model: str) -> Optional[ModelHandle]:
        return self.models.get(model)

    @staticmethod
    def _request_id(request: web.Request, prefix: str) -> str:
        """Trace context: honor a caller-provided X-Request-Id so one id
        is grep-able across frontend and worker logs (reference
        distributed trace ctx over transport headers, logging.rs:73-79).
        A unique suffix is ALWAYS appended — the raw header value is not
        unique (proxy retries, concurrent duplicates) and the engine keys
        request state by this id."""
        header = request.headers.get("x-request-id")
        if header:
            return f"{header[:120]}-{uuid.uuid4().hex[:8]}"
        return oai.request_id(prefix)

    def _start_trace(self, route: str, rid: str, model: str):
        """Root span for one HTTP request, reusing the request id as the
        trace id (one grep-able id across logs, metrics, and the merged
        Perfetto view).  Returns (span, contextvar token); both are
        no-ops when tracing is off."""
        span = self.tracer.start_span(
            f"http.{route}", trace_id=rid,
            attrs={"rid": rid, "model": model})
        token = tracing.use_span(span) if span.ctx is not None else None
        return span, token

    @staticmethod
    def _end_trace(span, token) -> None:
        span.end()
        if token is not None:
            tracing.restore(token)

    def _validate_context(self, handle: ModelHandle, pre):
        """Boundary validation (reference `protocols/openai/validate.rs`):
        a prompt that cannot fit the model context is a client error the
        HTTP layer must surface as a 400 — r2 silently finished such
        requests as zero-token LENGTH stops.  A prompt that fits but whose
        max_tokens would overflow gets max_tokens clamped."""
        ctx = handle.max_context
        n = len(pre.token_ids)
        if n >= ctx:
            return self._error(
                400,
                f"prompt has {n} tokens which exceeds the model's maximum "
                f"context length of {ctx} tokens",
                "invalid_request_error")
        budget = ctx - n
        if pre.sampling.max_tokens > budget:
            import dataclasses

            pre.sampling = dataclasses.replace(pre.sampling,
                                               max_tokens=budget)
        return None

    # -- routes -----------------------------------------------------------

    async def health(self, _req: web.Request) -> web.Response:
        ready = len(self.models) > 0
        return web.json_response(
            {"status": "ready" if ready else "starting",
             "models": self.models.names()},
            status=200 if ready else 503)

    async def live(self, _req: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def prometheus(self, _req: web.Request) -> web.Response:
        return web.Response(text=self.registry.expose(),
                            content_type="text/plain")

    async def debug_traces(self, req: web.Request) -> web.Response:
        """Most recent completed traces (`?n=K`, default 32) — the
        per-process buffer tools/trace_merge.py stitches across the
        deployment."""
        try:
            n = int(req.query.get("n", "32"))
        except ValueError:
            return self._error(400, "n must be an integer")
        return web.json_response(
            tracing.debug_traces_payload(n, self.tracer))

    async def debug_requests(self, req: web.Request) -> web.Response:
        """Slowest-N completed request ledgers (`?n=K`, default 10) with
        full phase stamps, plus the window's dominant phase and the
        goodput ratio — "which hop ate this request's latency", served
        straight from the LedgerSink ring."""
        try:
            n = int(req.query.get("n", "10"))
        except ValueError:
            return self._error(400, "n must be an integer")
        return web.json_response(self.ledger_sink.debug_payload(n))

    async def debug_flightrecorder(self, req: web.Request) -> web.Response:
        """The frontend's flight-recorder ring (`?n=K`, default 256):
        SLO state transitions and slow-request markers — the frontend
        half of a fleet postmortem (worker rings ride their
        StatusServers)."""
        from dynamo_tpu.runtime import flight_recorder

        try:
            n = int(req.query.get("n", "256"))
        except ValueError:
            return self._error(400, "n must be an integer")
        return web.json_response(
            flight_recorder.get_recorder().debug_payload(n))

    async def debug_deviceprofile(self, req: web.Request) -> web.Response:
        """This process's device-truth plane
        (runtime/device_profiler.py): state without `?ms=`, one bounded
        jax.profiler capture with `?ms=N` — same payload shape as the
        worker StatusServer route, so tooling treats every process
        uniformly.  (Worker captures ride the workers' own status
        ports or the control-plane `profile/<pid>` command; this route
        covers frontend-side device work.)"""
        import asyncio

        from dynamo_tpu.runtime import device_profiler

        prof = device_profiler.get_profiler()
        ms_raw = req.query.get("ms")
        if ms_raw is None:
            return web.json_response(prof.debug_payload())
        try:
            ms = int(ms_raw)
            if ms <= 0:
                raise ValueError
        except ValueError:
            return self._error(400, "ms must be a positive integer")
        res = await asyncio.to_thread(prof.capture, ms)
        return web.json_response(res, status=200 if res.get("ok") else 503)

    async def debug_slo(self, _req: web.Request) -> web.Response:
        """Current SLO burn-rate evaluation over this frontend's request
        histograms (runtime/slo.py; enabled via the --slo-* flags)."""
        from dynamo_tpu.runtime import slo as slo_mod

        if self.slo_monitor is None:
            return web.json_response(slo_mod.disabled_payload())
        return web.json_response(self.slo_monitor.payload())

    async def list_models(self, _req: web.Request) -> web.Response:
        listing = oai.ModelList(
            data=[oai.ModelInfo(id=n) for n in self.models.names()])
        return web.json_response(listing.model_dump())

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = oai.ChatCompletionRequest.model_validate(await request.json())
        except Exception as e:
            return self._error(400, f"invalid request: {e}")
        handle = self._lookup(body.model)
        if handle is None:
            return self._error(404, f"model {body.model!r} not found",
                               "model_not_found")
        rid = self._request_id(request, "chatcmpl")
        root, tok = self._start_trace("chat", rid, body.model)
        try:
            with self.tracer.start_span("frontend.preprocess"):
                try:
                    pre = handle.preprocessor.preprocess_chat(body, rid)
                except ValueError as e:
                    return self._error(400, str(e))
            mm = handle.multimodal
            if mm is not None and mm.image_refs(body.messages):
                # image_url parts → encode worker → prompt_embeds
                # (llm/multimodal.py; reference multimodal_v1 processor).
                try:
                    with self.tracer.start_span("frontend.encode_images"):
                        pre = await mm.attach(body.messages, pre)
                except Exception as e:
                    return self._error(
                        502, f"image encoding failed: {e}", "encode_error")
            elif mm is None and self._has_image_parts(body.messages):
                return self._error(
                    400, "this model has no multimodal pipeline configured "
                         "(image_url parts unsupported)")
            err = self._validate_context(handle, pre)
            if err is not None:
                return err
            self._attach_priority(request, pre)
            logger.info("request %s: chat model=%s prompt_tokens=%d "
                        "stream=%s", rid, body.model, len(pre.token_ids),
                        body.stream)
            root.set_attr(prompt_tokens=len(pre.token_ids),
                          stream=bool(body.stream))
            if body.stream:
                return await self._stream_chat(request, handle, body, pre,
                                               rid)
            return await self._unary_chat(handle, body, pre, rid)
        finally:
            self._end_trace(root, tok)

    @staticmethod
    def _attach_priority(request: web.Request, pre) -> None:
        """QoS class (ISSUE 15): the x-dynamo-priority header (named
        class or 0..2 integer) rides the preprocessed request's
        annotations to the worker's scheduler.  Absent header = standard;
        the worker side is equally forgiving (service.priority_of)."""
        header = request.headers.get("x-dynamo-priority")
        if header:
            from dynamo_tpu.llm.service import PRIORITY_ANNOTATION

            pre.annotations[PRIORITY_ANNOTATION] = header.strip()

    @staticmethod
    def _has_image_parts(messages) -> bool:
        from dynamo_tpu.llm.multimodal import MultimodalAttach

        return bool(MultimodalAttach.image_refs(messages))

    async def completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = oai.CompletionRequest.model_validate(await request.json())
        except Exception as e:
            return self._error(400, f"invalid request: {e}")
        handle = self._lookup(body.model)
        if handle is None:
            return self._error(404, f"model {body.model!r} not found",
                               "model_not_found")
        rid = self._request_id(request, "cmpl")
        root, tok = self._start_trace("completion", rid, body.model)
        try:
            return await self._completions_traced(request, handle, body,
                                                  rid, root)
        finally:
            self._end_trace(root, tok)

    async def _completions_traced(self, request, handle, body, rid, root):
        with self.tracer.start_span("frontend.preprocess"):
            try:
                pre = handle.preprocessor.preprocess_completion(body, rid)
            except ValueError as e:
                return self._error(400, str(e))
        err = self._validate_context(handle, pre)
        if err is not None:
            return err
        self._attach_priority(request, pre)
        logger.info("request %s: completion model=%s prompt_tokens=%d "
                    "stream=%s", rid, body.model, len(pre.token_ids),
                    body.stream)
        root.set_attr(prompt_tokens=len(pre.token_ids),
                      stream=bool(body.stream))
        if body.stream:
            return await self._stream_completion(request, handle, body, pre,
                                                 rid)

        start = time.monotonic()
        self.metrics.requests_total.inc(labels={"model": body.model})
        self.metrics.requests_in_flight.add(1, labels={"model": body.model})
        want_lp = bool(pre.sampling.logprobs)
        try:
            results, total_out = await self._collect_choices(
                handle, pre, body.n, body.model, start, want_lp)
        finally:
            self.metrics.requests_in_flight.add(-1, labels={"model": body.model})
        self._observe_done(body.model, start, len(pre.token_ids), total_out)
        choices = []
        for i, (text, reason, det, lp_sink) in enumerate(results):
            logprobs = None
            if lp_sink:
                logprobs = {
                    "tokens": [handle.tokenizer.decode([t])
                               for t, _ in lp_sink],
                    "token_logprobs": [lp for _, lp in lp_sink],
                }
            choices.append(oai.CompletionChoice(
                index=i, text=text, finish_reason=reason,
                logprobs=logprobs))
        resp = oai.CompletionResponse(
            id=rid, model=body.model, choices=choices,
            usage=oai.Usage(
                prompt_tokens=len(pre.token_ids),
                completion_tokens=total_out,
                total_tokens=len(pre.token_ids) + total_out))
        return web.json_response(resp.model_dump(exclude_none=True))

    async def clear_kv_blocks(self, _req: web.Request) -> web.Response:
        """Admin: flush every model's reusable KV blocks (reference
        `http/service/clear_kv_blocks.rs`)."""
        out = {}
        for name in self.models.names():
            clear = getattr(self.models.get(name).client,
                            "clear_kv_blocks", None)
            if clear is None:
                out[name] = {"status": "unsupported"}
                continue
            try:
                out[name] = {"status": "ok", "cleared": await clear()}
            except Exception as e:
                out[name] = {"status": "error", "error": str(e)}
        return web.json_response(out)

    async def responses(self, request: web.Request) -> web.StreamResponse:
        """/v1/responses (reference `protocols/openai/responses.rs`):
        normalised onto the chat pipeline; unary and SSE streaming."""
        try:
            body = oai.ResponsesRequest.model_validate(await request.json())
        except Exception as e:
            return self._error(400, f"invalid request: {e}")
        handle = self._lookup(body.model)
        if handle is None:
            return self._error(404, f"model {body.model!r} not found",
                               "model_not_found")
        rid = self._request_id(request, "resp")
        root, tok = self._start_trace("responses", rid, body.model)
        try:
            return await self._responses_traced(request, handle, body, rid,
                                                root)
        finally:
            self._end_trace(root, tok)

    async def _responses_traced(self, request, handle, body, rid, root):
        with self.tracer.start_span("frontend.preprocess"):
            try:
                chat = body.as_chat()
                pre = handle.preprocessor.preprocess_chat(chat, rid)
            except Exception as e:
                # as_chat's ChatMessage validation failures are client
                # input errors too (e.g. an unsupported role) — 400, not
                # 500.
                return self._error(400, str(e))
        err = self._validate_context(handle, pre)
        if err is not None:
            return err
        self._attach_priority(request, pre)
        logger.info("request %s: responses model=%s prompt_tokens=%d "
                    "stream=%s", rid, body.model, len(pre.token_ids),
                    body.stream)
        root.set_attr(prompt_tokens=len(pre.token_ids),
                      stream=bool(body.stream))
        if body.stream:
            return await self._stream_responses(request, handle, body, pre,
                                                rid)
        start = time.monotonic()
        self.metrics.requests_total.inc(labels={"model": body.model})
        self.metrics.requests_in_flight.add(1, labels={"model": body.model})
        det = StreamDetokenizer(handle.tokenizer, pre.stop_sequences)
        parts, reason = [], None
        try:
            async for out in self._token_stream(handle, pre, det,
                                                body.model, start):
                parts.append(out.text)
                if out.finished:
                    reason = out.finish_reason
        finally:
            self.metrics.requests_in_flight.add(-1,
                                                labels={"model": body.model})
        self._observe_done(body.model, start, len(pre.token_ids),
                           det.completion_tokens)
        # Responses-API status semantics: stop → completed; truncation
        # (length ceiling) → incomplete; engine error → failed.
        status = {"stop": "completed", "length": "incomplete",
                  "error": "failed"}.get(str(reason or "stop"), "completed")
        resp = oai.ResponsesResponse(
            id=rid, model=body.model, status=status,
            output=[oai.ResponseOutputMessage(
                status=status,
                content=[oai.ResponseOutputText(text="".join(parts))])],
            usage=oai.ResponsesUsage(
                input_tokens=len(pre.token_ids),
                output_tokens=det.completion_tokens,
                total_tokens=len(pre.token_ids) + det.completion_tokens))
        return web.json_response(resp.model_dump(exclude_none=True))

    async def _stream_responses(self, request, handle, body, pre, rid):
        """Responses-API SSE: `response.created` → N ×
        `response.output_text.delta` → `response.completed` (the event
        names OpenAI's Responses stream uses; the reference streams
        internally and folds for unary, `http/service/openai.rs:222-226`)."""
        start = time.monotonic()
        self.metrics.requests_total.inc(labels={"model": body.model})
        self.metrics.requests_in_flight.add(1, labels={"model": body.model})
        response = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await response.prepare(request)
        det = StreamDetokenizer(handle.tokenizer, pre.stop_sequences)
        parts, reason = [], None
        try:
            created = oai.ResponsesResponse(
                id=rid, model=body.model, status="in_progress")
            await response.write(oai.sse_encode_event(
                "response.created",
                {"type": "response.created",
                 "response": created.model_dump(exclude_none=True)}
            ).encode())
            async for out in self._token_stream(handle, pre, det,
                                                body.model, start):
                if out.text:
                    parts.append(out.text)
                    await response.write(oai.sse_encode_event(
                        "response.output_text.delta",
                        {"type": "response.output_text.delta",
                         "delta": out.text}).encode())
                if out.finished:
                    reason = out.finish_reason
                    break
            status = {"stop": "completed", "length": "incomplete",
                      "error": "failed"}.get(str(reason or "stop"),
                                             "completed")
            final = oai.ResponsesResponse(
                id=rid, model=body.model, status=status,
                output=[oai.ResponseOutputMessage(
                    status=status,
                    content=[oai.ResponseOutputText(text="".join(parts))])],
                usage=oai.ResponsesUsage(
                    input_tokens=len(pre.token_ids),
                    output_tokens=det.completion_tokens,
                    total_tokens=len(pre.token_ids)
                    + det.completion_tokens))
            await response.write(oai.sse_encode_event(
                "response.completed",
                {"type": "response.completed",
                 "response": final.model_dump(exclude_none=True)}
            ).encode())
        except (ConnectionResetError, asyncio.CancelledError):
            logger.info("client disconnected: %s", rid)
            raise
        finally:
            self.metrics.requests_in_flight.add(-1,
                                                labels={"model": body.model})
            self._observe_done(body.model, start, len(pre.token_ids),
                               det.completion_tokens)
        await response.write_eof()
        return response

    async def embeddings(self, request: web.Request) -> web.Response:
        """/v1/embeddings: last-token hidden-state embeddings (reference
        route `http/service/openai.rs:315`)."""
        try:
            body = oai.EmbeddingRequest.model_validate(await request.json())
        except Exception as e:
            return self._error(400, f"invalid request: {e}")
        handle = self._lookup(body.model)
        if handle is None:
            return self._error(404, f"model {body.model!r} not found",
                               "model_not_found")
        embed = getattr(handle.client, "embed", None)
        if embed is None:
            return self._error(501, "this model's engine does not serve "
                                    "embeddings", "not_implemented")
        inputs = body.inputs()
        if not inputs:
            return self._error(400, "input must be non-empty")
        if len(inputs) > 128:
            # Embeddings run one prefill per input on the engine; an
            # unbounded batch would starve token streaming for seconds.
            return self._error(400, f"too many inputs ({len(inputs)} > "
                                    "128 per request)")
        token_lists = []
        for item in inputs:
            toks = (handle.tokenizer.encode(item)
                    if isinstance(item, str) else list(item))
            if len(toks) >= handle.max_context:
                return self._error(
                    400, f"input of {len(toks)} tokens exceeds the model's "
                         f"maximum context length of {handle.max_context}")
            token_lists.append(toks)
        try:
            vecs = await embed(token_lists)
        except (ValueError, NotImplementedError) as e:
            return self._error(400, str(e))
        except (ConnectionError, OSError) as e:
            return self._error(503, f"embedding worker unavailable: {e}",
                               "service_unavailable")

        def encode_vec(vec):
            if body.encoding_format == "base64":
                import numpy as np

                return base64.b64encode(
                    np.asarray(vec, np.float32).tobytes()).decode("ascii")
            return [float(x) for x in vec]

        n_in = sum(len(t) for t in token_lists)
        resp = oai.EmbeddingResponse(
            model=body.model,
            data=[oai.EmbeddingData(index=i, embedding=encode_vec(vec))
                  for i, vec in enumerate(vecs)],
            usage=oai.Usage(prompt_tokens=n_in, total_tokens=n_in))
        return web.json_response(resp.model_dump(exclude_none=True))

    async def _stream_completion(self, request, handle, body, pre, rid):
        """SSE stream of `text_completion` chunks (ADVICE r1: the unary-only
        handler broke OpenAI streaming clients)."""

        def make_chunk(i, out, lps):
            logprobs = None
            if lps:
                logprobs = {
                    "tokens": [handle.tokenizer.decode([t])
                               for t, _ in lps],
                    "token_logprobs": [lp for _, lp in lps],
                }
            return [oai.CompletionResponse(
                id=rid, model=body.model,
                choices=[oai.CompletionChoice(
                    index=i, text=out.text or "",
                    finish_reason=out.finish_reason,
                    logprobs=logprobs)])]

        def make_usage_chunk(usage):
            return oai.CompletionResponse(
                id=rid, model=body.model, choices=[], usage=usage)

        return await self._stream_sse(request, handle, body, pre, rid,
                                      make_chunk, make_usage_chunk)

    # -- chat serving internals -------------------------------------------

    def _fan_out(self, pre, n: int):
        """n>1 sampling: clone the preprocessed request per choice with a
        distinct engine id; a client-pinned seed folds the choice index in
        (reproducible, but distinct across choices — vLLM convention)."""
        import copy
        import dataclasses

        out = []
        for i in range(n):
            clone = copy.copy(pre)
            clone.request_id = f"{pre.request_id}-c{i}" if i else pre.request_id
            if i and pre.sampling.seed is not None:
                clone.sampling = dataclasses.replace(
                    pre.sampling, seed=pre.sampling.seed + i)
            out.append(clone)
        return out

    async def _collect_one(self, handle, pre, model, start, want_lp,
                           on_first=None, observe_queue_wait=True):
        """Drain one engine stream → (text, finish_reason, det, lp_sink).
        `on_first` fires at the first yielded output (choice-0's prompt
        blocks are sealed by then — the signal siblings gate on)."""
        det = StreamDetokenizer(handle.tokenizer, pre.stop_sequences)
        lp_sink = [] if want_lp else None
        parts, reason = [], None
        async for out in self._token_stream(
                handle, pre, det, model, start, lp_sink=lp_sink,
                observe_queue_wait=observe_queue_wait):
            if on_first is not None:
                on_first()
                on_first = None
            parts.append(out.text)
            if out.finished:
                reason = out.finish_reason
        return "".join(parts), reason, det, lp_sink

    async def _collect_choices(self, handle, pre, n, model, start, want_lp):
        """n-choice unary collection.  Choice 0 starts FIRST; siblings
        launch at its FIRST TOKEN — the shared prompt blocks are sealed
        once prefill completes, so waiting for choice 0's whole stream
        (ADVICE r3) bought nothing but latency.  Siblings still
        prefix-hit instead of paying n× prefill for the same prompt.
        Failures don't leak running generations: everything is gathered
        with return_exceptions and the first error re-raised only after
        every stream has settled."""
        clones = self._fan_out(pre, n)
        if n == 1:
            r = await self._collect_one(handle, clones[0], model, start,
                                        want_lp)
            return [r], r[2].completion_tokens
        sealed = asyncio.Event()

        async def run0():
            try:
                return await self._collect_one(handle, clones[0], model,
                                               start, want_lp,
                                               on_first=sealed.set)
            finally:
                sealed.set()  # error/empty stream: don't strand siblings

        async def run_sib(clone):
            await sealed.wait()
            # Sibling TTFT measures from its own start: folding choice
            # 0's prefill into the histogram would skew it.  Queue wait
            # is choice 0's alone (a sibling's would read ~0).
            return await self._collect_one(handle, clone, model,
                                           time.monotonic(), want_lp,
                                           observe_queue_wait=False)

        results = await asyncio.gather(
            run0(), *(run_sib(c) for c in clones[1:]),
            return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        total_out = sum(det.completion_tokens for _, _, det, _ in results)
        return list(results), total_out

    # TPOT interval spans recorded per trace before they'd crowd out the
    # rest of the timeline (the histogram still sees every interval).
    MAX_TPOT_SPANS = 32

    async def _token_stream(self, handle, pre, det, model, start_ts,
                            lp_sink=None, observe_queue_wait=True):
        """Engine deltas → TextDeltas, with TTFT/ITL observation and the
        request-lifecycle trace spans (queue wait → TTFT → per-token
        TPOT intervals, parented under the request's root span).
        `lp_sink`: list collecting (token_id, logprob) pairs when the
        request asked for logprobs.  `observe_queue_wait`: False for
        n>1 sibling choices — their start_ts is their own launch time
        (post-seal), so a ~0 "queue wait" per sibling would skew the
        histogram low by a factor of n."""
        labels = {"model": model}
        tracer = self.tracer
        parent = tracing.current_span() if tracer.enabled else None
        led = None
        if observe_queue_wait:
            # Request ledger (ISSUE 18): begin BEFORE the client pipeline
            # so route/queue/prefill/kv_transfer stamps land on it; n>1
            # siblings (observe_queue_wait=False) stay ledger-less — one
            # ledger per HTTP request, choice 0's path.
            led = ledger_mod.begin(pre)
            # Queue wait, frontend view: request arrival → the
            # generation stream starting (preprocess, image encode,
            # routing, admission to the client pipeline).  The
            # engine-side engine.queue_wait span covers in-engine wait.
            t_entry = time.monotonic()
            self.request_metrics.queue_wait.observe(t_entry - start_ts,
                                                    labels=labels)
            if led is not None:
                led.stamp("receive", dur=t_entry - start_ts, t=t_entry)
            if parent is not None:
                tracer.record_span("frontend.queue_wait", parent,
                                   start_ts, t_entry)
        first = True
        last_t = None
        n_intervals = 0
        ttft_s = None
        itl_sum = 0.0

        def tpot_mean():
            return itl_sum / n_intervals if n_intervals else None

        async for delta in handle.client.generate(pre):
            now = time.monotonic()
            ledger_mod.absorb_delta(pre, delta, where="frontend")
            if (lp_sink is not None and delta.logprobs
                    and len(delta.logprobs) == len(delta.token_ids)):
                lp_sink.extend(zip(delta.token_ids, delta.logprobs))
            if delta.token_ids:
                if first:
                    ttft_s = now - start_ts
                    self.metrics.ttft.observe(now - start_ts,
                                              labels={"model": model})
                    self.request_metrics.ttft.observe(now - start_ts,
                                                      labels=labels)
                    if parent is not None:
                        tracer.record_span("frontend.ttft", parent,
                                           start_ts, now)
                    first = False
                elif last_t is not None:
                    self.metrics.itl.observe(now - last_t,
                                             labels={"model": model})
                    self.request_metrics.tpot.observe(now - last_t,
                                                      labels=labels)
                    n_intervals += 1
                    itl_sum += now - last_t
                    if (parent is not None
                            and n_intervals <= self.MAX_TPOT_SPANS):
                        tracer.record_span(
                            "decode.tpot", parent, last_t, now,
                            attrs={"index": n_intervals,
                                   "tokens": len(delta.token_ids)})
                last_t = now
                out = det.push_tokens(delta.token_ids)
                if out.finished:      # stop string hit mid-stream
                    self.request_metrics.observe_outcome(ok=True)
                    self.ledger_sink.fold(led, ttft_s, tpot_mean(),
                                          det.completion_tokens, ok=True)
                    yield out
                    return
                if out.text:
                    yield out
            if delta.finished:
                # Terminal outcome feeds the SLO error-rate objective:
                # engine ERROR finishes are budget burn, everything else
                # (stop/length/cancel) is a served request.
                ok = delta.finish_reason is not FinishReason.ERROR
                self.request_metrics.observe_outcome(ok=ok)
                self.ledger_sink.fold(led, ttft_s, tpot_mean(),
                                      det.completion_tokens, ok=ok)
                yield det.finish(delta.finish_reason)
                return
        # Engine stream ended without a finished marker (worker died):
        self.request_metrics.observe_outcome(ok=False)
        self.ledger_sink.fold(led, ttft_s, tpot_mean(),
                              det.completion_tokens, ok=False)
        yield det.finish(FinishReason.ERROR)

    async def _unary_chat(self, handle, body, pre, rid):
        start = time.monotonic()
        self.metrics.requests_total.inc(labels={"model": body.model})
        self.metrics.requests_in_flight.add(1, labels={"model": body.model})
        want_lp = bool(pre.sampling.logprobs)
        try:
            results, total_out = await self._collect_choices(
                handle, pre, body.n, body.model, start, want_lp)
        finally:
            self.metrics.requests_in_flight.add(-1, labels={"model": body.model})
        self._observe_done(body.model, start, len(pre.token_ids), total_out)

        choices = []
        for i, (text, reason, det, lp_sink) in enumerate(results):
            tool_calls = None
            if body.tools and body.tool_choice != "none":
                # Tool-call extraction (reference postprocessor/
                # tool_calling): only attempted when the client declared
                # tools; parse failure leaves plain content.  A pinned
                # tool_choice wraps the whole completion as that call's
                # arguments (no marker syntax expected from the model).
                from dynamo_tpu.llm.postprocessor import (
                    force_tool_call,
                    forced_tool_name,
                    parse_tool_calls,
                )

                forced = forced_tool_name(body.tool_choice, body.tools)
                if forced:
                    text, calls = "", force_tool_call(text, forced)
                else:
                    text, calls = parse_tool_calls(text,
                                                   body.tool_call_parser)
                if calls:
                    tool_calls = calls
                    reason = "tool_calls"
            logprobs = None
            if lp_sink:
                logprobs = oai.ChatLogprobs(content=[
                    oai.ChatLogprobEntry(
                        token=handle.tokenizer.decode([t]), logprob=lp)
                    for t, lp in lp_sink])
            choices.append(oai.ChatChoice(
                index=i,
                # OpenAI wire shape: `content` is present (possibly "")
                # unless the message is a tool call — `text or None`
                # under exclude_none silently DROPPED the key whenever
                # the detokenizer produced no text.
                message=oai.ChatMessage(
                    role="assistant",
                    content=(text or None) if tool_calls else text,
                    tool_calls=tool_calls),
                finish_reason=reason,
                logprobs=logprobs))
        resp = oai.ChatCompletionResponse(
            id=rid, model=body.model, choices=choices,
            usage=oai.Usage(
                prompt_tokens=len(pre.token_ids),
                completion_tokens=total_out,
                total_tokens=len(pre.token_ids) + total_out))
        return web.json_response(resp.model_dump(exclude_none=True))

    async def _stream_chat(self, request, handle, body, pre, rid):
        # Streaming tool calls (VERDICT r5 #8 — r5 was unary-only): one
        # incremental parser per choice turns content deltas into
        # OpenAI-spec `delta.tool_calls` fragments; the final chunk's
        # finish_reason flips to "tool_calls" when any call was emitted.
        use_tools = bool(body.tools) and body.tool_choice != "none"
        parsers = {}
        if use_tools:
            from dynamo_tpu.llm.postprocessor import (
                StreamingToolCallParser,
                forced_tool_name,
            )

            forced = forced_tool_name(body.tool_choice, body.tools)
            parsers = {i: StreamingToolCallParser(body.tool_call_parser,
                                                  forced_name=forced)
                       for i in range(body.n)}

        def _logprobs(lps):
            if not lps:
                return None
            return oai.ChatLogprobs(content=[
                oai.ChatLogprobEntry(
                    token=handle.tokenizer.decode([t]), logprob=lp)
                for t, lp in lps])

        def _chunk(i, delta, finish=None, lps=None):
            return oai.ChatCompletionChunk(
                id=rid, model=body.model,
                choices=[oai.ChatStreamChoice(
                    index=i, delta=delta, finish_reason=finish,
                    logprobs=_logprobs(lps))])

        def make_chunk(i, out, lps):
            if not use_tools:
                return [_chunk(
                    i, oai.ChatChoiceDelta(content=out.text or None),
                    out.finish_reason, lps)]
            p = parsers[i]
            content, deltas = p.push(out.text) if out.text else ("", [])
            finish = None
            if out.finished:
                fcontent, fdeltas, any_calls = p.finish()
                content += fcontent
                deltas = deltas + fdeltas
                finish = "tool_calls" if any_calls else out.finish_reason
            chunks = []
            if content:
                chunks.append(_chunk(
                    i, oai.ChatChoiceDelta(content=content)))
            for d in deltas:
                chunks.append(_chunk(
                    i, oai.ChatChoiceDelta(tool_calls=[d])))
            if out.finished:
                chunks.append(_chunk(i, oai.ChatChoiceDelta(), finish))
            # Logprobs ride the first chunk of the batch; while the
            # parser buffers (no chunk emitted) they'd be dropped, so
            # pin them to a bare chunk instead.
            if lps:
                if chunks:
                    chunks[0].choices[0].logprobs = _logprobs(lps)
                else:
                    chunks.append(_chunk(
                        i, oai.ChatChoiceDelta(), lps=lps))
            return chunks

        def make_usage_chunk(usage):
            return oai.ChatCompletionChunk(
                id=rid, model=body.model, choices=[], usage=usage)

        def head_chunk(i):
            # Leading chunk with the assistant role (OpenAI convention),
            # one per choice index.
            return oai.ChatCompletionChunk(
                id=rid, model=body.model,
                choices=[oai.ChatStreamChoice(
                    index=i,
                    delta=oai.ChatChoiceDelta(role="assistant",
                                              content=""))])

        return await self._stream_sse(request, handle, body, pre, rid,
                                      make_chunk, make_usage_chunk,
                                      head_chunk=head_chunk)

    async def _stream_sse(self, request, handle, body, pre, rid,
                          make_chunk, make_usage_chunk, head_chunk=None):
        """Shared SSE scaffolding for chat + text completion streams:
        metrics, disconnect-cancel, optional stream_options.include_usage
        final chunk, and the [DONE] sentinel.

        n > 1 multiplexes n engine streams into the one SSE stream with
        per-choice `index` (the reference streams everything internally
        and folds for unary, `http/service/openai.rs:222-226`; r3
        rejected stream+n>1 with a 400).  `make_chunk(i, out, lps)`
        stamps the choice index and returns the LIST of chunks one
        TextDelta expands to (content, tool-call fragments, finish).
        Choice 0 starts first; siblings launch at its first token so
        they prefix-hit the sealed prompt blocks.
        """
        start = time.monotonic()
        self.metrics.requests_total.inc(labels={"model": body.model})
        self.metrics.requests_in_flight.add(1, labels={"model": body.model})
        response = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await response.prepare(request)

        clones = self._fan_out(pre, body.n)
        dets = [StreamDetokenizer(handle.tokenizer, pre.stop_sequences)
                for _ in clones]
        want_lp = bool(pre.sampling.logprobs)
        queue: asyncio.Queue = asyncio.Queue()
        sealed = asyncio.Event()

        async def pump(i, clone):
            try:
                if i:
                    await sealed.wait()
                st = start if i == 0 else time.monotonic()
                lp_sink = [] if want_lp else None
                sent = 0
                async for out in self._token_stream(
                        handle, clone, dets[i], body.model, st,
                        lp_sink=lp_sink, observe_queue_wait=(i == 0)):
                    sealed.set()
                    lps = []
                    if lp_sink is not None:
                        lps, sent = lp_sink[sent:], len(lp_sink)
                    await queue.put(("chunk", i, out, lps))
                    if out.finished:
                        break
            except BaseException as e:
                await queue.put(("error", i, e, None))
                raise
            finally:
                sealed.set()
                await queue.put(("done", i, None, None))

        tasks = [asyncio.create_task(pump(i, c))
                 for i, c in enumerate(clones)]
        try:
            if head_chunk is not None:
                for i in range(len(clones)):
                    await response.write(
                        oai.sse_encode(head_chunk(i)).encode())
            remaining = len(clones)
            while remaining:
                # Coalesce every READY chunk into one socket write: at
                # high token rates the queue backs up while a write
                # drains, and one syscall per token-delta was a top-2
                # cost in frontend_bench (the reason the reference keeps
                # this loop in Rust, SURVEY §2.4.2).
                batch = [await queue.get()]
                while True:
                    try:
                        batch.append(queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                buf = []
                for kind, i, out, lps in batch:
                    if kind == "done":
                        remaining -= 1
                    elif kind == "error":
                        raise out
                    else:
                        # One TextDelta can fan out to several SSE chunks
                        # (content + tool_call fragments + finish).
                        buf.extend(oai.sse_encode(ch).encode()
                                   for ch in make_chunk(i, out, lps))
                if buf:
                    await response.write(b"".join(buf))
            if (body.stream_options or {}).get("include_usage"):
                n_in = len(pre.token_ids)
                total_out = sum(d.completion_tokens for d in dets)
                usage = oai.Usage(
                    prompt_tokens=n_in,
                    completion_tokens=total_out,
                    total_tokens=n_in + total_out)
                await response.write(
                    oai.sse_encode(make_usage_chunk(usage)).encode())
            await response.write(oai.SSE_DONE.encode())
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: closing the generator cancels the engine
            # request (reference disconnect.rs semantics).
            logger.info("client disconnected: %s", rid)
            raise
        finally:
            for t in tasks:
                t.cancel()
            # Retrieve every task's outcome: a second sibling error after
            # the first was raised would otherwise log "Task exception was
            # never retrieved" on every multi-choice failure.
            await asyncio.gather(*tasks, return_exceptions=True)
            self.metrics.requests_in_flight.add(-1, labels={"model": body.model})
            self._observe_done(body.model, start, len(pre.token_ids),
                               sum(d.completion_tokens for d in dets))
        await response.write_eof()
        return response

    def _observe_done(self, model, start_ts, in_tokens, out_tokens):
        labels = {"model": model}
        self.metrics.request_duration.observe(
            time.monotonic() - start_ts, labels=labels)
        self.metrics.input_tokens.observe(in_tokens, labels=labels)
        self.metrics.output_tokens.observe(out_tokens, labels=labels)
