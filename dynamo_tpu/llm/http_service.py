"""OpenAI-compatible HTTP service (aiohttp).

Role of the reference's axum server (`lib/llm/src/http/service/openai.rs`):
/v1/chat/completions, /v1/completions, /v1/models with SSE streaming,
client-disconnect cancellation (`disconnect.rs` — here: the request
generator is closed when aiohttp detects the peer went away, which
cancels the engine request), request metrics incl. TTFT/ITL histograms
(`metrics.rs`), /metrics exposition, and /health & /live endpoints
(reference `system_status_server.rs`).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from aiohttp import web

from dynamo_tpu.engine.scheduler import FinishReason
from dynamo_tpu.llm.backend import StreamDetokenizer, wire_finish_reason
from dynamo_tpu.llm.protocols import openai as oai
from dynamo_tpu.llm.service import ModelHandle, ModelManager
from dynamo_tpu.runtime.metrics import FrontendMetrics, MetricsRegistry

logger = logging.getLogger(__name__)


class HttpService:
    def __init__(
        self,
        models: ModelManager,
        registry: Optional[MetricsRegistry] = None,
    ) -> None:
        self.models = models
        self.registry = registry or MetricsRegistry()
        self.metrics = FrontendMetrics(self.registry)
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self.chat_completions)
        self.app.router.add_post("/v1/completions", self.completions)
        self.app.router.add_get("/v1/models", self.list_models)
        self.app.router.add_get("/metrics", self.prometheus)
        self.app.router.add_get("/health", self.health)
        self.app.router.add_get("/live", self.live)
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    # -- lifecycle --------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Bind and serve; returns the bound port (0 → ephemeral)."""
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        logger.info("HTTP service on %s:%s", host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _error(status: int, message: str, type_: str = "invalid_request_error"):
        body = oai.ErrorResponse(
            error=oai.ErrorDetail(message=message, type=type_))
        return web.json_response(body.model_dump(exclude_none=True),
                                 status=status)

    def _lookup(self, model: str) -> Optional[ModelHandle]:
        return self.models.get(model)

    # -- routes -----------------------------------------------------------

    async def health(self, _req: web.Request) -> web.Response:
        ready = len(self.models) > 0
        return web.json_response(
            {"status": "ready" if ready else "starting",
             "models": self.models.names()},
            status=200 if ready else 503)

    async def live(self, _req: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def prometheus(self, _req: web.Request) -> web.Response:
        return web.Response(text=self.registry.expose(),
                            content_type="text/plain")

    async def list_models(self, _req: web.Request) -> web.Response:
        listing = oai.ModelList(
            data=[oai.ModelInfo(id=n) for n in self.models.names()])
        return web.json_response(listing.model_dump())

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = oai.ChatCompletionRequest.model_validate(await request.json())
        except Exception as e:
            return self._error(400, f"invalid request: {e}")
        handle = self._lookup(body.model)
        if handle is None:
            return self._error(404, f"model {body.model!r} not found",
                               "model_not_found")
        rid = oai.request_id("chatcmpl")
        try:
            pre = handle.preprocessor.preprocess_chat(body, rid)
        except ValueError as e:
            return self._error(400, str(e))
        if body.stream:
            return await self._stream_chat(request, handle, body, pre, rid)
        return await self._unary_chat(handle, body, pre, rid)

    async def completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = oai.CompletionRequest.model_validate(await request.json())
        except Exception as e:
            return self._error(400, f"invalid request: {e}")
        handle = self._lookup(body.model)
        if handle is None:
            return self._error(404, f"model {body.model!r} not found",
                               "model_not_found")
        rid = oai.request_id("cmpl")
        try:
            pre = handle.preprocessor.preprocess_completion(body, rid)
        except ValueError as e:
            return self._error(400, str(e))
        if body.stream:
            return await self._stream_completion(request, handle, body, pre,
                                                 rid)

        start = time.monotonic()
        self.metrics.requests_total.inc(labels={"model": body.model})
        self.metrics.requests_in_flight.add(1, labels={"model": body.model})
        det = StreamDetokenizer(handle.tokenizer, pre.stop_sequences)
        text_parts = []
        reason = None
        try:
            async for out in self._token_stream(handle, pre, det, body.model,
                                                start):
                text_parts.append(out.text)
                if out.finished:
                    reason = out.finish_reason
        finally:
            self.metrics.requests_in_flight.add(-1, labels={"model": body.model})
        self._observe_done(body.model, start, len(pre.token_ids),
                           det.completion_tokens)
        resp = oai.CompletionResponse(
            id=rid, model=body.model,
            choices=[oai.CompletionChoice(
                text="".join(text_parts), finish_reason=reason)],
            usage=oai.Usage(
                prompt_tokens=len(pre.token_ids),
                completion_tokens=det.completion_tokens,
                total_tokens=len(pre.token_ids) + det.completion_tokens))
        return web.json_response(resp.model_dump(exclude_none=True))

    async def _stream_completion(self, request, handle, body, pre, rid):
        """SSE stream of `text_completion` chunks (ADVICE r1: the unary-only
        handler broke OpenAI streaming clients)."""

        def make_chunk(out):
            return oai.CompletionResponse(
                id=rid, model=body.model,
                choices=[oai.CompletionChoice(
                    text=out.text or "", finish_reason=out.finish_reason)])

        def make_usage_chunk(usage):
            return oai.CompletionResponse(
                id=rid, model=body.model, choices=[], usage=usage)

        return await self._stream_sse(request, handle, body, pre, rid,
                                      make_chunk, make_usage_chunk)

    # -- chat serving internals -------------------------------------------

    async def _token_stream(self, handle, pre, det, model, start_ts):
        """Engine deltas → TextDeltas, with TTFT/ITL observation."""
        first = True
        last_t = None
        async for delta in handle.client.generate(pre):
            now = time.monotonic()
            if delta.token_ids:
                if first:
                    self.metrics.ttft.observe(now - start_ts,
                                              labels={"model": model})
                    first = False
                elif last_t is not None:
                    self.metrics.itl.observe(now - last_t,
                                             labels={"model": model})
                last_t = now
                out = det.push_tokens(delta.token_ids)
                if out.finished:      # stop string hit mid-stream
                    yield out
                    return
                if out.text:
                    yield out
            if delta.finished:
                yield det.finish(delta.finish_reason)
                return
        # Engine stream ended without a finished marker (worker died):
        yield det.finish(FinishReason.ERROR)

    async def _unary_chat(self, handle, body, pre, rid):
        start = time.monotonic()
        self.metrics.requests_total.inc(labels={"model": body.model})
        self.metrics.requests_in_flight.add(1, labels={"model": body.model})
        det = StreamDetokenizer(handle.tokenizer, pre.stop_sequences)
        parts, reason = [], None
        try:
            async for out in self._token_stream(handle, pre, det,
                                                body.model, start):
                parts.append(out.text)
                if out.finished:
                    reason = out.finish_reason
        finally:
            self.metrics.requests_in_flight.add(-1, labels={"model": body.model})
        self._observe_done(body.model, start, len(pre.token_ids),
                           det.completion_tokens)
        resp = oai.ChatCompletionResponse(
            id=rid, model=body.model,
            choices=[oai.ChatChoice(
                message=oai.ChatMessage(role="assistant",
                                        content="".join(parts)),
                finish_reason=reason)],
            usage=oai.Usage(
                prompt_tokens=len(pre.token_ids),
                completion_tokens=det.completion_tokens,
                total_tokens=len(pre.token_ids) + det.completion_tokens))
        return web.json_response(resp.model_dump(exclude_none=True))

    async def _stream_chat(self, request, handle, body, pre, rid):
        def make_chunk(out):
            return oai.ChatCompletionChunk(
                id=rid, model=body.model,
                choices=[oai.ChatStreamChoice(
                    delta=oai.ChatChoiceDelta(content=out.text or None),
                    finish_reason=out.finish_reason)])

        def make_usage_chunk(usage):
            return oai.ChatCompletionChunk(
                id=rid, model=body.model, choices=[], usage=usage)

        # Leading chunk with the assistant role (OpenAI convention).
        head = oai.ChatCompletionChunk(
            id=rid, model=body.model,
            choices=[oai.ChatStreamChoice(
                delta=oai.ChatChoiceDelta(role="assistant", content=""))])
        return await self._stream_sse(request, handle, body, pre, rid,
                                      make_chunk, make_usage_chunk,
                                      head_chunk=head)

    async def _stream_sse(self, request, handle, body, pre, rid,
                          make_chunk, make_usage_chunk, head_chunk=None):
        """Shared SSE scaffolding for chat + text completion streams:
        metrics, disconnect-cancel, optional stream_options.include_usage
        final chunk, and the [DONE] sentinel."""
        start = time.monotonic()
        self.metrics.requests_total.inc(labels={"model": body.model})
        self.metrics.requests_in_flight.add(1, labels={"model": body.model})
        response = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await response.prepare(request)

        det = StreamDetokenizer(handle.tokenizer, pre.stop_sequences)
        try:
            if head_chunk is not None:
                await response.write(oai.sse_encode(head_chunk).encode())
            async for out in self._token_stream(handle, pre, det,
                                                body.model, start):
                await response.write(oai.sse_encode(make_chunk(out)).encode())
                if out.finished:
                    break
            if (body.stream_options or {}).get("include_usage"):
                n_in = len(pre.token_ids)
                usage = oai.Usage(
                    prompt_tokens=n_in,
                    completion_tokens=det.completion_tokens,
                    total_tokens=n_in + det.completion_tokens)
                await response.write(
                    oai.sse_encode(make_usage_chunk(usage)).encode())
            await response.write(oai.SSE_DONE.encode())
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: closing the generator cancels the engine
            # request (reference disconnect.rs semantics).
            logger.info("client disconnected: %s", rid)
            raise
        finally:
            self.metrics.requests_in_flight.add(-1, labels={"model": body.model})
            self._observe_done(body.model, start, len(pre.token_ids),
                               det.completion_tokens)
        await response.write_eof()
        return response

    def _observe_done(self, model, start_ts, in_tokens, out_tokens):
        labels = {"model": model}
        self.metrics.request_duration.observe(
            time.monotonic() - start_ts, labels=labels)
        self.metrics.input_tokens.observe(in_tokens, labels=labels)
        self.metrics.output_tokens.observe(out_tokens, labels=labels)
