"""KV-cache-aware routing.

Mirrors the reference's `lib/llm/src/kv_router/` capability set
(SURVEY.md §2.2): a radix index of block sequence-hashes → per-worker
residency fed by KV events, an overlap-scoring worker selector with
softmax sampling, router-local active-sequence load tracking, and a
TTL-based approximate indexer for engines that do not emit KV events.
"""

from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores, RadixTree
from dynamo_tpu.llm.kv_router.protocols import (
    KvCacheEvent,
    KvCacheEventData,
    RouterEvent,
    WorkerId,
)
from dynamo_tpu.llm.kv_router.router import KvRouter, KvRouterConfig
from dynamo_tpu.llm.kv_router.scheduler import DefaultWorkerSelector, KVHitRateEvent
from dynamo_tpu.llm.kv_router.sequence import ActiveSequences, ActiveSequencesMultiWorker

__all__ = [
    "ActiveSequences",
    "ActiveSequencesMultiWorker",
    "DefaultWorkerSelector",
    "KVHitRateEvent",
    "KvCacheEvent",
    "KvCacheEventData",
    "KvIndexer",
    "KvRouter",
    "KvRouterConfig",
    "OverlapScores",
    "RadixTree",
    "RouterEvent",
    "WorkerId",
]
