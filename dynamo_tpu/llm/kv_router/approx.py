"""Approximate KV residency for engines that do not emit KV events.

Role of the reference's `lib/llm/src/kv_router/approx.rs` (ApproxKvIndexer
:166): when a request is routed to a worker, *assume* that worker will hold
the request's prefix blocks for a TTL (default 120 s, refreshed on re-use),
and score future requests against those assumptions.  Strictly optimistic —
it never learns about evictions — which is why it is a fallback, not the
default.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dynamo_tpu.llm.kv_router.indexer import OverlapScores
from dynamo_tpu.llm.kv_router.protocols import WorkerId

DEFAULT_TTL_SECS = 120.0


class ApproxKvIndexer:
    """TTL-decayed assumed residency, indexed hash-first for O(prefix)
    lookups: block_hash → {worker: expiry}."""

    def __init__(self, block_size: int = 64, ttl_secs: float = DEFAULT_TTL_SECS) -> None:
        self.block_size = block_size
        self.ttl_secs = ttl_secs
        self._lock = threading.Lock()
        self._by_hash: Dict[int, Dict[WorkerId, float]] = {}
        self._heap: List[Tuple[float, WorkerId, int]] = []  # lazy-deleted min-heap

    def _now(self) -> float:
        return time.monotonic()

    def process_routing_decision(
        self, worker: WorkerId, sequence_hashes: Sequence[int]
    ) -> None:
        """Record that `worker` will (presumably) cache these prefix blocks."""
        now = self._now()
        exp = now + self.ttl_secs
        with self._lock:
            self._expire(now)
            for h in sequence_hashes:
                self._by_hash.setdefault(h, {})[worker] = exp
                heapq.heappush(self._heap, (exp, worker, h))

    def find_matches(self, sequence_hashes: Sequence[int]) -> OverlapScores:
        now = self._now()
        scores: Dict[WorkerId, int] = {}
        with self._lock:
            self._expire(now)
            active: Optional[Set[WorkerId]] = None
            for depth, h in enumerate(sequence_hashes, start=1):
                entry = self._by_hash.get(h)
                if not entry:
                    break
                holders = {w for w, exp in entry.items() if exp > now}
                if active is not None:
                    holders &= active
                if not holders:
                    break
                for w in holders:
                    scores[w] = depth
                active = holders
        return OverlapScores(scores=scores)

    def remove_worker(self, worker: WorkerId) -> None:
        with self._lock:
            empty = []
            for h, entry in self._by_hash.items():
                entry.pop(worker, None)
                if not entry:
                    empty.append(h)
            for h in empty:
                del self._by_hash[h]

    def _expire(self, now: float) -> None:
        while self._heap and self._heap[0][0] <= now:
            _, w, h = heapq.heappop(self._heap)
            entry = self._by_hash.get(h)
            if entry is not None:
                exp = entry.get(w)
                if exp is not None and exp <= now:
                    del entry[w]
                    if not entry:
                        del self._by_hash[h]
