"""KV-aware routed engine client: the frontend side of KV routing.

Ties the router core (indexer + selector + active sequences, this package)
into the serving path, playing the reference's `KvPushRouter`
(`kv_router.rs:304`) role:

- subscribes to the `kv_events` subject on the control plane and feeds the
  RadixTree indexer (reference: NATS kv_events → `KvIndexer` event loop);
- on every request, scores live instances (prefix overlap + decode/prefill
  load) and dispatches *direct* to the chosen worker;
- tracks in-flight state (ActiveSequencesMultiWorker) — prefill complete on
  first token, per-token block growth, free on finish;
- removes workers from the index when their instances vanish.

Composes under MigrationClient: a retried generate() re-routes, and the
dead worker has already been dropped from the instance set by its lease.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Optional

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.llm.kv_router.protocols import RouterEvent
from dynamo_tpu.llm.kv_router.router import KvRouter, KvRouterConfig
from dynamo_tpu.llm.kv_router.watcher import LoadMetricsWatcher
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime import tracing

logger = logging.getLogger(__name__)

KV_EVENTS_SUBJECT = "kv_events"
HIT_RATE_SUBJECT = "kv_hit_rate"
ACTIVE_SEQS_SUBJECT = "active_seqs"  # reference kv_router.rs:63


class KvRoutedEngineClient:
    """EngineClient with KV-cache-aware worker selection."""

    def __init__(self, client, runtime, block_size: int = 64,
                 config: Optional[KvRouterConfig] = None,
                 registry=None) -> None:
        from dynamo_tpu.llm.discovery import delta_from_wire, request_to_wire

        self._to_wire = request_to_wire
        self._from_wire = delta_from_wire
        self.client = client          # runtime Client (instance watcher)
        self.runtime = runtime
        # Hit-rate events ride pub/sub to the namespace aggregator
        # (reference KVHitRateEvent → `components/metrics`).
        self.router = KvRouter(config or KvRouterConfig(block_size=block_size),
                               on_hit_rate_event=self._queue_hit_rate_event)
        self._event_task: Optional[asyncio.Task] = None
        self._sub = None
        # Worker-published ForwardPassMetrics, merged into selection cost
        # (r2 published these every second and routed on none of it).
        self._metrics = LoadMetricsWatcher(runtime.cp, name="kv-router")
        # Replica sync: other frontends' routing decisions fold into our
        # optimistic accounting under a namespaced request key (reference
        # ACTIVE_SEQUENCES_SUBJECT replica sync, kv_router.rs:62-63).
        import uuid as _uuid

        self._router_id = _uuid.uuid4().hex[:12]
        self._seq_sub = None
        self._seq_task: Optional[asyncio.Task] = None
        # Penalty box: workers that just failed a connection are excluded
        # from routing until their lease expires or the TTL passes —
        # otherwise the highest-overlap (dead) worker would be re-chosen on
        # every migration retry (reference PushRouter fault detection,
        # `push_router.rs:168`).
        self._penalty: dict = {}
        self._penalty_ttl = 3.0
        self._last_decision = None  # last KVHitRateEvent (routing spans)
        # Fleet prefix reuse: requests routed with a remote-prefix hint
        # attached (the donor side of block_manager/prefix_share.py).
        # Plain int always; a Prometheus counter too when the frontend
        # hands us its registry (runtime/metrics.MetricsRegistry).
        self.remote_hint_routes = 0
        self._remote_routes_counter = (
            registry.counter(
                "router_remote_prefix_routes_total",
                "Requests routed with a remote-prefix donor hint")
            if registry is not None else None)

    async def start(self) -> None:
        self._sub = await self.runtime.cp.subscribe(KV_EVENTS_SUBJECT)
        self._event_task = asyncio.create_task(self._pump_events())
        await self._metrics.start()
        if self.router.config.replica_sync:
            self._seq_sub = await self.runtime.cp.subscribe(
                ACTIVE_SEQS_SUBJECT)
            self._seq_task = asyncio.create_task(self._pump_active_seqs())

    async def stop(self) -> None:
        for sub in (self._sub, self._seq_sub):
            if sub:
                sub.cancel()
        for task in (self._event_task, self._seq_task):
            if task:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        await self._metrics.stop()

    # -- replica sync ------------------------------------------------------

    def _publish_seq(self, kind: str, request_id: str, **fields) -> None:
        if not self.router.config.replica_sync:
            return

        async def pub():
            # One retry (ADVICE r3): a dropped 'free' leaves a phantom
            # reservation on peer routers skewing placement until the
            # 900 s expire sweep; still best-effort after that — local
            # accounting holds either way.
            for attempt in (0, 1):
                try:
                    await self.runtime.cp.publish(ACTIVE_SEQS_SUBJECT, {
                        "router": self._router_id, "kind": kind,
                        "request_id": request_id, **fields})
                    return
                except Exception:
                    if attempt == 0:
                        await asyncio.sleep(0.2)

        try:
            asyncio.get_running_loop().create_task(pub())
        except RuntimeError:
            pass

    async def _pump_active_seqs(self) -> None:
        import time

        last_sweep = time.monotonic()
        backoff = 1.0
        while True:
            try:
                msg = await asyncio.wait_for(self._seq_sub.next(),
                                             timeout=30.0)
                backoff = 1.0
            except asyncio.TimeoutError:
                msg = None
            except asyncio.CancelledError:
                raise
            except ConnectionError:
                # ADVICE r3: don't go silently dark until restart.  The
                # control-plane client reconnects and restores this SAME
                # subscription; just keep draining after a pause.
                logger.warning("active_seqs subscription lost; waiting "
                               "%.0fs for reconnect", backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)
                continue
            # Periodic leak sweep: a remote router SIGKILLed between its
            # "add" and "free" would otherwise reserve phantom load
            # forever (ActiveSequences.expire_older_than exists for
            # exactly this).  The TTL comfortably exceeds any real
            # stream; local entries also freed by generate()'s finally.
            now = time.monotonic()
            if now - last_sweep > 60.0:
                last_sweep = now
                dropped = self.router.active.expire_older_than(900.0)
                if dropped:
                    logger.warning("expired %d leaked sequence "
                                   "reservations", dropped)
            if msg is None:
                continue
            if msg.get("router") == self._router_id:
                continue  # own echo
            try:
                key = f"{msg['router']}:{msg['request_id']}"
                kind = msg["kind"]
                if kind == "add":
                    self.router.active.add_request(
                        key, msg["worker"], msg["isl"], msg["overlap"],
                        expected_output_tokens=msg.get("expected", 0))
                elif kind == "prefill":
                    self.router.active.mark_prefill_complete(key)
                elif kind == "free":
                    self.router.active.free(key)
            except Exception:
                logger.exception("bad active_seqs payload")

    def _queue_hit_rate_event(self, ev) -> None:
        # Sync callback from the selector: publish fire-and-forget — a
        # telemetry publish must never add a control-plane round trip (or
        # its failures) to the request hot path.
        self._last_decision = ev  # routing-span attrs (cost, candidates)
        async def pub():
            try:
                await self.runtime.cp.publish(HIT_RATE_SUBJECT, {
                    "worker_id": ev.worker_id,
                    "isl_blocks": ev.isl_blocks,
                    "overlap_blocks": ev.overlap_blocks,
                })
            except Exception:
                # dynamo-lint: disable=DL003 best-effort metrics publish
                pass  # observability must not tax the request hot path

        try:
            asyncio.get_running_loop().create_task(pub())
        except RuntimeError:
            pass  # no loop (sync tests): drop

    async def _pump_events(self) -> None:
        backoff = 1.0
        while True:
            try:
                payload = await self._sub.next()
                backoff = 1.0
            except asyncio.CancelledError:
                raise
            except ConnectionError:
                # ADVICE r3: a frozen index silently degrades routing
                # until restart.  The control-plane client reconnects and
                # restores this SAME subscription; keep draining.
                logger.warning("kv_events subscription lost; waiting "
                               "%.0fs for reconnect", backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)
                continue
            try:
                self.router.apply_event(RouterEvent.from_dict(payload))
            except Exception:
                logger.exception("bad kv event payload")

    def _sync_workers(self) -> list:
        """Reconcile the router's worker set with live instances."""
        import time

        live = self.client.instance_ids()
        known = self.router.workers()
        for w in known:
            if w not in live:
                self.router.remove_worker(w)
        now = time.monotonic()
        self._penalty = {w: t for w, t in self._penalty.items() if t > now}
        healthy = [w for w in live if w not in self._penalty]
        return healthy or live  # all penalised → try anyway

    @staticmethod
    def _request_priority(request) -> Optional[int]:
        """QoS class from the request's annotations (the http frontend's
        `x-dynamo-priority` header lands there) — the selector biases
        interactive traffic away from deep queues."""
        from dynamo_tpu.llm.service import PRIORITY_ANNOTATION, priority_of

        if PRIORITY_ANNOTATION not in getattr(request, "annotations", {}):
            return None  # unannotated: keep the topology-blind cost
        return priority_of(request)

    def _worker_slices(self) -> dict:
        """Published SliceSpec per live instance (instance-record
        metadata, `fleet.topology`): the selector's HBM-capacity
        weighting and the donor pick's fabric-reachability read.
        Workers predating the topology plane map to None."""
        from dynamo_tpu.fleet.topology import SliceSpec

        return {
            i.instance_id: SliceSpec.from_dict(i.metadata.get("slice"))
            for i in self.client.instances()
        }

    async def embed(self, token_lists):
        from dynamo_tpu.llm.discovery import RemoteEngineClient

        return await RemoteEngineClient(self.client).embed(token_lists)

    async def clear_kv_blocks(self) -> int:
        from dynamo_tpu.llm.discovery import RemoteEngineClient

        return await RemoteEngineClient(self.client).clear_kv_blocks()

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        import time as _time

        from dynamo_tpu.runtime import ledger as ledger_mod

        workers = self._sync_workers()
        led = ledger_mod.ledger_of(request)
        route_t0 = _time.monotonic()
        # Routing-decision span: which worker won, the prefix overlap it
        # won on, and the selector's cost/candidate count — the
        # "why was this request placed here" record in the merged trace.
        route_span = tracing.get_tracer().start_span(
            "router.select", attrs={"request_id": request.request_id})
        try:
            worker_id, overlap = self.router.find_best_match(
                request.request_id, request.token_ids, workers,
                expected_output_tokens=request.sampling.max_tokens,
                metrics=self._metrics.fresh(),
                priority=self._request_priority(request),
                slices=self._worker_slices())
        except BaseException as e:
            # No candidates / selector failure: the span must still end,
            # or an empty fleet leaks one open span per rejected request.
            route_span.end(error=type(e).__name__)
            raise
        ev = self._last_decision
        donor = self.router.last_donor
        donor_id = None
        # Always clear first: a migration RETRY reuses the same request
        # object (shared annotations dict), and a stale hint from the
        # previous attempt could point at a donor that has since died.
        from dynamo_tpu.llm.block_manager.prefix_share import HINT_ANNOTATION

        request.annotations.pop(HINT_ANNOTATION, None)
        if donor is not None:
            # Fleet prefix reuse: the selected worker's overlap is poor
            # but this live peer holds a deep prefix — tell the worker
            # where to pull it from (address from the instance record;
            # a donor that just vanished simply attaches no hint).
            addr = next((i.address for i in self.client.instances()
                         if i.instance_id == donor.worker_id), None)
            if addr:
                from dynamo_tpu.llm.block_manager.prefix_share import (
                    attach_hint)

                attach_hint(
                    request, addr,
                    donor.overlap_blocks * self.router.config.block_size,
                    donor.worker_id)
                donor_id = donor.worker_id
                self.remote_hint_routes += 1
                if self._remote_routes_counter is not None:
                    self._remote_routes_counter.inc()
        route_span.end(
            worker=int(worker_id), overlap_blocks=int(overlap),
            candidates=(ev.candidates if ev is not None else len(workers)),
            cost=(round(ev.cost, 3) if ev is not None else None),
            remote_prefix_donor=donor_id)
        if led is not None:
            attrs = {"worker": int(worker_id),
                     "overlap_blocks": int(overlap)}
            if donor_id is not None:
                attrs["donor"] = int(donor_id)
            led.stamp("route", dur=_time.monotonic() - route_t0, **attrs)
        logger.debug("kv-routed %s → worker %s (overlap %d blocks)",
                     request.request_id, worker_id, overlap)
        self._publish_seq("add", request.request_id, worker=worker_id,
                          isl=len(request.token_ids), overlap=overlap,
                          expected=request.sampling.max_tokens)
        first = True
        try:
            async for d in self.client.direct(self._to_wire(request),
                                              worker_id):
                delta = self._from_wire(d)
                delta.request_id = request.request_id
                ledger_mod.absorb_delta(request, delta, where="kv_router")
                if delta.token_ids:
                    if first:
                        self.router.mark_prefill_complete(request.request_id)
                        self._publish_seq("prefill", request.request_id)
                        first = False
                    self.router.push_token(request.request_id,
                                           len(delta.token_ids))
                yield delta
        except ConnectionError:
            import time

            self._penalty[worker_id] = time.monotonic() + self._penalty_ttl
            raise
        finally:
            self.router.free(request.request_id)
            self._publish_seq("free", request.request_id)
