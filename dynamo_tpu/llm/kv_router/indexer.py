"""Radix index of KV-block residency: sequence-hash → which workers hold it.

Role of the reference's `lib/llm/src/kv_router/indexer.rs` (RadixTree :222,
KvIndexer :641, find_matches :274, OverlapScores :520).

Because block hashes are *chained* (a hash commits to its whole prefix —
see dynamo_tpu.tokens), the prefix tree can be stored flat: a map
block_hash → {workers}.  Matching a request is walking its sequence hashes
in order and intersecting with the shrinking set of workers that still
match; no trie traversal needed.  Parent links are kept only for eviction
bookkeeping and diagnostics.

Event ordering: events are applied per-worker in `event_id` order; stale or
duplicate events (e.g. re-delivered after worker restart) are dropped with a
counter rather than corrupting the index.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

from dynamo_tpu.llm.kv_router.protocols import (
    KvEventKind,
    RouterEvent,
    WorkerId,
)

logger = logging.getLogger(__name__)


@dataclass
class OverlapScores:
    """Per-worker count of matched prefix blocks for one request
    (reference OverlapScores, `indexer.rs:520`).

    `scores[w] = n` means worker `w` holds the first `n` blocks of the
    request's block sequence (prefix overlap, not total overlap — only a
    cached *prefix* saves prefill work).
    """

    scores: Dict[WorkerId, int] = field(default_factory=dict)
    # Tokens known resident but on no worker queried (frequency data etc.)
    # reserved for future use.

    def best(self) -> int:
        return max(self.scores.values(), default=0)


class RadixTree:
    """Flat chained-hash index with per-worker reverse maps.

    Thread-compatible but not thread-safe; KvIndexer serializes access.
    """

    def __init__(self) -> None:
        # block_hash -> set of workers with the block resident
        self._residency: Dict[int, Set[WorkerId]] = defaultdict(set)
        # worker -> set of resident block hashes (for clear/remove-worker)
        self._worker_blocks: Dict[WorkerId, Set[int]] = defaultdict(set)

    # -- mutation ---------------------------------------------------------
    def store(self, worker: WorkerId, block_hashes: Sequence[int]) -> None:
        wb = self._worker_blocks[worker]
        for h in block_hashes:
            self._residency[h].add(worker)
            wb.add(h)

    def remove(self, worker: WorkerId, block_hashes: Sequence[int]) -> None:
        wb = self._worker_blocks.get(worker)
        if wb is None:
            return
        for h in block_hashes:
            wb.discard(h)
            ws = self._residency.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self._residency[h]

    def clear_worker(self, worker: WorkerId) -> None:
        """Remove every block attributed to `worker` (cache cleared, or the
        worker left the cluster)."""
        wb = self._worker_blocks.pop(worker, None)
        if not wb:
            return
        for h in wb:
            ws = self._residency.get(h)
            if ws is not None:
                ws.discard(worker)
                if not ws:
                    del self._residency[h]

    # -- queries ----------------------------------------------------------
    def find_matches(
        self, sequence_hashes: Sequence[int], early_exit: bool = False
    ) -> OverlapScores:
        """Prefix-overlap scores for a request's chained block hashes.

        Walks hashes in sequence order; a worker's score is the length of
        its *contiguous* matched prefix.  `early_exit` stops at the first
        depth where a single worker holds the full prefix so far and no
        other worker can catch up (used for latency-sensitive lookups).
        """
        scores: Dict[WorkerId, int] = {}
        active: Optional[Set[WorkerId]] = None  # None = all workers still eligible
        for depth, h in enumerate(sequence_hashes, start=1):
            holders = self._residency.get(h)
            if not holders:
                break
            matched = holders if active is None else (holders & active)
            if not matched:
                break
            for w in matched:
                scores[w] = depth
            active = set(matched)
            if early_exit and len(active) == 1:
                # The single remaining worker's score keeps growing only for
                # itself; deeper walk cannot change the *relative* ranking.
                remaining = sequence_hashes[depth:]
                w = next(iter(active))
                for h2 in remaining:
                    ws = self._residency.get(h2)
                    if not ws or w not in ws:
                        break
                    scores[w] += 1
                break
        return OverlapScores(scores=scores)

    def num_blocks(self) -> int:
        return len(self._residency)

    def workers(self) -> List[WorkerId]:
        return [w for w, b in self._worker_blocks.items() if b]

    def blocks_for_worker(self, worker: WorkerId) -> Set[int]:
        return set(self._worker_blocks.get(worker, ()))


class KvIndexer:
    """Serialized event-application front of the RadixTree
    (reference KvIndexer, `indexer.rs:641`: a single-threaded event loop).

    Synchronous core guarded by a lock — Python event volumes make a
    dedicated thread unnecessary — plus an asyncio-friendly `apply_queue`
    pump for transports that deliver events on a stream.
    """

    def __init__(self, block_size: int = 64) -> None:
        self.block_size = block_size
        self.tree = RadixTree()
        self._lock = threading.Lock()
        self._last_event_id: Dict[WorkerId, int] = {}
        self.stale_events_dropped = 0
        self.malformed_events = 0

    def apply_event(self, ev: RouterEvent) -> None:
        with self._lock:
            last = self._last_event_id.get(ev.worker_id)
            if last is not None and ev.event.event_id <= last:
                self.stale_events_dropped += 1
                logger.debug(
                    "dropping stale kv event %s from %s (last=%s)",
                    ev.event.event_id,
                    ev.worker_id,
                    last,
                )
                return
            # Validate *before* advancing the cursor so a malformed event can
            # be corrected and redelivered under the same event_id.
            data = ev.event.data
            if data.kind == KvEventKind.STORED and data.store is None:
                raise ValueError(f"stored event without store data: {ev}")
            if data.kind == KvEventKind.REMOVED and data.remove is None:
                raise ValueError(f"removed event without remove data: {ev}")
            self._last_event_id[ev.worker_id] = ev.event.event_id
            if data.kind == KvEventKind.STORED:
                self.tree.store(ev.worker_id, data.store.block_hashes)
            elif data.kind == KvEventKind.REMOVED:
                self.tree.remove(ev.worker_id, data.remove.block_hashes)
            elif data.kind == KvEventKind.CLEARED:
                self.tree.clear_worker(ev.worker_id)

    def remove_worker(self, worker: WorkerId) -> None:
        """Worker left (lease expired): forget its residency and event cursor."""
        with self._lock:
            self.tree.clear_worker(worker)
            self._last_event_id.pop(worker, None)

    def find_matches(self, sequence_hashes: Sequence[int]) -> OverlapScores:
        with self._lock:
            return self.tree.find_matches(sequence_hashes)

    async def pump(self, queue: "asyncio.Queue[RouterEvent]") -> None:
        """Drain RouterEvents from an asyncio queue until cancelled.

        A malformed event must not kill the ingestion loop (a dead pump means
        the index silently freezes while the router keeps consulting it), so
        apply failures are counted and logged, never propagated.
        """
        while True:
            ev = await queue.get()
            try:
                self.apply_event(ev)
            except Exception:
                self.malformed_events += 1
                # getattr: the event may be malformed at the object level
                # (wrong type entirely); touching .worker_id here must not
                # re-raise and kill the pump.
                logger.exception(
                    "dropping malformed router event from worker %s",
                    getattr(ev, "worker_id", repr(ev)),
                )


class KvIndexerSharded:
    """Worker-sharded indexer (reference `KvIndexerSharded`,
    `indexer.rs:856`): each worker's residency lives in its own KvIndexer
    shard keyed by `worker_id % n_shards`, so event application for
    different workers contends on different locks and a busy worker's
    event storm can't serialize behind the whole fleet's.

    Same surface as KvIndexer; `find_matches` merges per-shard overlap
    scores (each worker's score lives wholly in its own shard, so the
    merge is a plain dict union).
    """

    def __init__(self, block_size: int = 64, n_shards: int = 4) -> None:
        if n_shards <= 0:
            raise ValueError(f"n_shards must be positive, got {n_shards}")
        self.block_size = block_size
        self.shards = [KvIndexer(block_size) for _ in range(n_shards)]

    def _shard(self, worker: WorkerId) -> KvIndexer:
        return self.shards[hash(worker) % len(self.shards)]

    def apply_event(self, ev: RouterEvent) -> None:
        self._shard(ev.worker_id).apply_event(ev)

    def remove_worker(self, worker: WorkerId) -> None:
        self._shard(worker).remove_worker(worker)

    def find_matches(self, sequence_hashes: Sequence[int]) -> OverlapScores:
        merged = OverlapScores()
        for shard in self.shards:
            merged.scores.update(shard.find_matches(sequence_hashes).scores)
        return merged

    @property
    def stale_events_dropped(self) -> int:
        return sum(s.stale_events_dropped for s in self.shards)

    @property
    def tree(self):
        """Compatibility view for worker enumeration (`workers()`)."""
        class _Union:
            def __init__(self, shards):
                self._shards = shards

            def workers(self):
                out = []
                for s in self._shards:
                    out.extend(s.tree.workers())
                return out

        return _Union(self.shards)
