"""KV event and metric wire types.

Role of the reference's `lib/llm/src/kv_router/protocols.rs` (KvCacheEvent
stored/removed/cleared) and the `ForwardPassMetrics{WorkerStats, KvStats}`
surface of `publisher.rs:482` — the two feedback channels the router consumes:
*which blocks live where* (events) and *how loaded each worker is* (metrics).

Plain dataclasses with dict (msgpack/json-ready) codecs; no pydantic on this
hot path.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from enum import Enum
from typing import List, Optional, Sequence

WorkerId = str


class KvEventKind(str, Enum):
    STORED = "stored"
    REMOVED = "removed"
    CLEARED = "cleared"


@dataclass(frozen=True)
class KvCacheStoreData:
    """Blocks became resident on a worker.

    `block_hashes` are chained sequence hashes (see dynamo_tpu.tokens), in
    sequence order; `parent_hash` is the sequence hash of the block preceding
    block_hashes[0] (None = sequence start).
    """

    block_hashes: Sequence[int]
    parent_hash: Optional[int] = None
    token_counts: Optional[Sequence[int]] = None  # tokens per block, if partial tails matter


@dataclass(frozen=True)
class KvCacheRemoveData:
    block_hashes: Sequence[int]


@dataclass(frozen=True)
class KvCacheEventData:
    kind: KvEventKind
    store: Optional[KvCacheStoreData] = None
    remove: Optional[KvCacheRemoveData] = None

    @staticmethod
    def stored(block_hashes: Sequence[int], parent_hash: Optional[int] = None) -> "KvCacheEventData":
        return KvCacheEventData(KvEventKind.STORED, store=KvCacheStoreData(tuple(block_hashes), parent_hash))

    @staticmethod
    def removed(block_hashes: Sequence[int]) -> "KvCacheEventData":
        return KvCacheEventData(KvEventKind.REMOVED, remove=KvCacheRemoveData(tuple(block_hashes)))

    @staticmethod
    def cleared() -> "KvCacheEventData":
        return KvCacheEventData(KvEventKind.CLEARED)


@dataclass(frozen=True)
class KvCacheEvent:
    """One engine-side cache mutation, ordered per worker by `event_id`."""

    event_id: int
    data: KvCacheEventData


@dataclass(frozen=True)
class RouterEvent:
    """A KvCacheEvent attributed to its emitting worker (what the indexer consumes)."""

    worker_id: WorkerId
    event: KvCacheEvent

    def to_dict(self) -> dict:
        d = asdict(self)
        d["event"]["data"]["kind"] = self.event.data.kind.value
        return d

    @staticmethod
    def from_dict(d: dict) -> "RouterEvent":
        data = d["event"]["data"]
        kind = KvEventKind(data["kind"])
        store = KvCacheStoreData(**data["store"]) if data.get("store") else None
        remove = KvCacheRemoveData(**data["remove"]) if data.get("remove") else None
        return RouterEvent(
            worker_id=d["worker_id"],
            event=KvCacheEvent(
                event_id=d["event"]["event_id"],
                data=KvCacheEventData(kind, store=store, remove=remove),
            ),
        )


# ---------------------------------------------------------------------------
# Worker load metrics (the `load_metrics` stats surface)
# ---------------------------------------------------------------------------


@dataclass
class WorkerStats:
    request_active_slots: int = 0
    request_total_slots: int = 0
    num_requests_waiting: int = 0


@dataclass
class KvStats:
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    gpu_cache_usage_perc: float = 0.0  # name kept engine-agnostic: device cache usage
    gpu_prefix_cache_hit_rate: float = 0.0


@dataclass
class SpecDecodeStats:
    num_spec_tokens: int = 0
    num_drafts: int = 0
    num_accepted_tokens: int = 0
    num_accepted_tokens_per_pos: List[int] = field(default_factory=list)


@dataclass
class ForwardPassMetrics:
    """Per-forward-pass load snapshot published by every worker
    (reference `publisher.rs` ForwardPassMetrics).  `expert_load` carries
    the cumulative per-expert token-assignment counts for MoE engines
    (the expert-distribution surface of reference
    `sglang/common/base_handlers.py:40-62`); None for dense models.
    `moe_dropped_tokens` is the capacity-honesty counter: assignments a
    bounded `ModelConfig.moe_capacity` dropped (0 forever at the exact
    serving default — a nonzero value means the deployment explicitly
    traded exactness for dispatch-buffer size)."""

    worker_stats: WorkerStats = field(default_factory=WorkerStats)
    kv_stats: KvStats = field(default_factory=KvStats)
    spec_decode_stats: Optional[SpecDecodeStats] = None
    expert_load: Optional[List[int]] = None
    moe_dropped_tokens: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "ForwardPassMetrics":
        spec = d.get("spec_decode_stats")
        return ForwardPassMetrics(
            worker_stats=WorkerStats(**d.get("worker_stats", {})),
            kv_stats=KvStats(**d.get("kv_stats", {})),
            spec_decode_stats=SpecDecodeStats(**spec) if spec else None,
            expert_load=d.get("expert_load"),
            moe_dropped_tokens=d.get("moe_dropped_tokens", 0),
        )
