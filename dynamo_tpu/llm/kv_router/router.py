"""KvRouter: the routing decision engine.

Role of the reference's `lib/llm/src/kv_router.rs` (KvRouterConfig :76,
KvRouter :145): combine

  - overlap scores from the (exact or approximate) indexer,
  - router-local optimistic load (ActiveSequences),
  - the worker selector's cost/sampling policy,

into `find_best_match(request_id, tokens) -> (worker, overlap_blocks)`,
and keep the optimistic accounting in sync with the request lifecycle
(prefill done / token pushed / freed).

Transport-agnostic: candidate workers are provided by the caller (the
runtime's client watches instance liveness); KV events arrive via
`apply_event`.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dynamo_tpu.llm.kv_router.approx import ApproxKvIndexer
from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.llm.kv_router.protocols import RouterEvent, WorkerId
from dynamo_tpu.llm.kv_router.scheduler import (
    DefaultWorkerSelector,
    KVHitRateEvent,
    RemotePrefixHint,
    WorkerLoadSnapshot,
    pick_donor,
)
from dynamo_tpu.llm.kv_router.sequence import ActiveSequencesMultiWorker
from dynamo_tpu.tokens import compute_block_hashes

logger = logging.getLogger(__name__)


@dataclass
class KvRouterConfig:
    block_size: int = 64
    overlap_score_weight: float = 1.0
    temperature: float = 0.0
    # Exact indexer (engine emits KV events) vs TTL-based approximation.
    use_kv_events: bool = True
    approx_ttl_secs: float = 120.0
    # > 1 → worker-sharded indexer (reference KvIndexerSharded,
    # indexer.rs:856): per-worker event storms stop serializing the fleet.
    indexer_shards: int = 1
    # Publish/consume routing decisions across router replicas (reference
    # ACTIVE_SEQUENCES_SUBJECT, kv_router.rs:62-63) — needed once more
    # than one frontend routes the same workers.
    replica_sync: bool = True
    # Fleet-wide prefix reuse: when the chosen worker's overlap is poor
    # but a peer's is deep, attach a remote-prefix hint (donor address +
    # covered tokens) so the serving worker pulls the prefix
    # peer-to-peer instead of recomputing it (scheduler.pick_donor →
    # block_manager/prefix_share.py).
    remote_prefix_hints: bool = True
    remote_prefix_min_frac: float = 0.5    # donor must cover >= this
    remote_prefix_min_gain_blocks: int = 2  # donor - chosen overlap floor


class KvRouter:
    def __init__(
        self,
        config: Optional[KvRouterConfig] = None,
        on_hit_rate_event: Optional[Callable[[KVHitRateEvent], None]] = None,
    ) -> None:
        self.config = config or KvRouterConfig()
        if not self.config.use_kv_events:
            self.indexer = None
        elif self.config.indexer_shards > 1:
            from dynamo_tpu.llm.kv_router.indexer import KvIndexerSharded

            self.indexer = KvIndexerSharded(
                self.config.block_size, self.config.indexer_shards)
        else:
            self.indexer = KvIndexer(self.config.block_size)
        self.approx: Optional[ApproxKvIndexer] = (
            None
            if self.config.use_kv_events
            else ApproxKvIndexer(self.config.block_size, self.config.approx_ttl_secs)
        )
        self.active = ActiveSequencesMultiWorker(self.config.block_size)
        self.selector = DefaultWorkerSelector(
            overlap_score_weight=self.config.overlap_score_weight,
            temperature=self.config.temperature,
            on_hit_rate_event=on_hit_rate_event,
        )
        # Donor candidate of the LAST find_best_match (None when the
        # chosen worker's own overlap was fine, or hints are disabled).
        self.last_donor: Optional[RemotePrefixHint] = None

    def workers(self) -> List[WorkerId]:
        """Workers the router currently knows anything about (index
        residency or in-flight accounting)."""
        known = set(self.active.workers())
        if self.indexer:
            known.update(self.indexer.tree.workers())
        return sorted(known)

    # -- event ingestion --------------------------------------------------
    def apply_event(self, ev: RouterEvent) -> None:
        if self.indexer:
            self.indexer.apply_event(ev)

    def remove_worker(self, worker: WorkerId) -> None:
        if self.indexer:
            self.indexer.remove_worker(worker)
        if self.approx:
            self.approx.remove_worker(worker)
        self.active.remove_worker(worker)

    # -- routing ----------------------------------------------------------
    def find_best_match(
        self,
        request_id: str,
        token_ids: Sequence[int],
        workers: Sequence[WorkerId],
        update_states: bool = True,
        expected_output_tokens: int = 0,
        metrics: Optional[Dict[WorkerId, object]] = None,
        priority: Optional[int] = None,
        slices: Optional[Dict[WorkerId, object]] = None,
    ) -> Tuple[WorkerId, int]:
        """Choose a worker for the request; returns (worker, overlap_blocks).

        `workers` is the current live instance set.  When `update_states`
        the decision is recorded in the optimistic accounting (callers must
        later `free(request_id)`).  `expected_output_tokens` (e.g. the
        request's max_tokens) pre-reserves decode-growth blocks in that
        accounting so the selector sees future occupancy.

        `priority` (llm.service.priority_of) enables the QoS bias:
        interactive requests avoid over-threshold queues.  `slices` maps
        worker id → published SliceSpec (instance-record metadata) so the
        selector weighs per-slice HBM capacity and the donor pick prefers
        device-fabric-reachable peers; both default to the topology-blind
        behavior for fleets that publish nothing.
        """
        if not workers:
            raise ValueError("no live workers to route to")
        seq_hashes = compute_block_hashes(token_ids, self.config.block_size)
        request_blocks = (len(token_ids) + self.config.block_size - 1) // self.config.block_size

        if self.indexer:
            overlaps = self.indexer.find_matches(seq_hashes)
        elif self.approx:
            overlaps = self.approx.find_matches(seq_hashes)
        else:  # pragma: no cover
            raise RuntimeError("router has neither exact nor approximate indexer")

        bs = self.config.block_size
        decode_blocks = self.active.decode_blocks()
        prefill_tokens = self.active.prefill_tokens()
        candidates = [
            WorkerLoadSnapshot(
                worker_id=w,
                overlap_blocks=overlaps.scores.get(w, 0),
                decode_blocks=decode_blocks.get(w, 0),
                prefill_blocks=(prefill_tokens.get(w, 0) + bs - 1) // bs,
                metrics=(metrics or {}).get(w),
                slice=(slices or {}).get(w),
            )
            for w in workers
        ]
        try:
            chosen = self.selector.select(candidates, request_blocks,
                                          priority=priority)
        except TypeError:
            # Custom selectors predating the QoS surface keep working;
            # they just route priority-blind.
            chosen = self.selector.select(candidates, request_blocks)

        # Fleet prefix reuse: offer the deepest-overlap LIVE peer as a
        # donor when it beats the chosen worker's own prefix coverage.
        # Restricting scores to `workers` (the live instance set) plus
        # remove_worker's index purge keeps hints off dead donors.
        self.last_donor = None
        if self.config.remote_prefix_hints:
            live_scores = {w: overlaps.scores.get(w, 0) for w in workers}
            self.last_donor = pick_donor(
                live_scores, chosen.worker_id, chosen.overlap_blocks,
                request_blocks,
                min_donor_frac=self.config.remote_prefix_min_frac,
                min_gain_blocks=self.config.remote_prefix_min_gain_blocks,
                slices=slices, metrics=metrics)

        if update_states:
            self.active.add_request(
                request_id, chosen.worker_id, len(token_ids),
                chosen.overlap_blocks,
                expected_output_tokens=expected_output_tokens,
            )
            if self.approx:
                self.approx.process_routing_decision(chosen.worker_id, seq_hashes)
        return chosen.worker_id, chosen.overlap_blocks

    # -- request lifecycle ------------------------------------------------
    def mark_prefill_complete(self, request_id: str) -> None:
        self.active.mark_prefill_complete(request_id)

    def push_token(self, request_id: str, n: int = 1) -> None:
        self.active.push_token(request_id, n)

    def free(self, request_id: str) -> None:
        self.active.free(request_id)
