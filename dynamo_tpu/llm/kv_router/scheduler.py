"""Worker selection: overlap-aware cost with temperature sampling.

Role of the reference's `lib/llm/src/kv_router/scheduler.rs`
(DefaultWorkerSelector :321, cost formula :371-374, softmax_sample :248).

Cost per candidate worker:

    potential_prefill_blocks = request_blocks - overlap_blocks(worker)
    cost = overlap_score_weight * (potential_prefill_blocks
                                   + outstanding_prefill_blocks(worker))
           + decode_blocks(worker)

(outstanding_prefill_blocks = queued prefill work the router already sent to
that worker — same units, so a worker busy prefilling someone else's long
prompt is as unattractive as prefilling ours from scratch.)

Lower is better.  With temperature 0 the lowest-cost worker wins (random
tie-break); with temperature > 0 workers are sampled ∝ softmax(-cost / T),
which spreads load when costs are close and avoids herding every request at
the momentarily-cheapest worker.
"""

from __future__ import annotations

import logging
import math
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, WorkerId

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class KVHitRateEvent:
    """Emitted per routing decision for observability (reference
    `scheduler.rs:22`): how much of the request's prefix was already
    cached on the chosen worker, plus the decision's cost and candidate
    count so routing spans (runtime/tracing.py) can show WHY a worker
    won, not just which one."""

    worker_id: WorkerId
    isl_blocks: int
    overlap_blocks: int
    cost: float = 0.0
    candidates: int = 0


@dataclass(frozen=True)
class RemotePrefixHint:
    """A donor candidate for fleet-wide prefix reuse: `worker_id` holds
    the request's first `overlap_blocks` blocks (per the indexer's
    stored-block events).  The routing client turns this into the
    `remote_prefix` annotation — donor RPC address + covered-token
    high-water mark — that the serving worker's PrefixFetcher consumes
    (`block_manager/prefix_share.py`)."""

    worker_id: WorkerId
    overlap_blocks: int


def pick_donor(
    scores: Dict[WorkerId, int],
    chosen: WorkerId,
    chosen_overlap: int,
    request_blocks: int,
    *,
    min_donor_frac: float = 0.5,
    min_gain_blocks: int = 2,
) -> Optional[RemotePrefixHint]:
    """The remote-prefix donor decision: when the chosen worker's local
    overlap is poor but a peer's is deep, pulling the peer's sealed
    blocks beats recomputing them.

    A peer qualifies as donor when it covers at least `min_donor_frac`
    of the request's blocks AND beats the chosen worker's own overlap by
    at least `min_gain_blocks` (a 1-block gain isn't worth a pull RPC).
    Deepest overlap wins; EQUAL overlaps tie-break deterministically on
    worker id (ascending) so replica routers agree on the donor and
    tests are reproducible.  `scores` must already be restricted to
    LIVE workers — `KvIndexer.remove_worker` purges departed workers
    from the index, so hints never point at dead donors."""
    if request_blocks <= 0:
        return None

    def id_key(w):
        # Numeric ids compare numerically (lease ids are ints — worker 2
        # must beat worker 10), everything else lexically; the type tag
        # keeps mixed fleets deterministic.
        return (0, w, "") if isinstance(w, int) else (1, 0, str(w))

    floor = max(1, math.ceil(min_donor_frac * request_blocks))
    best: Optional[RemotePrefixHint] = None
    for w, ov in scores.items():
        if w == chosen:
            continue
        if ov < floor or ov - chosen_overlap < min_gain_blocks:
            continue
        if (best is None or ov > best.overlap_blocks
                or (ov == best.overlap_blocks
                    and id_key(w) < id_key(best.worker_id))):
            best = RemotePrefixHint(worker_id=w, overlap_blocks=ov)
    return best


@dataclass
class WorkerLoadSnapshot:
    """Candidate worker state at selection time: router-local optimistic
    accounting merged with the worker's last published metrics."""

    worker_id: WorkerId
    overlap_blocks: int = 0
    decode_blocks: int = 0
    prefill_blocks: int = 0  # outstanding prefill work already routed there
    metrics: Optional[ForwardPassMetrics] = None


def softmax_sample(
    costs: Dict[WorkerId, float],
    temperature: float,
    rng: Optional[random.Random] = None,
) -> WorkerId:
    """Sample a worker: argmin at T=0 (ties broken uniformly), else
    softmax over -cost/T."""
    if not costs:
        raise ValueError("no candidate workers")
    rng = rng or random
    if temperature <= 0.0:
        lo = min(costs.values())
        best = [w for w, c in costs.items() if c == lo]
        return rng.choice(best)
    # Stabilized softmax over negated costs.
    mx = max(-c / temperature for c in costs.values())
    weights = {w: math.exp(-c / temperature - mx) for w, c in costs.items()}
    total = sum(weights.values())
    r = rng.random() * total
    acc = 0.0
    for w, wt in weights.items():
        acc += wt
        if r <= acc:
            return w
    return next(reversed(weights))  # numeric fallthrough


class DefaultWorkerSelector:
    """The stock cost function; custom selectors implement the same
    `select(candidates, request_blocks) -> (worker, overlap)` surface
    (the reference exposes WorkerSelector for exactly this extension,
    `components/router/src/main.rs:27-44`)."""

    def __init__(
        self,
        overlap_score_weight: float = 1.0,
        temperature: float = 0.0,
        waiting_request_weight: float = 8.0,
        rng: Optional[random.Random] = None,
        on_hit_rate_event: Optional[Callable[[KVHitRateEvent], None]] = None,
    ) -> None:
        self.overlap_score_weight = overlap_score_weight
        self.temperature = temperature
        self.waiting_request_weight = waiting_request_weight
        self.rng = rng or random.Random()
        self.on_hit_rate_event = on_hit_rate_event

    def select(
        self,
        candidates: Sequence[WorkerLoadSnapshot],
        request_blocks: int,
    ) -> WorkerLoadSnapshot:
        if not candidates:
            raise ValueError("no candidate workers")
        costs: Dict[WorkerId, float] = {}
        by_id: Dict[WorkerId, WorkerLoadSnapshot] = {}
        for c in candidates:
            potential_prefill = max(0, request_blocks - c.overlap_blocks)
            # Decode load: router-local optimistic accounting merged with
            # the worker's last PUBLISHED stats (reference merges scraped
            # ForwardPassMetrics into routing via `scoring.rs`
            # ProcessedEndpoints).  max(): local accounting reacts
            # instantly to our own decisions; published truth covers load
            # this router never saw (other frontends, engine-internal
            # state) — r2 published these metrics and routed on neither.
            decode_load = c.decode_blocks
            waiting = 0
            if c.metrics is not None:
                decode_load = max(decode_load,
                                  c.metrics.kv_stats.kv_active_blocks)
                waiting = c.metrics.worker_stats.num_requests_waiting
            costs[c.worker_id] = (
                self.overlap_score_weight * (potential_prefill + c.prefill_blocks)
                + decode_load
                + self.waiting_request_weight * waiting
            )
            by_id[c.worker_id] = c
        chosen_id = softmax_sample(costs, self.temperature, self.rng)
        chosen = by_id[chosen_id]
        logger.debug(
            "selected worker %s cost=%.1f overlap=%d/%d blocks",
            chosen_id,
            costs[chosen_id],
            chosen.overlap_blocks,
            request_blocks,
        )
        if self.on_hit_rate_event:
            self.on_hit_rate_event(
                KVHitRateEvent(
                    worker_id=chosen_id,
                    isl_blocks=request_blocks,
                    overlap_blocks=min(chosen.overlap_blocks, request_blocks),
                    cost=costs[chosen_id],
                    candidates=len(costs),
                )
            )
        return chosen
