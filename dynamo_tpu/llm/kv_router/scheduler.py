"""Worker selection: overlap-aware cost with temperature sampling.

Role of the reference's `lib/llm/src/kv_router/scheduler.rs`
(DefaultWorkerSelector :321, cost formula :371-374, softmax_sample :248).

Cost per candidate worker:

    potential_prefill_blocks = request_blocks - overlap_blocks(worker)
    cost = overlap_score_weight * (potential_prefill_blocks
                                   + outstanding_prefill_blocks(worker))
           + decode_blocks(worker)

(outstanding_prefill_blocks = queued prefill work the router already sent to
that worker — same units, so a worker busy prefilling someone else's long
prompt is as unattractive as prefilling ours from scratch.)

Lower is better.  With temperature 0 the lowest-cost worker wins (random
tie-break); with temperature > 0 workers are sampled ∝ softmax(-cost / T),
which spreads load when costs are close and avoids herding every request at
the momentarily-cheapest worker.
"""

from __future__ import annotations

import logging
import math
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from dynamo_tpu.fleet.topology import (
    SliceSpec,
    donor_preference_key,
    free_hbm_bytes,
)
from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, WorkerId

logger = logging.getLogger(__name__)

# QoS routing bias (ISSUE 16 satellite): interactive-class requests
# (priority >= INTERACTIVE_PRIORITY, see llm.service.PRIORITY_CLASSES)
# avoid workers whose published waiting queue exceeds the threshold —
# a deep queue is head-of-line latency an interactive request must not
# eat for a few blocks of prefix overlap.  Best-effort and standard
# traffic keeps the plain cost (it FILLS the busy workers interactive
# traffic vacates).  When EVERY candidate is over the threshold the
# bias cancels out by construction — the degenerate all-busy fleet
# routes exactly as before rather than herding onto an arbitrary pick.
INTERACTIVE_PRIORITY = 2
QUEUE_DEPTH_THRESHOLD = 4
BUSY_QUEUE_PENALTY = 1024.0

# Slice-capacity weighting: decode load is normalized by the slice's
# total HBM relative to the largest candidate slice — 10 busy blocks on
# a v5e-1 decode cell mean more pressure than 10 on a v5p-16.  Clamped
# so a tiny or absurd published HBM figure cannot dominate the cost.
HBM_FACTOR_MIN = 0.25
HBM_FACTOR_MAX = 4.0


@dataclass(frozen=True)
class KVHitRateEvent:
    """Emitted per routing decision for observability (reference
    `scheduler.rs:22`): how much of the request's prefix was already
    cached on the chosen worker, plus the decision's cost and candidate
    count so routing spans (runtime/tracing.py) can show WHY a worker
    won, not just which one."""

    worker_id: WorkerId
    isl_blocks: int
    overlap_blocks: int
    cost: float = 0.0
    candidates: int = 0


@dataclass(frozen=True)
class RemotePrefixHint:
    """A donor candidate for fleet-wide prefix reuse: `worker_id` holds
    the request's first `overlap_blocks` blocks (per the indexer's
    stored-block events).  The routing client turns this into the
    `remote_prefix` annotation — donor RPC address + covered-token
    high-water mark — that the serving worker's PrefixFetcher consumes
    (`block_manager/prefix_share.py`)."""

    worker_id: WorkerId
    overlap_blocks: int


def pick_donor(
    scores: Dict[WorkerId, int],
    chosen: WorkerId,
    chosen_overlap: int,
    request_blocks: int,
    *,
    min_donor_frac: float = 0.5,
    min_gain_blocks: int = 2,
    slices: Optional[Dict[WorkerId, Optional[SliceSpec]]] = None,
    metrics: Optional[Dict[WorkerId, object]] = None,
) -> Optional[RemotePrefixHint]:
    """The remote-prefix donor decision: when the chosen worker's local
    overlap is poor but a peer's is deep, pulling the peer's sealed
    blocks beats recomputing them.

    A peer qualifies as donor when it covers at least `min_donor_frac`
    of the request's blocks AND beats the chosen worker's own overlap by
    at least `min_gain_blocks` (a 1-block gain isn't worth a pull RPC).
    Among qualifiers the preference is topology-aware
    (`fleet.topology.donor_preference_key`): a donor the CHOSEN worker
    can reach over the device fabric beats any host-wire-only one, then
    deepest coverage, then most free HBM (from the donor's published
    SliceSpec × its last metrics — an evicting donor may drop the
    blocks mid-pull), and exact ties break on the STABLE id key.  The
    old inline tie-break compared ids with a type-tagged tuple that
    ordered every int lease id before every string instance id, so a
    mixed fleet's replica routers could disagree on equal-overlap
    donors; `fleet.topology.stable_id_key` is now the one total order.
    `scores` must already be restricted to LIVE workers —
    `KvIndexer.remove_worker` purges departed workers from the index,
    so hints never point at dead donors."""
    if request_blocks <= 0:
        return None
    floor = max(1, math.ceil(min_donor_frac * request_blocks))
    puller_spec = (slices or {}).get(chosen)
    best: Optional[RemotePrefixHint] = None
    best_key = None
    for w, ov in scores.items():
        if w == chosen:
            continue
        if ov < floor or ov - chosen_overlap < min_gain_blocks:
            continue
        spec = (slices or {}).get(w)
        key = donor_preference_key(
            w, ov,
            reachable=bool(puller_spec is not None and spec is not None
                           and puller_spec.reachable(spec)),
            free_hbm=free_hbm_bytes(spec, (metrics or {}).get(w)))
        if best_key is None or key > best_key:
            best = RemotePrefixHint(worker_id=w, overlap_blocks=ov)
            best_key = key
    return best


@dataclass
class WorkerLoadSnapshot:
    """Candidate worker state at selection time: router-local optimistic
    accounting merged with the worker's last published metrics."""

    worker_id: WorkerId
    overlap_blocks: int = 0
    decode_blocks: int = 0
    prefill_blocks: int = 0  # outstanding prefill work already routed there
    metrics: Optional[ForwardPassMetrics] = None
    # Published slice topology (instance-record metadata), None for
    # workers predating the topology plane — every read degrades to the
    # topology-blind cost.
    slice: Optional[SliceSpec] = None


def softmax_sample(
    costs: Dict[WorkerId, float],
    temperature: float,
    rng: Optional[random.Random] = None,
) -> WorkerId:
    """Sample a worker: argmin at T=0 (ties broken uniformly), else
    softmax over -cost/T."""
    if not costs:
        raise ValueError("no candidate workers")
    rng = rng or random
    if temperature <= 0.0:
        lo = min(costs.values())
        best = [w for w, c in costs.items() if c == lo]
        return rng.choice(best)
    # Stabilized softmax over negated costs.
    mx = max(-c / temperature for c in costs.values())
    weights = {w: math.exp(-c / temperature - mx) for w, c in costs.items()}
    total = sum(weights.values())
    r = rng.random() * total
    acc = 0.0
    for w, wt in weights.items():
        acc += wt
        if r <= acc:
            return w
    return next(reversed(weights))  # numeric fallthrough


class DefaultWorkerSelector:
    """The stock cost function; custom selectors implement the same
    `select(candidates, request_blocks) -> (worker, overlap)` surface
    (the reference exposes WorkerSelector for exactly this extension,
    `components/router/src/main.rs:27-44`)."""

    def __init__(
        self,
        overlap_score_weight: float = 1.0,
        temperature: float = 0.0,
        waiting_request_weight: float = 8.0,
        rng: Optional[random.Random] = None,
        on_hit_rate_event: Optional[Callable[[KVHitRateEvent], None]] = None,
        queue_depth_threshold: int = QUEUE_DEPTH_THRESHOLD,
        busy_queue_penalty: float = BUSY_QUEUE_PENALTY,
    ) -> None:
        self.overlap_score_weight = overlap_score_weight
        self.temperature = temperature
        self.waiting_request_weight = waiting_request_weight
        self.rng = rng or random.Random()
        self.on_hit_rate_event = on_hit_rate_event
        self.queue_depth_threshold = queue_depth_threshold
        self.busy_queue_penalty = busy_queue_penalty

    def select(
        self,
        candidates: Sequence[WorkerLoadSnapshot],
        request_blocks: int,
        priority: Optional[int] = None,
    ) -> WorkerLoadSnapshot:
        if not candidates:
            raise ValueError("no candidate workers")
        # Slice-capacity reference: the biggest candidate slice with a
        # published HBM figure normalizes everyone else's decode load.
        ref_hbm = max((c.slice.total_hbm_bytes for c in candidates
                       if c.slice is not None
                       and c.slice.total_hbm_bytes > 0), default=0)
        waiting_by_id: Dict[WorkerId, int] = {
            c.worker_id: (c.metrics.worker_stats.num_requests_waiting
                          if c.metrics is not None else 0)
            for c in candidates
        }
        # QoS: the interactive bias only applies when SOME candidate is
        # under the queue threshold — an all-busy fleet must route
        # unbiased (degenerate case), not herd on a random worker.
        bias_busy = (priority is not None
                     and priority >= INTERACTIVE_PRIORITY
                     and any(w <= self.queue_depth_threshold
                             for w in waiting_by_id.values()))
        costs: Dict[WorkerId, float] = {}
        by_id: Dict[WorkerId, WorkerLoadSnapshot] = {}
        for c in candidates:
            potential_prefill = max(0, request_blocks - c.overlap_blocks)
            # Decode load: router-local optimistic accounting merged with
            # the worker's last PUBLISHED stats (reference merges scraped
            # ForwardPassMetrics into routing via `scoring.rs`
            # ProcessedEndpoints).  max(): local accounting reacts
            # instantly to our own decisions; published truth covers load
            # this router never saw (other frontends, engine-internal
            # state) — r2 published these metrics and routed on neither.
            decode_load = c.decode_blocks
            waiting = waiting_by_id[c.worker_id]
            if c.metrics is not None:
                decode_load = max(decode_load,
                                  c.metrics.kv_stats.kv_active_blocks)
            if ref_hbm and c.slice is not None \
                    and c.slice.total_hbm_bytes > 0:
                factor = ref_hbm / c.slice.total_hbm_bytes
                decode_load *= min(HBM_FACTOR_MAX,
                                   max(HBM_FACTOR_MIN, factor))
            cost = (
                self.overlap_score_weight * (potential_prefill + c.prefill_blocks)
                + decode_load
                + self.waiting_request_weight * waiting
            )
            if bias_busy and waiting > self.queue_depth_threshold:
                cost += self.busy_queue_penalty
            costs[c.worker_id] = cost
            by_id[c.worker_id] = c
        chosen_id = softmax_sample(costs, self.temperature, self.rng)
        chosen = by_id[chosen_id]
        logger.debug(
            "selected worker %s cost=%.1f overlap=%d/%d blocks",
            chosen_id,
            costs[chosen_id],
            chosen.overlap_blocks,
            request_blocks,
        )
        if self.on_hit_rate_event:
            self.on_hit_rate_event(
                KVHitRateEvent(
                    worker_id=chosen_id,
                    isl_blocks=request_blocks,
                    overlap_blocks=min(chosen.overlap_blocks, request_blocks),
                    cost=costs[chosen_id],
                    candidates=len(costs),
                )
            )
        return chosen
