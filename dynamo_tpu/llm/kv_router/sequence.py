"""Router-local tracking of in-flight load per worker.

Role of the reference's `lib/llm/src/kv_router/sequence.rs`
(ActiveSequences :48 / ActiveSequencesMultiWorker :225): the router cannot
wait for worker metrics to observe the load *it just created*, so it
optimistically accounts each routed request — prefill tokens it will cost
(minus cached overlap) and KV blocks it will occupy — and releases them as
the request progresses.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from dynamo_tpu.llm.kv_router.protocols import WorkerId


@dataclass
class ActiveSeq:
    request_id: str
    isl_tokens: int          # input sequence length
    overlap_blocks: int      # cached prefix blocks at admission
    total_blocks: int        # blocks the sequence occupies (grows with decode)
    reserved_blocks: int = 0  # pre-reserved for expected decode growth
    prefilling: bool = True
    created_at: float = 0.0


class ActiveSequences:
    """Per-worker in-flight accounting (one worker's view)."""

    def __init__(self, block_size: int = 64) -> None:
        self.block_size = block_size
        self._seqs: Dict[str, ActiveSeq] = {}

    # -- lifecycle --------------------------------------------------------
    def add_request(
        self,
        request_id: str,
        isl_tokens: int,
        overlap_blocks: int,
        expected_output_tokens: int = 0,
    ) -> None:
        """Track a routed request.  `expected_output_tokens` pre-reserves the
        decode blocks the request is expected to grow into (the reference
        scheduler's `potential_blocks` accounting, `kv_router/scheduler.rs`),
        so the selector sees future occupancy, not just the prompt."""
        total_blocks = (
            isl_tokens + (expected_output_tokens or 0) + self.block_size - 1
        ) // self.block_size
        self._seqs[request_id] = ActiveSeq(
            request_id=request_id,
            isl_tokens=isl_tokens,
            overlap_blocks=overlap_blocks,
            total_blocks=total_blocks,
            reserved_blocks=total_blocks,
            created_at=time.monotonic(),
        )

    def mark_prefill_complete(self, request_id: str) -> None:
        seq = self._seqs.get(request_id)
        if seq:
            seq.prefilling = False

    def push_token(self, request_id: str, n: int = 1) -> None:
        """Decode produced n tokens; grows block occupancy at boundaries."""
        seq = self._seqs.get(request_id)
        if not seq:
            return
        seq.prefilling = False
        seq.isl_tokens += n
        # Occupancy never drops below the admission-time reservation: the
        # pre-reserved decode growth stays visible to the selector until the
        # sequence actually outgrows it.
        seq.total_blocks = max(
            (seq.isl_tokens + self.block_size - 1) // self.block_size,
            seq.reserved_blocks,
        )

    def free(self, request_id: str) -> None:
        self._seqs.pop(request_id, None)

    # -- load views -------------------------------------------------------
    def expire_older_than(self, ttl_secs: float, now: Optional[float] = None) -> int:
        """Drop sequences older than `ttl_secs` (leaked accounting from
        callers that died between routing and free()); returns count dropped."""
        now = time.monotonic() if now is None else now
        stale = [rid for rid, s in self._seqs.items() if now - s.created_at > ttl_secs]
        for rid in stale:
            del self._seqs[rid]
        return len(stale)

    def active_prefill_tokens(self) -> int:
        """Tokens of prefill work outstanding (cached prefix excluded)."""
        return sum(
            max(0, s.isl_tokens - s.overlap_blocks * self.block_size)
            for s in self._seqs.values()
            if s.prefilling
        )

    def active_decode_blocks(self) -> int:
        """KV blocks occupied by in-flight sequences."""
        return sum(s.total_blocks for s in self._seqs.values())

    def num_active(self) -> int:
        return len(self._seqs)


class ActiveSequencesMultiWorker:
    """All workers' in-flight accounting, with request → worker attribution.

    Thread-safe: the router's selection path and the response-stream
    completion callbacks run on different tasks/threads.
    """

    def __init__(self, block_size: int = 64) -> None:
        self.block_size = block_size
        self._lock = threading.Lock()
        self._workers: Dict[WorkerId, ActiveSequences] = {}
        self._request_worker: Dict[str, WorkerId] = {}

    def _worker(self, worker: WorkerId) -> ActiveSequences:
        ws = self._workers.get(worker)
        if ws is None:
            ws = ActiveSequences(self.block_size)
            self._workers[worker] = ws
        return ws

    def workers(self) -> list:
        with self._lock:
            return sorted(self._workers)

    def add_request(
        self,
        request_id: str,
        worker: WorkerId,
        isl_tokens: int,
        overlap_blocks: int,
        expected_output_tokens: int = 0,
    ) -> None:
        with self._lock:
            self._request_worker[request_id] = worker
            self._worker(worker).add_request(
                request_id, isl_tokens, overlap_blocks,
                expected_output_tokens=expected_output_tokens)

    def mark_prefill_complete(self, request_id: str) -> None:
        with self._lock:
            w = self._request_worker.get(request_id)
            if w is not None:  # worker id 0 is falsy but real
                self._worker(w).mark_prefill_complete(request_id)

    def push_token(self, request_id: str, n: int = 1) -> None:
        with self._lock:
            w = self._request_worker.get(request_id)
            if w is not None:  # worker id 0 is falsy but real
                self._worker(w).push_token(request_id, n)

    def free(self, request_id: str) -> None:
        with self._lock:
            w = self._request_worker.pop(request_id, None)
            if w is not None:  # worker id 0 is falsy but real
                self._worker(w).free(request_id)

    def remove_worker(self, worker: WorkerId) -> None:
        with self._lock:
            ws = self._workers.pop(worker, None)
            if ws:
                for rid in list(self._request_worker):
                    if self._request_worker[rid] == worker:
                        del self._request_worker[rid]

    def expire_older_than(self, ttl_secs: float) -> int:
        """Sweep leaked sequences across all workers (call periodically)."""
        with self._lock:
            dropped = 0
            for ws in self._workers.values():
                dropped += ws.expire_older_than(ttl_secs)
            live = {rid for ws in self._workers.values() for rid in ws._seqs}
            for rid in [r for r in self._request_worker if r not in live]:
                del self._request_worker[rid]
            return dropped

    # -- load views -------------------------------------------------------
    def prefill_tokens(self) -> Dict[WorkerId, int]:
        with self._lock:
            return {w: ws.active_prefill_tokens() for w, ws in self._workers.items()}

    def decode_blocks(self) -> Dict[WorkerId, int]:
        with self._lock:
            return {w: ws.active_decode_blocks() for w, ws in self._workers.items()}

    def active_counts(self) -> Dict[WorkerId, int]:
        with self._lock:
            return {w: ws.num_active() for w, ws in self._workers.items()}
