"""Shared load-metrics watcher: one subscription, freshness-pruned view.

Three consumers need the same machinery — the KV router's cost merge
(client.py), the namespace aggregator (metrics_aggregator), and the
planner's observation loop — so it lives once here: subscribe to the
`load_metrics` subject, keep the latest ForwardPassMetrics per worker,
and serve a freshness-filtered snapshot.  `fresh()` also PRUNES stale
entries so worker churn (the planner spawns a new instance id per
scale-up) can't grow the map without bound.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, WorkerId

logger = logging.getLogger(__name__)

METRICS_SUBJECT = "load_metrics"


class LoadMetricsWatcher:
    def __init__(self, cp, stale_secs: float = 10.0,
                 name: str = "load-metrics") -> None:
        self.cp = cp
        self.stale_secs = stale_secs
        self.name = name
        self._metrics: Dict[WorkerId, tuple] = {}   # id → (metrics, ts)
        self._sub = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._sub = await self.cp.subscribe(METRICS_SUBJECT)
        self._task = asyncio.create_task(self._pump())

    async def stop(self) -> None:
        if self._sub:
            self._sub.cancel()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _pump(self) -> None:
        backoff = 1.0
        while True:
            try:
                payload = await self._sub.next()
                backoff = 1.0
            except asyncio.CancelledError:
                raise
            except ConnectionError:
                # ADVICE r3: returning here left the consumer silently
                # blind to load metrics until process restart.  The
                # control-plane client reconnects underneath and restores
                # this SAME subscription (a fresh subscribe() here would
                # double-deliver); keep draining it after a pause.
                logger.warning(
                    "%s: load_metrics subscription lost; waiting %.0fs "
                    "for reconnect", self.name, backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)
                continue
            try:
                self._metrics[payload["worker_id"]] = (
                    ForwardPassMetrics.from_dict(payload["metrics"]),
                    time.monotonic())
            except Exception:
                logger.exception("%s: bad load_metrics payload", self.name)

    def fresh(self) -> Dict[WorkerId, ForwardPassMetrics]:
        """Snapshot of workers heard from within `stale_secs`; prunes the
        rest from the map."""
        cutoff = time.monotonic() - self.stale_secs
        stale = [w for w, (_, ts) in self._metrics.items() if ts <= cutoff]
        for w in stale:
            del self._metrics[w]
        return {w: m for w, (m, _) in self._metrics.items()}
