"""Request migration: resume in-flight streams across worker death AND
planned drain.

Role of the reference's `lib/llm/src/migration.rs:27-163` (RetryManager):
wraps an EngineClient; when the stream dies mid-request (ConnectionError /
no instances), it re-issues the request to a surviving worker with the
already-generated tokens appended to the prompt and `max_tokens`
decremented (`track_response` semantics, `migration.rs:148-163`), up to
`migration_limit` attempts.  The client sees one uninterrupted stream.

ISSUE 15 extends the ladder with KV-CARRYING migration: a worker leaving
the fleet (planner scale-down, `--drain` SIGTERM, control-plane drain
command) ends each in-flight stream with a `migrate` delta — llm/drain.py
— naming its kv_blocks address and the stream's sealed-token high-water
mark.  The re-issue then carries a `migrate_kv` annotation
(prefix_share.MIGRATE_ANNOTATION); the receiving worker's
PrefixShareClient pulls the resident prefix peer-to-peer (device plane
where available) BEFORE admission, so the resumed stream prefills only
the unsealed tail instead of recomputing everything the source already
paid for.  The re-prefill path stays as the fallback rung for unplanned
death and refused pulls.

Resume contract: greedy streams are byte-identical to uninterrupted
serving (the sealed prefix is the same KV, the tail recomputes the same
logits); seeded stochastic streams keep the (seed, token-index) law via
`SamplingParams.seed_offset`, which advances the engine's fold_in index
by the tokens a previous incarnation already emitted.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
from typing import AsyncIterator, Optional

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.engine.scheduler import FinishReason
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime import flight_recorder
from dynamo_tpu.runtime.distributed import NoInstancesError
from dynamo_tpu.runtime.logutil import warn_rate_limited
from dynamo_tpu.runtime.rpc import RpcError

logger = logging.getLogger(__name__)

RETRYABLE = (ConnectionError, NoInstancesError)

# A draining worker refuses new admissions with this marker in the error
# string (llm/drain.py raises it; the RPC layer relays handler errors as
# RpcError with the remote message).  The refusal is as retryable as a
# death: the instance record is about to vanish — re-route elsewhere.
DRAIN_REFUSAL = "worker-draining"


def _is_drain_refusal(e: Exception) -> bool:
    return DRAIN_REFUSAL in str(e)


class MigrationClient:
    """EngineClient decorator adding stream migration.

    `registry` (runtime/metrics.MetricsRegistry, optional): counts
    `dynamo_migrations_total{reason}` — reason is what triggered the
    hop: "drain" (planned handoff, KV carried when the source offered
    it), "drain_refused" (raced a worker into its drain window),
    "death" (connection died mid-stream), "no_instances" (routing found
    nobody; the retry waits out the re-registration window).
    """

    def __init__(self, inner, migration_limit: int = 3,
                 retry_delay: float = 0.05, max_retry_delay: float = 2.0,
                 registry=None) -> None:
        self.inner = inner
        self.migration_limit = migration_limit
        self.retry_delay = retry_delay
        self.max_retry_delay = max_retry_delay
        self.migrations = 0          # cumulative hops (all reasons)
        self._counter = (registry.counter(
            "migrations_total",
            "Stream migrations by trigger reason (drain handoff, drain "
            "refusal, worker death, empty instance set)")
            if registry is not None else None)

    async def embed(self, token_lists):
        return await self.inner.embed(token_lists)

    async def clear_kv_blocks(self) -> int:
        return await self.inner.clear_kv_blocks()

    def _backoff(self, attempt: int) -> float:
        """Jittered exponential backoff: 2^attempt over the base delay,
        capped, with +/-50% jitter so a fleet of retrying streams never
        thunders back in lockstep (satellite of ISSUE 15; was a fixed
        0.05 s)."""
        base = min(self.max_retry_delay,
                   self.retry_delay * (2.0 ** attempt))
        return base * (0.5 + random.random())

    def _count(self, reason: str) -> None:
        self.migrations += 1
        if self._counter is not None:
            self._counter.inc(labels={"reason": reason})
        fl = flight_recorder.get_recorder()
        if fl.enabled:
            fl.record("migrate", reason=reason, hops=self.migrations)

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        import time as _time

        from dynamo_tpu.llm.block_manager.prefix_share import (
            MIGRATE_ANNOTATION, encode_hint)
        from dynamo_tpu.runtime.ledger import ledger_of

        generated: list = []
        attempts_left = self.migration_limit
        attempt = 0
        req = request
        led = ledger_of(request)
        while True:
            migrate_info: Optional[dict] = None
            reason = None
            t_break = None
            gen = self.inner.generate(req)
            try:
                async for delta in gen:
                    # getattr: operator tests compose duck-typed deltas
                    # that predate the migrate field.
                    if getattr(delta, "migrate", None) is not None:
                        # Planned drain handoff: the worker ends the
                        # stream here with its KV address; nothing to
                        # surface to the client — resume on a peer.
                        migrate_info = getattr(delta, "migrate", None)
                        reason = "drain"
                        break
                    generated.extend(delta.token_ids)
                    yield delta
                    if delta.finished:
                        return
                if migrate_info is None:
                    return  # clean end without finished marker: done
            except RETRYABLE as e:
                reason = ("no_instances"
                          if isinstance(e, NoInstancesError) else "death")
            except RpcError as e:
                if not _is_drain_refusal(e):
                    raise
                reason = "drain_refused"
            finally:
                # Deterministic close: a break (migrate delta) or an
                # upstream disconnect leaves `gen` suspended — close it
                # NOW so the wire layer sends its cancel frame and
                # worker-side wrappers run their cleanup before the
                # retry, not at GC time.
                t_break = _time.monotonic()
                try:
                    await gen.aclose()
                except Exception:
                    # dynamo-lint: disable=DL003 already-broken stream
                    pass  # nothing to salvage: the stream is done either way
            if attempts_left <= 0:
                logger.error("migration budget exhausted for %s (last "
                             "reason: %s)", request.request_id, reason)
                raise ConnectionError(
                    f"migration budget exhausted after "
                    f"{self.migration_limit} attempts ({reason})")
            attempts_left -= 1
            attempt += 1
            self._count(reason)
            # Resume: prompt + tokens so far; budget shrinks by what was
            # already delivered (reference migration.rs:148), and
            # seed_offset keeps seeded sampling's (seed, token-index)
            # contract across the hop.
            new_max = request.sampling.max_tokens - len(generated)
            if new_max <= 0:
                # Full budget was delivered before the worker left (only
                # the finished marker was lost) — close the stream as a
                # normal length-finish, not an error.
                yield TokenDelta(request_id=request.request_id,
                                 token_ids=[], finished=True,
                                 finish_reason=FinishReason.LENGTH)
                return
            annotations = dict(request.annotations)
            # A stale migrate hint from a previous hop must never chase
            # a worker that has since exited.
            annotations.pop(MIGRATE_ANNOTATION, None)
            carry = 0
            if (migrate_info and migrate_info.get("address")
                    and migrate_info.get("covered_tokens", 0) > 0):
                # KV-carrying rung: tell the receiving worker where the
                # sealed prefix lives; its PrefixShareClient pulls it
                # before admission (re-prefill only on refusal).
                carry = int(migrate_info["covered_tokens"])
                annotations[MIGRATE_ANNOTATION] = encode_hint(
                    migrate_info["address"], carry)
            req = dataclasses.replace(
                request,
                request_id=(f"{request.request_id}"
                            f"#m{self.migration_limit - attempts_left}"),
                token_ids=list(request.token_ids) + generated,
                annotations=annotations,
                sampling=dataclasses.replace(
                    request.sampling, max_tokens=new_max,
                    seed_offset=(request.sampling.seed_offset
                                 + len(generated))),
            )
            if led is not None:
                # The live ledger rides as a PLAIN attribute, not a
                # dataclass field — dataclasses.replace drops it, so the
                # resumed incarnation must carry it explicitly.
                req.ledger = led
            # One warning per stream per reason, rate-limited across the
            # retry storm a dead fleet produces (was one line per
            # attempt per request).
            warn_rate_limited(
                logger, f"migrate:{reason}", 10.0,
                "migrating streams (%s): e.g. %s, %d tokens in, "
                "%d KV tokens carried, %d attempts left",
                reason, request.request_id, len(generated), carry,
                attempts_left)
            if reason != "drain":
                # Planned handoffs re-route immediately (the drained
                # worker already left the instance set); failures back
                # off with jitter.
                await asyncio.sleep(self._backoff(attempt - 1))
            if led is not None and t_break is not None:
                # Client-visible stall: stream break → re-issue
                # (includes the backoff for unplanned deaths).
                led.stamp("migration", dur=_time.monotonic() - t_break,
                          reason=reason, attempt=attempt,
                          carried_tokens=carry)
