"""Request migration: resume in-flight streams on worker death.

Role of the reference's `lib/llm/src/migration.rs:27-163` (RetryManager):
wraps an EngineClient; when the stream dies mid-request (ConnectionError /
no instances), it re-issues the request to a surviving worker with the
already-generated tokens appended to the prompt and `max_tokens`
decremented (`track_response` semantics, `migration.rs:148-163`), up to
`migration_limit` attempts.  The client sees one uninterrupted stream.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
from typing import AsyncIterator

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.engine.scheduler import FinishReason
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime.distributed import NoInstancesError
from dynamo_tpu.runtime.rpc import RpcError

logger = logging.getLogger(__name__)

RETRYABLE = (ConnectionError, NoInstancesError)


class MigrationClient:
    """EngineClient decorator adding stream migration."""

    def __init__(self, inner, migration_limit: int = 3,
                 retry_delay: float = 0.05) -> None:
        self.inner = inner
        self.migration_limit = migration_limit
        self.retry_delay = retry_delay

    async def embed(self, token_lists):
        return await self.inner.embed(token_lists)

    async def clear_kv_blocks(self) -> int:
        return await self.inner.clear_kv_blocks()

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        generated: list = []
        attempts_left = self.migration_limit
        req = request
        while True:
            try:
                async for delta in self.inner.generate(req):
                    generated.extend(delta.token_ids)
                    yield delta
                    if delta.finished:
                        return
                return  # clean end without finished marker: treat as done
            except RETRYABLE as e:
                if attempts_left <= 0:
                    logger.error("migration budget exhausted for %s",
                                 request.request_id)
                    raise
                attempts_left -= 1
                # Resume: prompt + tokens so far; budget shrinks by
                # what was already delivered (reference migration.rs:148).
                new_max = request.sampling.max_tokens - len(generated)
                if new_max <= 0:
                    # Full budget was delivered before the worker died (only
                    # the finished marker was lost) — close the stream as a
                    # normal length-finish, not an error.
                    yield TokenDelta(request_id=request.request_id,
                                     token_ids=[], finished=True,
                                     finish_reason=FinishReason.LENGTH)
                    return
                req = dataclasses.replace(
                    request,
                    request_id=f"{request.request_id}#m{self.migration_limit - attempts_left}",
                    token_ids=list(request.token_ids) + generated,
                    sampling=dataclasses.replace(
                        request.sampling, max_tokens=new_max),
                )
                logger.warning(
                    "migrating %s after %s (%d tokens in, %d attempts left)",
                    request.request_id, type(e).__name__, len(generated),
                    attempts_left)
                await asyncio.sleep(self.retry_delay)
