"""Mock engine: a complete fake engine with authentic KV semantics.

Role of the reference's `lib/llm/src/mocker/` (SURVEY.md §2.2 and §4): a
vLLM-semantics engine — block-level prefix caching with LRU eviction,
watermark admission, chunked prefill, simulated step timing — that emits
*real* KV events and load metrics, so routing / frontend / disaggregation /
planner tests run with zero accelerator time.  The CI workhorse.
"""

from dynamo_tpu.llm.mocker.engine import MockEngine, MockEngineArgs

__all__ = ["MockEngine", "MockEngineArgs"]
