"""Mock engine: scheduler + simulated timing over MockKvManager.

Role of the reference's `mocker/{engine,scheduler,sequence}.rs`: an
`EngineClient` that behaves like a real continuous-batching engine —
watermark admission, chunked prefill under a token budget, prefix-cache
hits skipping prefill work, per-step simulated latency (scaled by
`speedup_ratio`), synthetic-but-deterministic output tokens — and emits
real KV events + ForwardPassMetrics.

Defaults mirror `mocker/protocols.rs:79-108` (16384 blocks × 64, 256 seqs,
8192 batched tokens, watermark 0.01).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from dataclasses import dataclass, field
from typing import AsyncIterator, Callable, Dict, List, Optional

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import FinishReason
from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.llm.mocker.kv_manager import MockKvManager
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.tokens import ROOT_PARENT_HASH, TokenBlockSequence

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class MockEngineArgs:
    """Reference `MockEngineArgs` (`mocker/protocols.rs:79-108`)."""

    num_blocks: int = 16_384
    block_size: int = 64
    max_num_seqs: int = 256
    max_batched_tokens: int = 8_192
    watermark: float = 0.01
    speedup_ratio: float = 1.0           # >1 → faster than "real" timing
    # Simulated hardware timing model (ms), loosely a v5e decode curve:
    prefill_ms_per_token: float = 0.35
    decode_base_ms: float = 4.0
    decode_ms_per_seq: float = 0.05


@dataclass
class _MockSeq:
    request: PreprocessedRequest
    queue: asyncio.Queue
    hash_seq: TokenBlockSequence
    prefilled: int = 0
    cached_tokens: int = 0               # prefix-cache hit, skipped work
    output: List[int] = field(default_factory=list)
    acquired_blocks: List[int] = field(default_factory=list)
    decoding: bool = False
    # Request-ledger timings (runtime/ledger.py): arrival → admission →
    # first token, stamped when the first token emits.
    arrival_ts: float = 0.0
    admit_ts: float = 0.0

    @property
    def prompt(self) -> List[int]:
        return self.request.token_ids

    @property
    def sampling(self) -> SamplingParams:
        return self.request.sampling


def _synthetic_token(request_id: str, index: int) -> int:
    """Deterministic pseudo-random output stream per request.

    Tokens land in printable ASCII (32..126) so any tokenizer — including
    the byte tokenizer used in e2e tests — detokenizes mock streams into
    visible text."""
    h = hashlib.blake2b(f"{request_id}:{index}".encode(),
                       digest_size=4).digest()
    return 32 + int.from_bytes(h, "little") % 95


class MockEngine:
    """Async mock engine implementing the EngineClient contract."""

    def __init__(
        self,
        args: MockEngineArgs = MockEngineArgs(),
        kv_event_sink: Optional[Callable[[KvCacheEvent], None]] = None,
    ) -> None:
        self.args = args
        self.kv = MockKvManager(args.num_blocks, args.block_size,
                                event_sink=kv_event_sink)
        self._waiting: List[_MockSeq] = []
        self._running: List[_MockSeq] = []
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self.metrics = ForwardPassMetrics(
            worker_stats=WorkerStats(request_total_slots=args.max_num_seqs),
            kv_stats=KvStats(kv_total_blocks=args.num_blocks))

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # -- EngineClient -----------------------------------------------------

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        await self.start()
        seq = _MockSeq(
            request=request,
            queue=asyncio.Queue(),
            hash_seq=TokenBlockSequence(block_size=self.args.block_size),
            arrival_ts=time.monotonic())
        self._waiting.append(seq)
        self._wake.set()
        try:
            while True:
                delta: TokenDelta = await seq.queue.get()
                yield delta
                if delta.finished:
                    return
        finally:
            # Client gone: retire the sequence if still active.
            if seq in self._waiting:
                self._waiting.remove(seq)
            if seq in self._running:
                self._retire(seq)

    # -- engine loop ------------------------------------------------------

    async def _loop(self) -> None:
        while True:
            if not self._waiting and not self._running:
                self._wake.clear()
                await self._wake.wait()
            step_ms = self._step()
            self._refresh_metrics()
            # Simulated hardware time, compressed by speedup_ratio.
            await asyncio.sleep(step_ms / 1000.0 / self.args.speedup_ratio)

    def _step(self) -> float:
        """One iteration: admit, chunked-prefill, decode.  Returns the
        simulated step latency in ms."""
        self._admit()
        budget = self.args.max_batched_tokens
        prefill_tokens = 0
        emitted_this_step = set()

        # Chunked prefill, FCFS.
        for seq in self._running:
            if seq.decoding or budget <= 0:
                continue
            remaining = len(seq.prompt) - seq.prefilled
            chunk = min(remaining, budget)
            seq.prefilled += chunk
            budget -= chunk
            prefill_tokens += chunk
            if seq.prefilled >= len(seq.prompt):
                seq.decoding = True
                emitted_this_step.add(id(seq))
                self._emit_token(seq)   # first token at end of prefill

        # Decode: every decoding sequence advances one token (those that
        # just produced their first token above wait for the next step).
        decoding = [s for s in self._running if s.decoding]
        for seq in list(decoding):
            if id(seq) in emitted_this_step:
                continue
            self._emit_token(seq)

        ms = prefill_tokens * self.args.prefill_ms_per_token
        if decoding:
            ms += (self.args.decode_base_ms
                   + self.args.decode_ms_per_seq * len(decoding))
        return ms

    def _admit(self) -> None:
        while self._waiting and len(self._running) < self.args.max_num_seqs:
            seq = self._waiting[0]
            hashes = [b.block_hash for b in TokenBlockSequence(
                seq.prompt, block_size=self.args.block_size).blocks]
            free_frac = (self.kv.capacity - self.kv.active_blocks) / self.kv.capacity
            if free_frac < self.args.watermark:
                break
            try:
                parents = [None] + hashes[:-1]
                reused = self.kv.acquire(hashes, parents)
            except RuntimeError:
                break  # capacity exhausted; retry after something finishes
            self._waiting.pop(0)
            seq.admit_ts = time.monotonic()
            seq.acquired_blocks = hashes
            seq.cached_tokens = reused * self.args.block_size
            # Prefix-cached tokens skip prefill work entirely.
            seq.prefilled = min(seq.cached_tokens, len(seq.prompt) - 1)
            seq.hash_seq.extend(seq.prompt)
            self._running.append(seq)

    def _emit_token(self, seq: _MockSeq) -> None:
        idx = len(seq.output)
        if idx == 0:
            self._stamp_ledger(seq)
        token = _synthetic_token(seq.request.request_id, idx)
        seq.output.append(token)
        # Decode growth: register newly-sealed blocks.
        newly = seq.hash_seq.extend([token])
        for blk in newly:
            parent = (blk.parent_hash
                      if blk.parent_hash != ROOT_PARENT_HASH else None)
            self.kv.extend(blk.block_hash, parent)
            seq.acquired_blocks.append(blk.block_hash)

        finished = (len(seq.output) >= seq.sampling.max_tokens
                    or token in seq.sampling.stop_token_ids)
        delta = TokenDelta(
            request_id=seq.request.request_id,
            token_ids=[token],
            finished=finished,
            finish_reason=(
                (FinishReason.STOP if token in seq.sampling.stop_token_ids
                 else FinishReason.LENGTH) if finished else None))
        seq.queue.put_nowait(delta)
        if finished:
            self._retire(seq)

    def _stamp_ledger(self, seq: _MockSeq) -> None:
        """Mock timing is real wall-clock (the loop sleeps the simulated
        step latency), so the same queue/prefill/first_token phases real
        engines stamp hold here — bench_gate's mocker-fleet coverage
        check reads them against measured TTFT."""
        from dynamo_tpu.runtime.ledger import enabled, ledger_of

        led = ledger_of(seq.request)
        if led is None or not enabled():
            return
        now = time.monotonic()
        admit = seq.admit_ts or seq.arrival_ts
        led.stamp("queue", dur=admit - seq.arrival_ts, t=admit)
        led.stamp("prefill", dur=now - admit, t=now,
                  prompt_tokens=len(seq.prompt),
                  cached_tokens=seq.cached_tokens)
        led.stamp("first_token", dur=0.0, t=now)

    def _retire(self, seq: _MockSeq) -> None:
        if seq in self._running:
            self._running.remove(seq)
        self.kv.release(seq.acquired_blocks)
        seq.acquired_blocks = []

    def _refresh_metrics(self) -> None:
        ws = self.metrics.worker_stats
        ws.request_active_slots = len(self._running)
        ws.num_requests_waiting = len(self._waiting)
        ks = self.metrics.kv_stats
        ks.kv_active_blocks = self.kv.active_blocks
        ks.gpu_cache_usage_perc = self.kv.usage
        total = self.kv.hit_blocks + self.kv.miss_blocks
        ks.gpu_prefix_cache_hit_rate = (
            self.kv.hit_blocks / total if total else 0.0)
