"""Block-level KV manager with prefix caching + LRU eviction (mock engine).

Role of the reference's `mocker/kv_manager.rs` (519 LoC) + `evictor.rs`:
tracks which token blocks (chained hashes) are resident, refcounts active
use, keeps freed blocks in an LRU "inactive" pool for prefix reuse, evicts
when capacity is needed, and reports every mutation as KV events — the
exact stream the router's indexer consumes.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from dynamo_tpu.llm.kv_router.protocols import KvCacheEvent, KvCacheEventData


@dataclass
class _Block:
    block_hash: int
    parent_hash: Optional[int]
    ref_count: int = 0


class MockKvManager:
    """Capacity-bounded prefix cache keyed by chained block hashes."""

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        event_sink: Optional[Callable[[KvCacheEvent], None]] = None,
    ) -> None:
        self.capacity = num_blocks
        self.block_size = block_size
        self._active: Dict[int, _Block] = {}
        self._inactive: "OrderedDict[int, _Block]" = OrderedDict()  # LRU
        self._event_sink = event_sink
        self._event_id = 0
        # Stats for metrics/tests.
        self.hit_blocks = 0
        self.miss_blocks = 0
        self.evicted_blocks = 0

    # -- capacity views ---------------------------------------------------

    @property
    def used_blocks(self) -> int:
        return len(self._active) + len(self._inactive)

    @property
    def active_blocks(self) -> int:
        return len(self._active)

    @property
    def usage(self) -> float:
        return self.active_blocks / self.capacity if self.capacity else 1.0

    def free_capacity(self) -> int:
        """Blocks allocatable right now (free + evictable inactive)."""
        return self.capacity - len(self._active)

    # -- matching ---------------------------------------------------------

    def match_prefix(self, block_hashes: Sequence[int]) -> int:
        """Longest resident prefix (active or inactive), in blocks."""
        n = 0
        for h in block_hashes:
            if h in self._active or h in self._inactive:
                n += 1
            else:
                break
        return n

    # -- allocation -------------------------------------------------------

    def can_allocate(self, block_hashes: Sequence[int],
                     extra_new: int = 0) -> bool:
        cached = self.match_prefix(block_hashes)
        need_new = len(block_hashes) - cached + extra_new
        return need_new <= self.free_capacity() - self._inactive_pinned(
            block_hashes[:cached])

    def _inactive_pinned(self, hashes: Sequence[int]) -> int:
        """Inactive blocks a reuse would revive (they stop being evictable
        but don't consume new capacity) — always 0 toward free capacity."""
        return 0

    def acquire(self, block_hashes: Sequence[int],
                parents: Sequence[Optional[int]]) -> int:
        """Pin `block_hashes` (full prefix of a sequence), reusing resident
        blocks and registering the rest.  Returns #blocks reused.

        Atomic: either the whole sequence is pinned or nothing is — a
        partial pin on capacity failure would leak refcounts and wedge
        admission forever.  Eviction of LRU inactive blocks makes room as
        needed; raises RuntimeError when even eviction can't free enough."""
        reused = 0
        pinned: List[int] = []
        try:
            for h, parent in zip(block_hashes, parents):
                blk = self._active.get(h)
                if blk is not None:
                    blk.ref_count += 1
                    pinned.append(h)
                    reused += 1
                    self.hit_blocks += 1
                    continue
                blk = self._inactive.pop(h, None)
                if blk is not None:
                    blk.ref_count = 1
                    self._active[h] = blk
                    pinned.append(h)
                    reused += 1
                    self.hit_blocks += 1
                    continue
                # New block: make room, then register.
                self._ensure_room(1)
                self._active[h] = _Block(h, parent, ref_count=1)
                pinned.append(h)
                self.miss_blocks += 1
                self._emit(KvCacheEventData.stored([h], parent_hash=parent))
        except RuntimeError:
            self.release(pinned)
            raise
        return reused

    def extend(self, block_hash: int, parent: Optional[int]) -> None:
        """Register one decode-grown block for an already-active sequence."""
        blk = self._active.get(block_hash)
        if blk is not None:
            blk.ref_count += 1
            return
        blk = self._inactive.pop(block_hash, None)
        if blk is not None:
            blk.ref_count = 1
            self._active[block_hash] = blk
            return
        self._ensure_room(1)
        self._active[block_hash] = _Block(block_hash, parent, ref_count=1)
        self._emit(KvCacheEventData.stored([block_hash], parent_hash=parent))

    def release(self, block_hashes: Sequence[int]) -> None:
        """Unpin a sequence's blocks; refcount-0 blocks go to the LRU pool
        (still resident → still a prefix-cache hit until evicted)."""
        for h in reversed(list(block_hashes)):
            blk = self._active.get(h)
            if blk is None:
                continue
            blk.ref_count -= 1
            if blk.ref_count <= 0:
                del self._active[h]
                self._inactive[h] = blk
                self._inactive.move_to_end(h)

    # -- eviction ---------------------------------------------------------

    def _ensure_room(self, n: int) -> None:
        while self.used_blocks + n > self.capacity:
            if not self._inactive:
                raise RuntimeError(
                    f"KV capacity exhausted: {self.active_blocks} active / "
                    f"{self.capacity} total")
            h, _ = self._inactive.popitem(last=False)  # LRU
            self.evicted_blocks += 1
            self._emit(KvCacheEventData.removed([h]))

    # -- events -----------------------------------------------------------

    def _emit(self, data: KvCacheEventData) -> None:
        if self._event_sink is None:
            return
        self._event_id += 1
        self._event_sink(KvCacheEvent(event_id=self._event_id, data=data))
