"""Model Deployment Card (MDC) — what a worker publishes about its model.

Role of the reference's `lib/llm/src/model_card.rs:90-120`
(ModelDeploymentCard: tokenizer / prompt-formatter / gen-config refs,
published to NATS object store + etcd entry): everything a frontend needs
to serve a model it has never seen locally — tokenizer construction,
chat template, context limits, KV geometry for routing.

Tokenizer specs:
- {"kind": "byte"} — dependency-free test tokenizer;
- {"kind": "hf_file", "path": ...} — shared-filesystem deployments;
- {"kind": "hf_inline", "json": <tokenizer.json contents>, "eos_token"?} —
  the artifact TRAVELS WITH THE CARD, so a frontend that has never seen
  the checkpoint serves the real tokenizer (the reference uploads MDC
  artifacts to the NATS object store, `model_card.rs:241`; our control
  plane carries them inline).
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Optional

from dynamo_tpu.llm.tokenizer import ByteTokenizer, HFTokenizer, Tokenizer


@dataclass
class ModelDeploymentCard:
    name: str
    tokenizer_spec: dict = field(default_factory=lambda: {"kind": "byte"})
    chat_template: Optional[str] = None
    max_context: int = 8192
    kv_block_size: int = 64
    default_max_tokens: int = 512
    model_type: str = "backend"        # reference ModelType::Backend
    revision: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "ModelDeploymentCard":
        return ModelDeploymentCard(**d)

    def build_tokenizer(self) -> Tokenizer:
        spec = self.tokenizer_spec
        kind = spec.get("kind", "byte")
        if kind == "byte":
            return ByteTokenizer()
        if kind == "hf_file":
            return HFTokenizer(spec["path"],
                               eos_token_ids=spec.get("eos_token_ids"),
                               eos_token=spec.get("eos_token"))
        if kind == "hf_inline":
            return HFTokenizer.from_json(
                spec["json"],
                eos_token_ids=spec.get("eos_token_ids"),
                eos_token=spec.get("eos_token"))
        raise ValueError(f"unknown tokenizer spec kind {kind!r}")
