"""Multimodal pipeline: processor → encode worker → LLM engine.

Role of the reference's `examples/multimodal_v1/components/` (processor
parses image parts out of the chat request; `encode_worker.py` runs the
vision tower and RDMA-transfers the embeddings to the LLM worker via
`nixl_connect` descriptors; the LLM worker splices them into the
prompt).  TPU-native mapping:

- **EncodeWorker** — the vision tower (a deterministic stub here: the
  skeleton's contract is embedding SHAPE and transport, not CLIP
  quality; a real tower drops into `encode()`).  Serves the `encode`
  RPC; embeddings travel on the DEVICE transfer plane
  (block_manager/device_transfer.py — the nixl_connect analog) with an
  inline-bytes fallback for plane-less peers.
- **MultimodalProcessor** — frontend-side: parses `image_url` content
  parts, fetches each image's embeddings from the encode worker, and
  builds a PreprocessedRequest whose prompt is
  [image placeholders][chat-template text] with `prompt_embeds`
  occupying the placeholder span.
- **engine** — `make_forward_step(with_input_embeds=True)`: masked
  prefill positions take the provided embeddings instead of the token
  lookup (engine routes any prefill batch carrying `prompt_embeds`
  through that variant).
"""

from __future__ import annotations

import hashlib
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

ENCODE_ENDPOINT = "encode"
PLACEHOLDER_TOKEN = 0


class StubVisionEncoder:
    """Deterministic image → [n_tokens, hidden] embeddings.

    Stands in for a CLIP/SigLIP tower: embeddings are a seeded-normal
    function of the image reference, so distinct images produce distinct
    (reproducible) embeddings and tests can assert the embeddings
    actually steer generation.

    `n_tokens` defaults to 64 — real towers emit hundreds of tokens per
    image (LLaVA's CLIP-L: 576), and the steering contract depends on
    the image span carrying real attention mass: at 16 tokens ahead of
    a ~107-token chat template, the tiny random test model's greedy
    argmax was provably insensitive to the image (the embeddings reached
    the engine and shifted logits by ~1, but never flipped the top
    token), which is exactly how the multimodal HTTP e2e tests failed
    from the seed onward."""

    def __init__(self, hidden_size: int, n_tokens: int = 64) -> None:
        self.hidden_size = hidden_size
        self.n_tokens = n_tokens

    def encode(self, image_ref: str) -> np.ndarray:
        seed = int.from_bytes(
            hashlib.blake2b(image_ref.encode(), digest_size=4).digest(),
            "little")
        rng = np.random.default_rng(seed)
        return rng.standard_normal(
            (self.n_tokens, self.hidden_size)).astype(np.float32) * 0.02


class EncodeWorker:
    """Serves `encode` RPC: image ref → embedding descriptor or inline
    bytes.  With a transfer plane the embeddings cross device-to-device
    (the nixl_connect Descriptor flow); without one they ride the RPC
    inline."""

    def __init__(self, encoder: StubVisionEncoder,
                 transfer_plane=None) -> None:
        self.encoder = encoder
        self.plane = transfer_plane
        self.encoded = 0

    def make_handler(self):
        async def handler(payload: dict):
            image = payload.get("image", "")
            emb = self.encoder.encode(image)
            self.encoded += 1
            # Descriptor only for peers that advertised a reachable
            # fabric (same probe discipline as kv_offer): a plane-less
            # or cross-fabric processor gets inline bytes instead of a
            # descriptor it could never pull.
            peer = payload.get("fabric")
            if self.plane is not None and peer is not None:
                import jax.numpy as jnp

                # Short TTL: this protocol has no kv_pulled ack, so the
                # offer must age out of the cap accounting on its own —
                # a puller slower than this is indistinguishable from a
                # dead one (the pull then fails like a dead holder).
                meta = self.plane.stage({0: jnp.asarray(emb)}, [0],
                                        peer_fabric=peer, ttl_s=30.0)
                if meta is not None:
                    yield {"kind": "descriptor", "meta": meta}
                    return
            yield {"kind": "inline", "data": emb.tobytes(),
                   "shape": list(emb.shape), "dtype": "float32"}

        return handler


async def _decode_reply(reply: Optional[dict],
                        transfer_plane=None) -> np.ndarray:
    if reply is None:
        raise ConnectionError("encode worker returned nothing")
    if reply["kind"] == "descriptor":
        if transfer_plane is None:
            raise ValueError("encode worker offered a device descriptor "
                             "but this processor has no transfer plane")
        blocks = await transfer_plane.pull(reply["meta"])
        return np.asarray(blocks[0])
    arr = np.frombuffer(reply["data"], dtype=reply["dtype"])
    return arr.reshape(reply["shape"]).copy()


def _encode_payload(image_ref: str, transfer_plane) -> dict:
    """The encode request: carries the puller's fabric id so the worker
    offers a descriptor only when this processor can actually pull it."""
    payload = {"image": image_ref}
    if transfer_plane is not None:
        payload["fabric"] = transfer_plane.fabric
    return payload


async def fetch_embeddings(rpc_client, image_ref: str,
                           transfer_plane=None) -> np.ndarray:
    """Processor-side: ask the encode worker for one image's embeddings,
    pulling device-direct when both sides run a reachable plane."""
    reply = None
    async for msg in rpc_client.call(
            ENCODE_ENDPOINT, _encode_payload(image_ref, transfer_plane)):
        reply = msg
    return await _decode_reply(reply, transfer_plane)


class MultimodalAttach:
    """Frontend hook wiring `image_url` chat parts into the request path
    (VERDICT r4 next-7: the processor existed but no HTTP request could
    reach it; reference `examples/multimodal_v1/components/processor.py`
    parses image parts out of live chat requests).

    The chat template renders TEXT parts only (ChatMessage.text), so the
    preprocessed token ids are already image-free; attach() prepends one
    placeholder per embedding row and hangs the embeddings on the
    request (LLaVA-style prefix convention).  Embeddings come from an
    encode worker discovered through the runtime (`encoder/encode`
    endpoint), or a local in-process encoder for single-process
    frontends."""

    def __init__(self, endpoint=None, local_encoder=None,
                 transfer_plane=None) -> None:
        if endpoint is None and local_encoder is None:
            raise ValueError("need an encoder endpoint or local encoder")
        self._endpoint = endpoint
        self._client = None
        self._local = local_encoder
        self._plane = transfer_plane

    @staticmethod
    def image_refs(messages) -> List[str]:
        refs: List[str] = []
        for m in messages:
            content = getattr(m, "content", None)
            if content is None and isinstance(m, dict):
                content = m.get("content")
            if not isinstance(content, list):
                continue
            for part in content:
                if not isinstance(part, dict):
                    continue
                if part.get("type") == "image_url":
                    url = part.get("image_url")
                    if isinstance(url, dict):
                        url = url.get("url", "")
                    refs.append(url or "")
        return refs

    async def _fetch(self, ref: str) -> np.ndarray:
        if self._local is not None:
            return self._local.encode(ref)
        if self._client is None:
            self._client = await self._endpoint.client()
        reply = None
        async for msg in self._client.generate(
                _encode_payload(ref, self._plane)):
            reply = msg
        return await _decode_reply(reply, self._plane)

    async def attach(self, messages, pre):
        """Mutates `pre` (token_ids + prompt_embeds) for the request's
        image parts; no-op when there are none."""
        refs = self.image_refs(messages)
        if not refs:
            return pre
        embeds = [await self._fetch(ref) for ref in refs]
        emb = np.concatenate(embeds, axis=0)
        pre.token_ids = [PLACEHOLDER_TOKEN] * emb.shape[0] \
            + list(pre.token_ids)
        pre.prompt_embeds = emb
        return pre


class MultimodalProcessor:
    """Chat request with image parts → (token_ids, prompt_embeds).

    Prompt layout follows the LLaVA-style prefix convention the
    reference example uses: all image embedding spans first (placeholder
    token ids), then the templated text tokens."""

    def __init__(self, tokenizer, rpc_client, transfer_plane=None) -> None:
        self.tokenizer = tokenizer
        self.rpc = rpc_client
        self.plane = transfer_plane

    @staticmethod
    def split_images(messages: List[dict]) -> Tuple[List[dict], List[str]]:
        """Extract image_url parts; returns (text-only messages, refs)."""
        images: List[str] = []
        out: List[dict] = []
        for m in messages:
            content = m.get("content")
            if isinstance(content, list):
                texts = []
                for part in content:
                    if part.get("type") == "image_url":
                        url = part.get("image_url")
                        if isinstance(url, dict):
                            url = url.get("url", "")
                        images.append(url or "")
                    elif part.get("type") == "text":
                        texts.append(part.get("text", ""))
                out.append({**m, "content": " ".join(texts)})
            else:
                out.append(m)
        return out, images

    async def build(self, messages: List[dict]
                    ) -> Tuple[List[int], Optional[np.ndarray]]:
        text_msgs, images = self.split_images(messages)
        text = " ".join(m.get("content") or "" for m in text_msgs)
        text_tokens = self.tokenizer.encode(text)
        if not images:
            return text_tokens, None
        embeds = []
        for ref in images:
            embeds.append(await fetch_embeddings(self.rpc, ref,
                                                 self.plane))
        emb = np.concatenate(embeds, axis=0)
        tokens = [PLACEHOLDER_TOKEN] * emb.shape[0] + list(text_tokens)
        return tokens, emb
