"""Perf recording + event record/replay.

Role of the reference's `lib/llm/src/perf.rs` (stream timing recorder:
per-response arrival timestamps), `recorder.rs` (JSONL event recorder)
and `kv_router/recorder.rs` (KV-event record + replay into an indexer).

- `StreamRecorder` wraps any EngineClient and records, per request, the
  arrival time of every token delta: TTFT, ITLs, and summary percentiles
  come out of the raw timeline, not from pre-aggregated histograms — the
  difference matters when diagnosing tail stalls (the reference keeps
  raw arrivals for the same reason, `perf.rs:1-30`).
- `JsonlRecorder` appends timestamped events to a JSONL file and
  `replay_jsonl` streams them back.
- `record_kv_events` subscribes a control plane's `kv_events` subject
  into a JSONL file; `replay_kv_events` feeds a recording back into a
  KvRouter/KvIndexer — reproducing a production routing state offline.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from typing import AsyncIterator, Dict, List, Optional

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Stream timing


@dataclass
class StreamTiming:
    """Raw per-request timeline (monotonic seconds)."""

    request_id: str
    start: float
    arrivals: List[float] = field(default_factory=list)  # per-delta times
    tokens: List[int] = field(default_factory=list)      # tokens per delta
    finished: bool = False

    @property
    def ttft(self) -> Optional[float]:
        return self.arrivals[0] - self.start if self.arrivals else None

    @property
    def itls(self) -> List[float]:
        return [b - a for a, b in zip(self.arrivals, self.arrivals[1:])]

    @property
    def output_tokens(self) -> int:
        return sum(self.tokens)

    @property
    def duration(self) -> Optional[float]:
        return self.arrivals[-1] - self.start if self.arrivals else None


def _pct(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    s = sorted(values)
    idx = min(len(s) - 1, max(0, round(q * (len(s) - 1))))
    return s[idx]


class StreamRecorder:
    """EngineClient decorator recording stream timings."""

    def __init__(self, inner) -> None:
        self.inner = inner
        self.timings: Dict[str, StreamTiming] = {}

    async def generate(self, request) -> AsyncIterator:
        t = StreamTiming(request_id=request.request_id,
                         start=time.monotonic())
        self.timings[request.request_id] = t
        async for delta in self.inner.generate(request):
            if delta.token_ids:
                t.arrivals.append(time.monotonic())
                t.tokens.append(len(delta.token_ids))
            if delta.finished:
                t.finished = True
            yield delta

    def summary(self) -> dict:
        """Aggregate percentiles across recorded streams (the numbers the
        reference's profiler tables report: TTFT/ITL p50/p95)."""
        done = [t for t in self.timings.values() if t.arrivals]
        ttfts = [t.ttft for t in done if t.ttft is not None]
        itls = [x for t in done for x in t.itls]
        total_tokens = sum(t.output_tokens for t in done)
        span = (max(t.arrivals[-1] for t in done)
                - min(t.start for t in done)) if done else 0.0
        return {
            "requests": len(done),
            "output_tokens": total_tokens,
            "ttft_p50": _pct(ttfts, 0.50),
            "ttft_p95": _pct(ttfts, 0.95),
            "itl_p50": _pct(itls, 0.50),
            "itl_p95": _pct(itls, 0.95),
            "tok_s": total_tokens / span if span > 0 else 0.0,
        }


# ---------------------------------------------------------------------------
# JSONL event recording


class JsonlRecorder:
    """Append-only timestamped JSONL event log (reference recorder.rs)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "a")
        self.count = 0

    def record(self, kind: str, payload: dict) -> None:
        self._f.write(json.dumps({
            "ts": time.time(), "kind": kind, "payload": payload}) + "\n")
        self.count += 1

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def replay_jsonl(path: str):
    """Yield (ts, kind, payload) tuples from a recording."""
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            yield d["ts"], d["kind"], d["payload"]


# ---------------------------------------------------------------------------
# KV-event record/replay (kv_router/recorder.rs)


async def record_kv_events(cp, path: str,
                           subject: str = "kv_events") -> asyncio.Task:
    """Subscribe `kv_events` into a JSONL file; returns the pump task
    (cancel it to stop; the recorder is flushed per event)."""
    rec = JsonlRecorder(path)
    sub = await cp.subscribe(subject)

    async def pump():
        try:
            while True:
                payload = await sub.next()
                rec.record("kv_event", payload)
                rec.flush()
        except (asyncio.CancelledError, ConnectionError):
            raise
        finally:
            sub.cancel()
            rec.close()

    return asyncio.create_task(pump())


def replay_kv_events(path: str, router) -> int:
    """Apply a recording to a KvRouter (or anything with `apply_event`);
    returns the number of events applied.  Rebuilds the exact radix-index
    state a production run had — offline routing analysis."""
    from dynamo_tpu.llm.kv_router.protocols import RouterEvent

    n = 0
    for _, kind, payload in replay_jsonl(path):
        if kind != "kv_event":
            continue
        router.apply_event(RouterEvent.from_dict(payload))
        n += 1
    return n
