"""Tool-call postprocessor: parse function calls out of generated text.

Role of the reference's `lib/llm/src/postprocessor/tool_calling/
{parsers,json_parser}.rs`: model families emit tool calls in different
wire formats; the parser normalises them into OpenAI `tool_calls`
entries.  Formats covered (the reference's parser matrix):

- hermes:  <tool_call>{"name": ..., "arguments": {...}}</tool_call>
- mistral: [TOOL_CALLS][{"name": ..., "arguments": {...}}, ...]
- llama3_json / plain JSON: the whole completion is one call object or a
  list of them ({"name": ..., "arguments"|"parameters": {...}})
- "auto" tries each in that order.

Unparseable text returns (text, []) — the completion stays a normal
assistant message, never an error (parser failures must not break
serving; reference behaviour)."""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional, Tuple

HERMES_RE = re.compile(r"<tool_call>\s*(\{.*?\})\s*</tool_call>", re.DOTALL)
MISTRAL_TAG = "[TOOL_CALLS]"


def _call_entry(name: str, arguments: Any) -> Dict[str, Any]:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


def _from_obj(obj: Any) -> Optional[Dict[str, Any]]:
    if not isinstance(obj, dict) or "name" not in obj:
        return None
    args = obj.get("arguments", obj.get("parameters", {}))
    return _call_entry(obj["name"], args)


def _parse_hermes(text: str):
    calls = []
    for m in HERMES_RE.finditer(text):
        try:
            entry = _from_obj(json.loads(m.group(1)))
        except json.JSONDecodeError:
            continue
        if entry:
            calls.append(entry)
    if not calls:
        return text, []
    content = HERMES_RE.sub("", text).strip()
    return content, calls


def _parse_mistral(text: str):
    idx = text.find(MISTRAL_TAG)
    if idx < 0:
        return text, []
    payload = text[idx + len(MISTRAL_TAG):].strip()
    try:
        data = json.loads(payload)
    except json.JSONDecodeError:
        return text, []
    if isinstance(data, dict):
        data = [data]
    calls = [e for e in (_from_obj(o) for o in data) if e]
    if not calls:
        return text, []  # keep the full text: nothing valid was extracted
    return text[:idx].strip(), calls


def _parse_json(text: str):
    stripped = text.strip()
    # Fenced model output (```json ... ```) is common; unwrap it.
    if stripped.startswith("```"):
        stripped = re.sub(r"^```(?:json)?\s*|\s*```$", "", stripped,
                          flags=re.DOTALL).strip()
    if not (stripped.startswith("{") or stripped.startswith("[")):
        return text, []
    try:
        data = json.loads(stripped)
    except json.JSONDecodeError:
        return text, []
    if isinstance(data, dict):
        data = [data]
    if not isinstance(data, list):
        return text, []
    calls = [e for e in (_from_obj(o) for o in data) if e]
    if calls and len(calls) == len(data):
        return "", calls
    return text, []


PARSERS = {
    "hermes": _parse_hermes,
    "mistral": _parse_mistral,
    "json": _parse_json,
    "llama3_json": _parse_json,
}


def parse_tool_calls(text: str, fmt: str = "auto"
                     ) -> Tuple[str, List[Dict[str, Any]]]:
    """Returns (remaining_content, tool_calls).  tool_calls empty when
    nothing parses — the text passes through untouched."""
    if fmt != "auto":
        parser = PARSERS.get(fmt)
        if parser is None:
            raise ValueError(f"unknown tool-call format {fmt!r}; "
                             f"have {sorted(PARSERS)} or 'auto'")
        return parser(text)
    for parser in (_parse_hermes, _parse_mistral, _parse_json):
        content, calls = parser(text)
        if calls:
            return content, calls
    return text, []
