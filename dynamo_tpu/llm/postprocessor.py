"""Tool-call postprocessor: parse function calls out of generated text.

Role of the reference's `lib/llm/src/postprocessor/tool_calling/
{parsers,json_parser}.rs`: model families emit tool calls in different
wire formats; the parser normalises them into OpenAI `tool_calls`
entries.  Formats covered (the reference's parser matrix):

- hermes:  <tool_call>{"name": ..., "arguments": {...}}</tool_call>
- mistral: [TOOL_CALLS][{"name": ..., "arguments": {...}}, ...]
- llama3_json / plain JSON: the whole completion is one call object or a
  list of them ({"name": ..., "arguments"|"parameters": {...}})
- "auto" tries each in that order.

Unparseable text returns (text, []) — the completion stays a normal
assistant message, never an error (parser failures must not break
serving; reference behaviour)."""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional, Tuple

HERMES_RE = re.compile(r"<tool_call>\s*(\{.*?\})\s*</tool_call>", re.DOTALL)
MISTRAL_TAG = "[TOOL_CALLS]"


def _call_entry(name: str, arguments: Any) -> Dict[str, Any]:
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


def _from_obj(obj: Any) -> Optional[Dict[str, Any]]:
    if not isinstance(obj, dict) or "name" not in obj:
        return None
    args = obj.get("arguments", obj.get("parameters", {}))
    return _call_entry(obj["name"], args)


def _parse_hermes(text: str):
    calls = []
    for m in HERMES_RE.finditer(text):
        try:
            entry = _from_obj(json.loads(m.group(1)))
        except json.JSONDecodeError:
            continue
        if entry:
            calls.append(entry)
    if not calls:
        return text, []
    content = HERMES_RE.sub("", text).strip()
    return content, calls


def _parse_mistral(text: str):
    idx = text.find(MISTRAL_TAG)
    if idx < 0:
        return text, []
    payload = text[idx + len(MISTRAL_TAG):].strip()
    try:
        data = json.loads(payload)
    except json.JSONDecodeError:
        return text, []
    if isinstance(data, dict):
        data = [data]
    calls = [e for e in (_from_obj(o) for o in data) if e]
    if not calls:
        return text, []  # keep the full text: nothing valid was extracted
    return text[:idx].strip(), calls


def _parse_json(text: str):
    stripped = text.strip()
    # Fenced model output (```json ... ```) is common; unwrap it.
    if stripped.startswith("```"):
        stripped = re.sub(r"^```(?:json)?\s*|\s*```$", "", stripped,
                          flags=re.DOTALL).strip()
    if not (stripped.startswith("{") or stripped.startswith("[")):
        return text, []
    try:
        data = json.loads(stripped)
    except json.JSONDecodeError:
        return text, []
    if isinstance(data, dict):
        data = [data]
    if not isinstance(data, list):
        return text, []
    calls = [e for e in (_from_obj(o) for o in data) if e]
    if calls and len(calls) == len(data):
        return "", calls
    return text, []


PARSERS = {
    "hermes": _parse_hermes,
    "mistral": _parse_mistral,
    "json": _parse_json,
    "llama3_json": _parse_json,
}


def forced_tool_name(tool_choice: Any,
                     tools: Optional[List[Dict[str, Any]]]) -> Optional[str]:
    """The function name a `tool_choice` value forces, if any.

    `{"type": "function", "function": {"name": ...}}` pins that name;
    `"required"` with exactly one declared tool pins that tool (with
    several tools the model still chooses — nothing to force here).
    """
    if isinstance(tool_choice, dict):
        return (tool_choice.get("function") or {}).get("name")
    if tool_choice == "required" and tools and len(tools) == 1:
        return (tools[0].get("function") or {}).get("name")
    return None


def force_tool_call(text: str, name: str) -> List[Dict[str, Any]]:
    """Wrap a completion as ONE call to `name` (forced tool_choice: the
    whole generation is the arguments payload, OpenAI semantics — no
    marker syntax expected from the model)."""
    return [_call_entry(name, text)]


class StreamingToolCallParser:
    """Incremental tool-call extraction for SSE chat streams.

    Mirrors the unary `parse_tool_calls` matrix, but emits OpenAI-spec
    `delta.tool_calls` entries mid-stream: the first delta of call `i`
    carries `index`/`id`/`type`/`function.name` (arguments ""), then
    argument fragments follow as `{"index": i, "function":
    {"arguments": ...}}`.

    Strategy per format:
    - hermes: text streams through as content; `<tool_call>` starts a
      capture that is parsed and emitted the moment `</tool_call>`
      closes — truly incremental for multi-call generations.
    - mistral `[TOOL_CALLS]` and bare-JSON completions: the payload is
      one JSON document, unparseable until complete, so it buffers to
      end-of-stream and the calls are emitted from `finish()`.
    - a tail that might still grow into a marker (e.g. "<tool") is
      jailed, exactly like the stop-sequence jail in the detokenizer.
    - `forced_name` (pinned tool_choice): no marker syntax expected —
      the header delta goes out at the first token and every text chunk
      streams as an arguments fragment.
    """

    _HERMES_OPEN = "<tool_call>"
    _HERMES_CLOSE = "</tool_call>"

    def __init__(self, fmt: str = "auto",
                 forced_name: Optional[str] = None) -> None:
        if fmt != "auto" and fmt not in PARSERS:
            raise ValueError(f"unknown tool-call format {fmt!r}; "
                             f"have {sorted(PARSERS)} or 'auto'")
        self.fmt = fmt
        self.forced_name = forced_name
        self.calls_emitted = 0
        self._jail = ""            # possible marker prefix, held back
        self._capture = ""         # text inside an active capture
        self._capturing: Optional[str] = None   # None|"hermes"|"tail"
        self._started = False      # saw any non-whitespace yet
        self._forced_index: Optional[int] = None
        if fmt == "hermes":
            self._markers = (self._HERMES_OPEN,)
        elif fmt == "mistral":
            self._markers = (MISTRAL_TAG,)
        elif fmt == "auto":
            self._markers = (self._HERMES_OPEN, MISTRAL_TAG)
        else:                      # json family: no mid-stream markers
            self._markers = ()

    # -- emission helpers -------------------------------------------------

    def _emit_calls(self, calls: List[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
        out = []
        for c in calls:
            idx = self.calls_emitted
            self.calls_emitted += 1
            out.append({"index": idx, "id": c["id"], "type": "function",
                        "function": {"name": c["function"]["name"],
                                     "arguments": ""}})
            args = c["function"]["arguments"]
            if args:
                out.append({"index": idx,
                            "function": {"arguments": args}})
        return out

    def _forced_header(self) -> Dict[str, Any]:
        self._forced_index = self.calls_emitted
        self.calls_emitted += 1
        entry = _call_entry(self.forced_name, "")
        return {"index": self._forced_index, "id": entry["id"],
                "type": "function",
                "function": {"name": self.forced_name, "arguments": ""}}

    def _marker_jail(self, text: str) -> Tuple[str, str]:
        """Split off the longest tail that is a proper prefix of a
        marker (it may still complete in the next chunk)."""
        max_hold = max((len(m) for m in self._markers), default=1) - 1
        for k in range(min(max_hold, len(text)), 0, -1):
            tail = text[-k:]
            if any(m.startswith(tail) for m in self._markers):
                return text[:-k], tail
        return text, ""

    # -- the incremental API ----------------------------------------------

    def push(self, text: str) -> Tuple[str, List[Dict[str, Any]]]:
        """Feed a content delta; returns (releasable_content, deltas)."""
        if self.forced_name is not None:
            deltas = []
            if self._forced_index is None:
                deltas.append(self._forced_header())
            if text:
                deltas.append({"index": self._forced_index,
                               "function": {"arguments": text}})
            return "", deltas

        deltas: List[Dict[str, Any]] = []
        content: List[str] = []
        work = self._jail + text
        self._jail = ""
        while work:
            if self._capturing == "tail":
                self._capture += work
                break
            if self._capturing == "hermes":
                self._capture += work
                end = self._capture.find(self._HERMES_CLOSE)
                if end == -1:
                    break
                seg = self._capture[: end + len(self._HERMES_CLOSE)]
                work = self._capture[end + len(self._HERMES_CLOSE):]
                self._capture = ""
                self._capturing = None
                # Malformed JSON inside the markers: the unary parser
                # keeps the segment as content, so the stream must too
                # (rest == "" whenever the parse succeeded).
                rest, calls = _parse_hermes(seg)
                content.append(rest)
                deltas.extend(self._emit_calls(calls))
                continue
            if not self._started:
                stripped = work.lstrip()
                if not stripped:
                    self._jail = work   # pure whitespace: defer verdict
                    break
                self._started = True
                # A JSON-looking stream head means the WHOLE completion
                # may be one tool-call document: buffer to the end (the
                # unary parser decides at finish).
                if self.fmt in ("json", "llama3_json") or (
                        self.fmt == "auto" and stripped[0] in "{[`"):
                    self._capturing = "tail"
                    continue
            found = [(work.find(m), m) for m in self._markers
                     if m in work]
            if found:
                pos, marker = min(found)
                content.append(work[:pos])
                work = work[pos:]
                if marker == self._HERMES_OPEN:
                    self._capturing = "hermes"
                else:               # [TOOL_CALLS]: buffer to end
                    self._capturing = "tail"
                continue
            release, self._jail = self._marker_jail(work)
            content.append(release)
            break
        return "".join(content), deltas

    def finish(self) -> Tuple[str, List[Dict[str, Any]], bool]:
        """End of stream: flush buffers.  Returns (content, deltas,
        any_calls) — `any_calls` decides the `tool_calls` finish_reason."""
        if self.forced_name is not None:
            deltas = ([self._forced_header()]
                      if self._forced_index is None else [])
            return "", deltas, True
        leftover = self._jail
        self._jail = ""
        if self._capturing == "tail":
            fmt = self.fmt if self.fmt in PARSERS else "auto"
            text, calls = parse_tool_calls(self._capture + leftover, fmt)
        elif self._capturing == "hermes":
            # Unterminated <tool_call>: nothing parseable — the capture
            # is plain content after all.
            text, calls = self._capture + leftover, []
        else:
            text, calls = leftover, []
        self._capture = ""
        self._capturing = None
        deltas = self._emit_calls(calls)
        return text, deltas, self.calls_emitted > 0


def parse_tool_calls(text: str, fmt: str = "auto"
                     ) -> Tuple[str, List[Dict[str, Any]]]:
    """Returns (remaining_content, tool_calls).  tool_calls empty when
    nothing parses — the text passes through untouched."""
    if fmt != "auto":
        parser = PARSERS.get(fmt)
        if parser is None:
            raise ValueError(f"unknown tool-call format {fmt!r}; "
                             f"have {sorted(PARSERS)} or 'auto'")
        return parser(text)
    for parser in (_parse_hermes, _parse_mistral, _parse_json):
        content, calls = parser(text)
        if calls:
            return content, calls
    return text, []
