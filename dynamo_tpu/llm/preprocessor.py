"""OpenAI request → internal engine request (template + tokenize).

Role of the reference's `OpenAIPreprocessor` (`lib/llm/src/preprocessor.rs:94`
+ `preprocessor/prompt/template/{oai,tokcfg}.rs`): render the chat template,
tokenize, and fold the OpenAI sampling surface + model generation defaults
into the internal request the engine consumes.

Chat templates are Jinja2 (same format HF ships in tokenizer_config.json);
a model card may carry its own template string, otherwise a Llama-3-style
default is used.  The rendered prompt is attached as an annotation
(reference `formatted_prompt` annotation) for debuggability.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

import jinja2

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
)
from dynamo_tpu.llm.tokenizer import Tokenizer

DEFAULT_CHAT_TEMPLATE = (
    "{% if tools %}"
    "<|start_header_id|>system<|end_header_id|>\n\n"
    "You may call these tools; respond with a JSON object "
    '{"name": ..., "arguments": ...} to invoke one:\n'
    "{{ tools | tojson }}<|eot_id|>"
    "{% endif %}"
    "{% for message in messages %}"
    "<|start_header_id|>{{ message.role }}<|end_header_id|>\n\n"
    "{{ message.content }}"
    "{% if message.tool_calls %}"
    "{{ message.tool_calls | tojson }}"
    "{% endif %}"
    "<|eot_id|>"
    "{% endfor %}"
    "{% if add_generation_prompt %}"
    "<|start_header_id|>assistant<|end_header_id|>\n\n"
    "{% endif %}"
)


@dataclass
class PreprocessedRequest:
    """The internal request form handed to routing + engine (reference
    `protocols/common/preprocessor.rs` PreprocessedRequest)."""

    request_id: str
    model: str
    token_ids: List[int]
    sampling: SamplingParams
    stop_sequences: List[str] = field(default_factory=list)
    annotations: Dict[str, str] = field(default_factory=dict)
    # Multimodal: [n, hidden] embeddings occupying prompt positions
    # [0, n) — the encode-worker output (llm/multimodal.py); token_ids
    # carry placeholders there.
    prompt_embeds: Optional[object] = None


class OpenAIPreprocessor:
    def __init__(
        self,
        tokenizer: Tokenizer,
        chat_template: Optional[str] = None,
        default_max_tokens: int = 512,
    ) -> None:
        self.tokenizer = tokenizer
        self.default_max_tokens = default_max_tokens
        env = jinja2.Environment(
            loader=jinja2.BaseLoader(), autoescape=False,
            trim_blocks=True, lstrip_blocks=True)
        self._template = env.from_string(chat_template or DEFAULT_CHAT_TEMPLATE)

    # -- chat -------------------------------------------------------------

    def render_chat(self, request: ChatCompletionRequest) -> str:
        messages = []
        for m in request.messages:
            msg = {"role": m.role, "content": m.text()}
            if m.tool_calls:
                msg["tool_calls"] = m.tool_calls
            messages.append(msg)
        # Declared tools flow into the template context (the `tools`
        # variable HF chat templates consume) — without this the model
        # never sees the tool schemas and can't emit calls.
        # tool_choice (OpenAI semantics): "none" hides the schemas for
        # this turn; {"type":"function","function":{"name": N}} narrows
        # them to the forced tool.
        tools = request.tools or None
        choice = request.tool_choice
        if choice == "none":
            tools = None
        elif isinstance(choice, dict):
            forced = choice.get("function", {}).get("name")
            if forced and not tools:
                # ADVICE r3: forcing a named function with no tools
                # declared was silently ignored — inconsistent with the
                # unknown-tool 400 below.  OpenAI semantics: client error.
                raise ValueError(
                    f"tool_choice forces tool {forced!r} but the request "
                    "declares no tools")
            if forced and tools:
                tools = [t for t in tools
                         if t.get("function", {}).get("name") == forced]
                if not tools:
                    # OpenAI semantics: forcing an undeclared tool is a
                    # client error, not a silent fall-back to all tools.
                    raise ValueError(
                        f"tool_choice forces unknown tool {forced!r}")
        return self._template.render(
            messages=messages, add_generation_prompt=True, tools=tools)

    def preprocess_chat(
        self, request: ChatCompletionRequest, request_id: str
    ) -> PreprocessedRequest:
        prompt = self.render_chat(request)
        token_ids = self.tokenizer.encode(prompt)
        return self._build(request, request_id, token_ids,
                           annotations={"formatted_prompt": prompt})

    # -- completions ------------------------------------------------------

    def preprocess_completion(
        self, request: CompletionRequest, request_id: str
    ) -> PreprocessedRequest:
        prompt = request.prompt
        if isinstance(prompt, str):
            token_ids = self.tokenizer.encode(prompt)
            ann = {"formatted_prompt": prompt}
        elif prompt and isinstance(prompt[0], int):
            token_ids = list(prompt)  # pre-tokenized
            ann = {}
        else:
            raise ValueError("batched prompts not supported; send one request per prompt")
        return self._build(request, request_id, token_ids, annotations=ann)

    # -- shared -----------------------------------------------------------

    def _build(self, request, request_id, token_ids, annotations):
        sampling = SamplingParams(
            # OpenAI's documented default is temperature=1.0 (stochastic);
            # clients must opt in to greedy with temperature=0.
            temperature=request.temperature if request.temperature is not None else 1.0,
            top_k=request.top_k or 0,
            top_p=request.top_p if request.top_p is not None else 1.0,
            max_tokens=request.effective_max_tokens(self.default_max_tokens),
            stop_token_ids=tuple(self.tokenizer.eos_token_ids),
            seed=request.seed,
            logprobs=bool(request.logprobs),
        )
        return PreprocessedRequest(
            request_id=request_id,
            model=request.model,
            token_ids=token_ids,
            sampling=sampling,
            stop_sequences=request.stop_list(),
            annotations=annotations,
        )
