"""Wire protocols: OpenAI-compatible API types, SSE codec, internal request
forms (reference `lib/llm/src/protocols/` — SURVEY.md §2.2)."""
