"""OpenAI-compatible request/response types + SSE codec.

Covers the surface the reference serves (`lib/llm/src/http/service/
openai.rs` routes: /v1/chat/completions, /v1/completions, /v1/models) with
pydantic models — validation at the HTTP boundary like the reference's
`protocols/openai/validate.rs`.

Streaming: `sse_encode` produces the `data: {json}\n\n` framing with the
terminal `data: [DONE]` sentinel (reference `protocols/codec.rs`).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, Field, field_validator


# ---------------------------------------------------------------------------
# Shared


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ErrorDetail(BaseModel):
    message: str
    type: str = "invalid_request_error"
    code: Optional[str] = None


class ErrorResponse(BaseModel):
    error: ErrorDetail


def request_id(prefix: str = "cmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def now_ts() -> int:
    return int(time.time())


# ---------------------------------------------------------------------------
# Chat completions


class ChatMessage(BaseModel):
    role: Literal["system", "user", "assistant", "tool"]
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None

    def text(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if self.content is None:
            return ""
        # Multi-part content: concatenate textual parts — chat uses
        # "text", the Responses API uses "input_text"/"output_text"
        # (image parts are the multimodal pipeline's job).
        return "".join(
            p.get("text", "") for p in self.content
            if p.get("type") in ("text", "input_text", "output_text"))


class SamplingFields(BaseModel):
    """Sampling knobs shared by chat + text completions."""

    max_tokens: Optional[int] = Field(default=None, ge=1)
    max_completion_tokens: Optional[int] = Field(default=None, ge=1)
    temperature: Optional[float] = Field(default=None, ge=0.0, le=2.0)
    top_p: Optional[float] = Field(default=None, gt=0.0, le=1.0)
    top_k: Optional[int] = Field(default=None, ge=0)
    stop: Optional[Union[str, List[str]]] = None
    seed: Optional[int] = None
    frequency_penalty: Optional[float] = Field(default=None, ge=-2.0, le=2.0)
    presence_penalty: Optional[float] = Field(default=None, ge=-2.0, le=2.0)
    n: int = 1
    logprobs: Optional[Union[bool, int]] = None
    stream: bool = False
    stream_options: Optional[Dict[str, Any]] = None
    user: Optional[str] = None
    # Reference NVext extension escape hatch (protocols/openai NVext).
    nvext: Optional[Dict[str, Any]] = None

    @field_validator("n")
    @classmethod
    def _n_sane(cls, v):
        if v < 1 or v > 8:
            raise ValueError("n must be in [1, 8]")
        return v

    def stop_list(self) -> List[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def effective_max_tokens(self, default: int = 512) -> int:
        return self.max_completion_tokens or self.max_tokens or default


class ChatCompletionRequest(SamplingFields):
    model: str
    messages: List[ChatMessage]
    # Tool calling (reference postprocessor/tool_calling): declared tools
    # flow into the chat template; responses are parsed for call syntax.
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    # Parser family for tool-call extraction ("auto" tries them all).
    tool_call_parser: str = "auto"

    @field_validator("tool_call_parser")
    @classmethod
    def _known_parser(cls, v):
        # Validate BEFORE generation runs — an unknown parser failing
        # after the tokens were produced would waste the whole request.
        from dynamo_tpu.llm.postprocessor import PARSERS

        if v != "auto" and v not in PARSERS:
            raise ValueError(
                f"unknown tool_call_parser {v!r}; have "
                f"{sorted(PARSERS)} or 'auto'")
        return v

    @field_validator("messages")
    @classmethod
    def _nonempty(cls, v):
        if not v:
            raise ValueError("messages must be non-empty")
        return v


class ChatChoiceDelta(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None
    # Streaming tool-call fragments (OpenAI spec): the first delta of a
    # call carries index/id/type/function.name, later ones append to
    # function.arguments.
    tool_calls: Optional[List[Dict[str, Any]]] = None


class ChatStreamChoice(BaseModel):
    index: int = 0
    delta: ChatChoiceDelta
    finish_reason: Optional[str] = None
    logprobs: Optional["ChatLogprobs"] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=now_ts)
    model: str
    choices: List[ChatStreamChoice]
    usage: Optional[Usage] = None


class ChatLogprobEntry(BaseModel):
    token: str
    logprob: float


class ChatLogprobs(BaseModel):
    content: List[ChatLogprobEntry] = Field(default_factory=list)


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: Optional[str] = None
    logprobs: Optional[ChatLogprobs] = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=now_ts)
    model: str
    choices: List[ChatChoice]
    usage: Usage = Field(default_factory=Usage)


# ---------------------------------------------------------------------------
# Text completions


class CompletionRequest(SamplingFields):
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    echo: bool = False


class CompletionChoice(BaseModel):
    index: int = 0
    text: str
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class CompletionResponse(BaseModel):
    """Doubles as the SSE chunk type when streaming (same `text_completion`
    object tag, OpenAI convention); chunks leave `usage` unset so clients
    never read zeroed counts mid-stream."""

    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=now_ts)
    model: str
    choices: List[CompletionChoice]
    usage: Optional[Usage] = None


# ---------------------------------------------------------------------------
# Responses API (the newer OpenAI surface; reference protocols/openai/
# responses.rs)


class ResponsesRequest(BaseModel):
    model: str
    input: Union[str, List[Dict[str, Any]]]
    instructions: Optional[str] = None
    max_output_tokens: Optional[int] = Field(default=None, ge=1)
    temperature: Optional[float] = Field(default=None, ge=0.0, le=2.0)
    top_p: Optional[float] = Field(default=None, gt=0.0, le=1.0)
    stream: bool = False

    def as_chat(self) -> "ChatCompletionRequest":
        """Normalise to the internal chat form (one preprocessor path)."""
        messages: List[ChatMessage] = []
        if self.instructions:
            messages.append(ChatMessage(role="system",
                                        content=self.instructions))
        if isinstance(self.input, str):
            messages.append(ChatMessage(role="user", content=self.input))
        else:
            for item in self.input:
                role = item.get("role", "user")
                if role == "developer":  # Responses-API alias for system
                    role = "system"
                messages.append(ChatMessage(
                    role=role, content=item.get("content")))
        return ChatCompletionRequest(
            model=self.model, messages=messages,
            max_tokens=self.max_output_tokens,
            temperature=self.temperature, top_p=self.top_p)


class ResponseOutputText(BaseModel):
    type: Literal["output_text"] = "output_text"
    text: str


class ResponseOutputMessage(BaseModel):
    type: Literal["message"] = "message"
    role: Literal["assistant"] = "assistant"
    status: str = "completed"
    content: List[ResponseOutputText] = Field(default_factory=list)


class ResponsesUsage(BaseModel):
    input_tokens: int = 0
    output_tokens: int = 0
    total_tokens: int = 0


class ResponsesResponse(BaseModel):
    id: str
    object: Literal["response"] = "response"
    created_at: int = Field(default_factory=now_ts)
    model: str
    status: str = "completed"
    output: List[ResponseOutputMessage] = Field(default_factory=list)
    usage: ResponsesUsage = Field(default_factory=ResponsesUsage)

    @property
    def output_text(self) -> str:
        return "".join(t.text for m in self.output for t in m.content)


# ---------------------------------------------------------------------------
# Embeddings


class EmbeddingRequest(BaseModel):
    model: str
    input: Union[str, List[str], List[int], List[List[int]]]
    encoding_format: Literal["float", "base64"] = "float"
    user: Optional[str] = None

    def inputs(self) -> List[Union[str, List[int]]]:
        """Normalise to a list of prompts (strings or token lists)."""
        if isinstance(self.input, str):
            return [self.input]
        if not self.input:
            return []
        if isinstance(self.input[0], int):
            return [list(self.input)]
        return list(self.input)


class EmbeddingData(BaseModel):
    object: Literal["embedding"] = "embedding"
    index: int
    # float list, or a base64 string of packed float32 when the request
    # asked for encoding_format="base64" (OpenAI SDK default).
    embedding: Union[List[float], str]


class EmbeddingResponse(BaseModel):
    object: Literal["list"] = "list"
    data: List[EmbeddingData] = Field(default_factory=list)
    model: str
    usage: Usage = Field(default_factory=Usage)


# ---------------------------------------------------------------------------
# Models listing


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=now_ts)
    owned_by: str = "dynamo-tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: List[ModelInfo] = Field(default_factory=list)


# ---------------------------------------------------------------------------
# SSE codec


SSE_DONE = "data: [DONE]\n\n"


def sse_encode(payload: BaseModel) -> str:
    return f"data: {payload.model_dump_json(exclude_none=True)}\n\n"


def sse_encode_event(event: str, payload: dict) -> str:
    """Named-event SSE frame (the Responses API's `event:` framing)."""
    import json as _json

    return f"event: {event}\ndata: {_json.dumps(payload)}\n\n"


def sse_decode_line(line: str) -> Optional[dict]:
    """Parse one `data: ...` line; None for comments/blank/[DONE]."""
    line = line.strip()
    if not line.startswith("data:"):
        return None
    body = line[5:].strip()
    if body == "[DONE]":
        return None
    return json.loads(body)
