"""Model manager + the engine-client seam.

`EngineClient` is the streaming contract everything composes through — the
analog of the reference's `AsyncEngine` trait (`lib/runtime/src/engine.rs:
207`: `generate(SingleIn<Req>) -> ManyOut<Resp>`).  A local engine, a
KV-routed remote pool, and a mock engine all implement it, so the HTTP
frontend doesn't know which it's talking to (reference EngineConfig
{StaticFull, Dynamic} assembly, `entrypoint/input/common.rs:183`).

`ModelManager` is the frontend's model registry (reference
`discovery/model_manager.rs:33`): models appear/disappear at runtime as
workers register/deregister.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import AsyncIterator, Dict, List, Optional, Protocol

from dynamo_tpu.engine.engine import InferenceEngine, TokenDelta
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor, PreprocessedRequest
from dynamo_tpu.llm.tokenizer import Tokenizer


class EngineClient(Protocol):
    """Streaming generate contract (AsyncEngine analog)."""

    def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]: ...


# QoS classes (ISSUE 15): the frontend's x-dynamo-priority header (or a
# router/operator annotation) rides the request's annotations dict under
# this key; the worker resolves it to the scheduler's integer class.
PRIORITY_ANNOTATION = "priority"
PRIORITY_CLASSES = {"best_effort": 0, "best-effort": 0, "batch": 0,
                    "standard": 1, "default": 1,
                    "interactive": 2, "realtime": 2}


def priority_of(request) -> int:
    """Scheduler priority from a request's `priority` annotation: a
    named class or a bare integer; anything malformed (version-skewed
    frontend) is standard — never fail a request over QoS metadata."""
    raw = request.annotations.get(PRIORITY_ANNOTATION)
    if raw is None:
        return 1
    raw = str(raw).strip().lower()
    if raw in PRIORITY_CLASSES:
        return PRIORITY_CLASSES[raw]
    try:
        return max(0, min(2, int(raw)))
    except ValueError:
        return 1


class LocalEngineClient:
    """EngineClient over an in-process InferenceEngine."""

    def __init__(self, engine: InferenceEngine) -> None:
        self._engine = engine

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[TokenDelta]:
        import time as _time

        from dynamo_tpu.runtime import tracing
        from dynamo_tpu.runtime.ledger import ledger_of

        # Bind the serving task's span to the request id so engine-thread
        # spans (admission→first-token) parent under it — the in-process
        # analog of engine_wire_handler's worker-side binding.
        tracer = tracing.get_tracer()
        span = tracing.current_span()
        if span is not None:
            tracer.bind(request.request_id, span.ctx)
        # Request-ledger stamps (runtime/ledger.py), all ON THIS event
        # loop: engine queue/prefill/first_token phases from the scalars
        # the core parked at first-token time, plus a per-token decode
        # interval summary accumulated here — the engine thread and its
        # EngineStepCounters never see any of it.
        led = ledger_of(request)
        n_intervals = 0
        interval_sum = 0.0
        interval_max = 0.0
        last_t: Optional[float] = None
        try:
            async for delta in self._engine.generate(
                    request.request_id, request.token_ids, request.sampling,
                    prompt_embeds=request.prompt_embeds,
                    priority=priority_of(request)):
                if led is not None and delta.token_ids:
                    now = _time.monotonic()
                    if last_t is None:
                        self._stamp_first_token(led, request.request_id)
                    else:
                        gap = now - last_t
                        n_intervals += 1
                        interval_sum += gap
                        interval_max = max(interval_max, gap)
                    last_t = now
                if led is not None and delta.finished and n_intervals:
                    led.stamp("decode", dur=interval_sum, n=n_intervals,
                              max_s=round(interval_max, 6))
                yield delta
        finally:
            tracer.unbind(request.request_id)

    def _stamp_first_token(self, led, request_id: str) -> None:
        """Engine-phase stamps from the core's parked first-token
        timings: queue (arrival→prefill start), prefill (start→end,
        with cached-token and preemption attrs) and first_token
        (prefill end→first token emit) tile the engine's share of
        TTFT."""
        timings = self._engine.pop_ledger_timings(request_id)
        if timings is None:
            return
        arrival, pf_start, pf_end, first, prompt, cached, preempts = timings
        led.stamp("queue", dur=pf_start - arrival, t=pf_start)
        led.stamp("prefill", dur=pf_end - pf_start, t=pf_end,
                  prompt_tokens=prompt, cached_tokens=cached,
                  preempts=preempts)
        led.stamp("first_token", dur=first - pf_end, t=first)

    async def embed(self, token_lists):
        """Last-token hidden-state embeddings: [n, hidden] (the
        /v1/embeddings engine surface)."""
        return await self._engine.embed(token_lists)

    async def clear_kv_blocks(self) -> int:
        return await self._engine.clear_kv_blocks()


@dataclass
class ModelHandle:
    """Everything the frontend needs to serve one model."""

    name: str
    tokenizer: Tokenizer
    preprocessor: OpenAIPreprocessor
    client: EngineClient
    # Context ceiling for boundary validation (reference validate.rs);
    # requests whose prompt alone exceeds it get a 400, and max_tokens is
    # clamped to fit.
    max_context: int = 8192
    # Multimodal hook (llm/multimodal.MultimodalAttach): image_url chat
    # parts → prompt_embeds; None = text-only model.
    multimodal: Optional[object] = None


class ModelManager:
    def __init__(self) -> None:
        self._models: Dict[str, ModelHandle] = {}

    def register(self, handle: ModelHandle) -> None:
        self._models[handle.name] = handle

    def remove(self, name: str) -> Optional[ModelHandle]:
        return self._models.pop(name, None)

    def get(self, name: str) -> Optional[ModelHandle]:
        return self._models.get(name)

    def names(self) -> List[str]:
        return sorted(self._models)

    def __len__(self) -> int:
        return len(self._models)
