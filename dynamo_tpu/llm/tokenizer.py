"""Tokenizer abstraction + incremental detokenization.

Role of the reference's `lib/llm/src/tokenizers.rs` (Encoding, DecodeStream):
a thin trait over concrete tokenizers plus the *incremental* decode stream
the per-token hot loop needs — UTF-8 multi-byte sequences and BPE merge
boundaries mean you cannot just decode tokens one at a time and concatenate.

Backends:
- `HFTokenizer` — HuggingFace `tokenizers` (same Rust core the reference
  binds) loaded from a local `tokenizer.json`; no hub download here (the
  hub fetch lives in model_card/local_model resolution).
- `ByteTokenizer` — 1 byte = 1 token (+ specials), dependency-free; the
  test-fixture tokenizer (reference uses checked-in fixture models,
  `lib/llm/tests/data/sample-models/`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    """What the preprocessor/backend need from any tokenizer."""

    def encode(self, text: str) -> List[int]: ...
    def decode(self, token_ids: Sequence[int]) -> str: ...
    @property
    def eos_token_ids(self) -> tuple: ...
    @property
    def vocab_size(self) -> int: ...


class DecodeStream:
    """Incremental detokenizer (reference `tokenizers.rs` DecodeStream).

    Holds back output while the byte sequence at the tail is an incomplete
    UTF-8 character or the tokenizer would merge differently: we decode the
    window of all unflushed tokens and emit only the stable prefix (text
    whose bytes can no longer change when more tokens arrive).
    """

    REPLACEMENT = "�"

    def __init__(self, tokenizer: "Tokenizer") -> None:
        self._tok = tokenizer
        self._pending: List[int] = []
        self._emitted = ""  # text already flushed for the pending window
        self._held = 0      # consecutive pushes held on a broken tail

    def _past_prefix(self, text: str) -> str:
        """Text beyond the already-emitted prefix.  When the tokenizer
        re-merged the window so the flushed prefix changed, we cannot
        retract flushed text; emit only the part past the longest
        common prefix (minimises duplication)."""
        if text.startswith(self._emitted):
            return text[len(self._emitted):]
        common = 0
        for a, b in zip(self._emitted, text):
            if a != b:
                break
            common += 1
        return text[common:]

    def push(self, token_id: int) -> str:
        """Feed one token; returns newly-stable text (possibly "")."""
        self._pending.append(token_id)
        text = self._tok.decode(self._pending)
        if text.endswith(self.REPLACEMENT):
            # Tail may be an incomplete multi-byte sequence — hold
            # everything after the already-emitted prefix.  But only
            # while it could still complete: a UTF-8 char spans at most
            # 4 bytes (4 byte-level tokens), so a tail still broken
            # after 4 consecutive held pushes is invalid bytes, not an
            # unfinished char.  An unconditional hold turned any
            # gibberish burst into a stalled stream and an EMPTY final
            # text (flush drops the held tail), which is how the
            # multimodal e2e test got a contentless 200.
            self._held += 1
            if self._held < 4:
                return ""
            # Emit everything before the NEWEST token as U+FFFD; the
            # newest token stays pending — it may be the first byte of
            # a legitimate char that follows the garbage run (emitting
            # it too would corrupt that char).
            last = self._pending[-1]
            out = self._past_prefix(self._tok.decode(self._pending[:-1]))
            self._pending = [last]
            self._emitted = ""
            self._held = 1
            return out
        self._held = 0
        if not text.startswith(self._emitted):
            # Tokenizer re-merged the window so the already-flushed
            # prefix changed (see _past_prefix).
            out = self._past_prefix(text)
            self._pending = []
            self._emitted = ""
            return out
        out = text[len(self._emitted):]
        # Window can be reset at a clean boundary to bound decode cost.
        if len(self._pending) >= 16:
            self._pending = []
            self._emitted = ""
        else:
            self._emitted = text
        return out

    def flush(self) -> str:
        """Emit whatever is still held back (end of stream).  A held
        INCOMPLETE tail (at most 3 tokens — longer broken tails already
        burst out of push()) is dropped: the char never finished."""
        text = self._tok.decode(self._pending)
        out = text[len(self._emitted):] if text.startswith(self._emitted) else text
        self._pending = []
        self._emitted = ""
        self._held = 0
        return out.replace(self.REPLACEMENT, "")


@dataclass
class ByteTokenizer:
    """Byte-level tokenizer: token = byte value; specials above 255.

    Deterministic, zero-dependency, exercises real UTF-8 boundary handling
    in DecodeStream (multi-byte chars span multiple tokens).
    """

    bos_id: int = 256
    eos_id: int = 257

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, token_ids: Sequence[int]) -> str:
        data = bytes(t for t in token_ids if 0 <= t <= 255)
        return data.decode("utf-8", errors="replace")

    @property
    def eos_token_ids(self) -> tuple:
        return (self.eos_id,)

    @property
    def vocab_size(self) -> int:
        return 258


class HFTokenizer:
    """HuggingFace `tokenizers` wrapper loaded from a local tokenizer.json."""

    def __init__(self, path: str, eos_token_ids: Optional[Sequence[int]] = None,
                 eos_token: Optional[str] = None):
        from tokenizers import Tokenizer as _HFTok

        self._tok = _HFTok.from_file(path)
        self._init_eos(eos_token_ids, eos_token)

    @classmethod
    def from_json(cls, json_str: str,
                  eos_token_ids: Optional[Sequence[int]] = None,
                  eos_token: Optional[str] = None) -> "HFTokenizer":
        """Build from tokenizer.json CONTENTS — the artifact travels inside
        the model card so remote frontends never need the worker's
        filesystem (reference: MDC artifacts ride the NATS object store,
        `model_card.rs:241`)."""
        from tokenizers import Tokenizer as _HFTok

        self = cls.__new__(cls)
        self._tok = _HFTok.from_str(json_str)
        self._init_eos(eos_token_ids, eos_token)
        return self

    def _init_eos(self, eos_token_ids, eos_token) -> None:
        self._eos = tuple(eos_token_ids or ())
        candidates = ([eos_token] if eos_token else []) + [
            "</s>", "<|endoftext|>", "<|eot_id|>", "<|end_of_text|>"]
        if not self._eos:
            for name in candidates:
                tid = self._tok.token_to_id(name)
                if tid is not None:
                    self._eos += (tid,)

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, token_ids: Sequence[int]) -> str:
        return self._tok.decode(list(token_ids), skip_special_tokens=True)

    @property
    def eos_token_ids(self) -> tuple:
        return self._eos

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()
