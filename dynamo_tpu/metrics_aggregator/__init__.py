"""Namespace-wide metrics aggregator service.

Role of the reference's `components/metrics` Rust binary
(`components/metrics/src/main.rs:15-28`): subscribe to every worker's
`load_metrics` publications and the routers' `kv_hit_rate` events, keep
the latest snapshot per worker, and expose the aggregate as Prometheus
text over HTTP — the series the planner and dashboards scrape.

    python -m dynamo_tpu.metrics_aggregator --control-plane HOST:PORT \
        [--http-port 8081]
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

from aiohttp import web

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.llm.kv_router.watcher import LoadMetricsWatcher
from dynamo_tpu.runtime.metrics import MetricsRegistry

logger = logging.getLogger(__name__)

HIT_RATE_SUBJECT = "kv_hit_rate"
STALE_SECS = 30.0


class MetricsAggregator:
    """Subscribes, aggregates, exposes."""

    def __init__(self, cp) -> None:
        self.cp = cp
        self.registry = MetricsRegistry(prefix="dynamo_aggregate")
        self._watcher = LoadMetricsWatcher(cp, stale_secs=STALE_SECS,
                                           name="aggregator")
        self._tasks = []
        self._subs = []
        # Router-side KV hit telemetry.
        self._hit_isl = self.registry.counter(
            "kv_hit_isl_blocks_total", "request prefix blocks seen by router")
        self._hit_overlap = self.registry.counter(
            "kv_hit_overlap_blocks_total", "blocks already cached on the "
            "chosen worker")
        self._g_workers = self.registry.gauge(
            "workers", "workers with fresh load_metrics")
        self._g_active = self.registry.gauge(
            "request_active_slots", "active request slots across workers")
        self._g_waiting = self.registry.gauge(
            "requests_waiting", "queued requests across workers")
        self._g_blocks = self.registry.gauge(
            "kv_active_blocks", "active KV blocks across workers")
        self._g_usage = self.registry.gauge(
            "kv_usage_mean", "mean device cache usage across workers")

    async def start(self) -> None:
        await self._watcher.start()
        sub = await self.cp.subscribe(HIT_RATE_SUBJECT)
        self._subs.append(sub)
        self._tasks.append(asyncio.create_task(self._pump_hits(sub)))

    async def stop(self) -> None:
        await self._watcher.stop()
        for s in self._subs:
            s.cancel()
        for t in self._tasks:
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass

    async def _pump_hits(self, sub) -> None:
        backoff = 1.0
        while True:
            try:
                payload = await sub.next()
                backoff = 1.0
            except asyncio.CancelledError:
                raise
            except ConnectionError:
                # ADVICE r3: don't go silently dark until restart.  The
                # control-plane client reconnects and restores this SAME
                # subscription; keep draining after a pause.
                logger.warning("kv_hit_rate subscription lost; waiting "
                               "%.0fs for reconnect", backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)
                continue
            try:
                self._hit_isl.inc(float(payload["isl_blocks"]))
                self._hit_overlap.inc(float(payload["overlap_blocks"]))
            except Exception:
                logger.exception("bad kv_hit_rate payload")

    def fresh_workers(self) -> Dict[int, ForwardPassMetrics]:
        return self._watcher.fresh()

    def _refresh_gauges(self) -> None:
        fresh = self.fresh_workers()
        self._g_workers.set(len(fresh))
        self._g_active.set(sum(
            m.worker_stats.request_active_slots for m in fresh.values()))
        self._g_waiting.set(sum(
            m.worker_stats.num_requests_waiting for m in fresh.values()))
        self._g_blocks.set(sum(
            m.kv_stats.kv_active_blocks for m in fresh.values()))
        usages = [m.kv_stats.gpu_cache_usage_perc for m in fresh.values()]
        self._g_usage.set(sum(usages) / len(usages) if usages else 0.0)

    def expose(self) -> str:
        self._refresh_gauges()
        return self.registry.expose()


async def serve(cp, host: str = "127.0.0.1", port: int = 0):
    """Start aggregator + HTTP /metrics; returns (aggregator, runner, port)."""
    agg = MetricsAggregator(cp)
    await agg.start()

    async def metrics(_req):
        return web.Response(text=agg.expose(),
                            content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    bound = site._server.sockets[0].getsockname()[1]
    logger.info("metrics aggregator on %s:%d", host, bound)
    return agg, runner, bound
