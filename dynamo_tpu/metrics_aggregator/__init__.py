"""Namespace-wide metrics aggregator service.

Role of the reference's `components/metrics` Rust binary
(`components/metrics/src/main.rs:15-28`): subscribe to every worker's
`load_metrics` publications and the routers' `kv_hit_rate` events, keep
the latest snapshot per worker, and expose the aggregate as Prometheus
text over HTTP — the series the planner and dashboards scrape.

Additionally scrapes the `/metrics` of any process advertised under the
control plane's `status_endpoints/` prefix (router_service, planner —
components with a status server but no pub/sub metrics stream) and
appends their exposition verbatim, so one aggregator URL covers the whole
namespace.

    python -m dynamo_tpu.metrics_aggregator --control-plane HOST:PORT \
        [--http-port 8081]
"""

from __future__ import annotations

import asyncio
import logging
import re
import time
from typing import Dict, Optional

from aiohttp import web

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.llm.kv_router.watcher import LoadMetricsWatcher
from dynamo_tpu.runtime.metrics import MetricsRegistry

logger = logging.getLogger(__name__)

HIT_RATE_SUBJECT = "kv_hit_rate"
STALE_SECS = 30.0
SCRAPE_INTERVAL = 5.0
SCRAPE_TIMEOUT = 2.0
# A target that keeps failing is carried (marked stale) this long after
# its last success, then dropped entirely.
STALE_DROP_SECS = 60.0

# Request-ledger series scraped from frontends (ISSUE 18):
# per-phase histogram components and the goodput token counters.
_PHASE_RE = re.compile(
    r'^dynamo_request_phase_seconds_(sum|count)'
    r'\{[^}]*phase="([^"]+)"[^}]*\}\s+([0-9.eE+-]+)')

# Device-truth drift series scraped from workers (ISSUE 20): the
# per-series modeled-vs-measured ratio and the XLA cost-registry size.
_DRIFT_RE = re.compile(
    r'^dynamo_modeled_vs_measured_ratio'
    r'\{[^}]*series="([^"]+)"[^}]*\}\s+([0-9.eE+-]+)')
_REGISTRY_SIZE_RE = re.compile(
    r'^dynamo_program_registry_size\s+([0-9.eE+-]+)')


class MetricsAggregator:
    """Subscribes, aggregates, exposes — and scrapes advertised status
    servers (workers, frontend, router_service, planner)."""

    def __init__(self, cp, scrape_interval: float = SCRAPE_INTERVAL,
                 scrape_timeout: float = SCRAPE_TIMEOUT,
                 stale_drop_secs: float = STALE_DROP_SECS) -> None:
        self.cp = cp
        self.scrape_interval = scrape_interval
        self.scrape_timeout = scrape_timeout
        self.stale_drop_secs = stale_drop_secs
        # address → {"text": last /metrics text, "last_ok": ts,
        #            "stale": last attempt failed}
        self._scraped: Dict[str, dict] = {}
        self.registry = MetricsRegistry(prefix="dynamo_aggregate")
        self._scrape_failures = self.registry.counter(
            "scrape_failures_total",
            "Failed /metrics scrapes of advertised status endpoints")
        self._endpoint_reaps = self.registry.counter(
            "endpoint_reaps_total",
            "Stale status-endpoint registrations deleted because their "
            "recorded pid is provably dead (kill -9'd worker cleanup)")
        self._watcher = LoadMetricsWatcher(cp, stale_secs=STALE_SECS,
                                           name="aggregator")
        self._tasks = []
        self._subs = []
        # Router-side KV hit telemetry.
        self._hit_isl = self.registry.counter(
            "kv_hit_isl_blocks_total", "request prefix blocks seen by router")
        self._hit_overlap = self.registry.counter(
            "kv_hit_overlap_blocks_total", "blocks already cached on the "
            "chosen worker")
        self._g_workers = self.registry.gauge(
            "workers", "workers with fresh load_metrics")
        self._g_active = self.registry.gauge(
            "request_active_slots", "active request slots across workers")
        self._g_waiting = self.registry.gauge(
            "requests_waiting", "queued requests across workers")
        self._g_blocks = self.registry.gauge(
            "kv_active_blocks", "active KV blocks across workers")
        self._g_usage = self.registry.gauge(
            "kv_usage_mean", "mean device cache usage across workers")
        # Fleet goodput attribution (ISSUE 18): every frontend folds its
        # completed request ledgers into
        # dynamo_request_phase_seconds{phase=} + the goodput counter
        # pair; the aggregator re-exposes them pre-summed.  Merge
        # semantics are SUM: a phase's fleet mean is
        # sum(_sum)/sum(_count) across frontends, and goodput is the
        # summed token counters' ratio — both hold because every
        # underlying series is a monotone per-instance total.
        self._g_phase_sum = self.registry.gauge(
            "request_phase_seconds_sum",
            "summed ledger phase seconds across frontends (label phase=)")
        self._g_phase_count = self.registry.gauge(
            "request_phase_seconds_count",
            "summed ledger phase observations across frontends "
            "(label phase=)")
        self._g_goodput_good = self.registry.gauge(
            "goodput_good_tokens",
            "output tokens from SLO-good requests across frontends")
        self._g_goodput_total = self.registry.gauge(
            "goodput_tokens",
            "output tokens from all finished requests across frontends")
        self._g_goodput = self.registry.gauge(
            "goodput_ratio",
            "fleet goodput: SLO-good tokens / total tokens (0 when no "
            "tokens yet)")
        # Device-truth drift (ISSUE 20): workers audit their analytical
        # model (KV-byte accounting, roofline time) against XLA's
        # per-program cost analysis and expose
        # dynamo_modeled_vs_measured_ratio{series=}.  Merge semantics
        # are MEAN per series: the ratio is already a normalized
        # per-worker quantity (modeled/measured), so summing would scale
        # with fleet size while a mean stays comparable to the
        # per-worker drift band.  Registry sizes SUM — distinct workers
        # compile distinct program sets.
        self._g_drift_ratio = self.registry.gauge(
            "modeled_vs_measured_ratio",
            "mean modeled-vs-measured drift ratio across workers "
            "(label series=; >1 = the analytical model over-claims)")
        self._g_registry_size = self.registry.gauge(
            "program_registry_size",
            "XLA cost-registry programs summed across workers")

    async def start(self) -> None:
        await self._watcher.start()
        sub = await self.cp.subscribe(HIT_RATE_SUBJECT)
        self._subs.append(sub)
        self._tasks.append(asyncio.create_task(self._pump_hits(sub)))
        self._tasks.append(asyncio.create_task(self._scrape_loop()))

    async def stop(self) -> None:
        await self._watcher.stop()
        for s in self._subs:
            s.cancel()
        for t in self._tasks:
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass

    async def _pump_hits(self, sub) -> None:
        backoff = 1.0
        while True:
            try:
                payload = await sub.next()
                backoff = 1.0
            except asyncio.CancelledError:
                raise
            except ConnectionError:
                # ADVICE r3: don't go silently dark until restart.  The
                # control-plane client reconnects and restores this SAME
                # subscription; keep draining after a pause.
                logger.warning("kv_hit_rate subscription lost; waiting "
                               "%.0fs for reconnect", backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)
                continue
            try:
                self._hit_isl.inc(float(payload["isl_blocks"]))
                self._hit_overlap.inc(float(payload["overlap_blocks"]))
            except Exception:
                logger.exception("bad kv_hit_rate payload")

    async def _scrape_once(self) -> None:
        """One sweep of `/metrics` from every status server advertised
        under `status_endpoints/` (runtime/status.register_status_endpoint).

        Failure policy (a crashed worker must be VISIBLE, not silently
        flat): a failed target increments
        `dynamo_aggregate_scrape_failures_total`, its last-good series
        stay in the exposition behind a STALE comment for
        `stale_drop_secs` after the last success, and only then drop.
        Targets no longer advertised drop immediately.  A failed target
        whose registration pid is provably dead (ISSUE 14:
        `runtime/status.registration_pid_dead` — loopback address +
        signal-0 probe) is REAPED: its key is deleted from the control
        plane and `dynamo_aggregate_endpoint_reaps_total` counts it, so
        kill -9'd workers stop haunting discovery forever."""
        import aiohttp

        from dynamo_tpu.runtime.status import (
            STATUS_ENDPOINTS_PREFIX, registration_pid_dead)

        entries = await self.cp.get_prefix(f"{STATUS_ENDPOINTS_PREFIX}/")
        # addr → (key, entry): the reap path needs the key to delete and
        # the entry's pid to probe (first registration per address wins).
        by_addr: Dict[str, tuple] = {}
        for key, entry in sorted(entries.items()):
            if isinstance(entry, dict) and entry.get("address"):
                by_addr.setdefault(entry["address"], (key, entry))
        addrs = sorted(by_addr)
        results = []
        if addrs:
            # Per-endpoint timeout: one hung target must not consume the
            # sweep's whole budget and starve the others.
            timeout = aiohttp.ClientTimeout(total=self.scrape_timeout)

            async def fetch(s, addr):
                try:
                    async with s.get(f"http://{addr}/metrics",
                                     timeout=timeout) as resp:
                        if resp.status == 200:
                            return addr, await resp.text()
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError):
                    pass
                return addr, None

            # Concurrent fetches: registration keys are unleased
            # (stale ones accumulate across restarts), so one
            # sweep must cost ~one timeout total, not one per
            # dead address serially.
            async with aiohttp.ClientSession(timeout=timeout) as s:
                results = await asyncio.gather(
                    *(fetch(s, a) for a in addrs))
        now = time.monotonic()
        fresh: Dict[str, dict] = {}
        for addr, text in results:
            if text is not None:
                fresh[addr] = {"text": text, "last_ok": now,
                               "stale": False}
                continue
            key, entry = by_addr[addr]
            if registration_pid_dead(entry):
                # Dead process, stale registration: reap the key so the
                # fleet view (and every future sweep) stops carrying it.
                try:
                    await self.cp.delete(key)
                    self._endpoint_reaps.inc(labels={"endpoint": addr})
                    logger.info(
                        "reaped stale status endpoint %s (%s, pid %s "
                        "dead)", key, addr, entry.get("pid"))
                    continue  # no STALE carry: the target is gone
                except Exception:
                    logger.warning("failed to reap stale endpoint %s",
                                   key, exc_info=True)
            self._scrape_failures.inc(labels={"endpoint": addr})
            prev = self._scraped.get(addr)
            if prev is not None and (now - prev["last_ok"]
                                     <= self.stale_drop_secs):
                fresh[addr] = dict(prev, stale=True)
        self._scraped = fresh

    async def _scrape_loop(self) -> None:
        while True:
            # The whole iteration is guarded (like _pump_hits): one
            # malformed status_endpoints entry or transient session
            # error must not silently kill scraping forever.
            try:
                await self._scrape_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("status-endpoint scrape failed; retrying")
            await asyncio.sleep(self.scrape_interval)

    def fresh_workers(self) -> Dict[int, ForwardPassMetrics]:
        return self._watcher.fresh()

    def _refresh_gauges(self) -> None:
        fresh = self.fresh_workers()
        self._g_workers.set(len(fresh))
        self._g_active.set(sum(
            m.worker_stats.request_active_slots for m in fresh.values()))
        self._g_waiting.set(sum(
            m.worker_stats.num_requests_waiting for m in fresh.values()))
        self._g_blocks.set(sum(
            m.kv_stats.kv_active_blocks for m in fresh.values()))
        usages = [m.kv_stats.gpu_cache_usage_perc for m in fresh.values()]
        self._g_usage.set(sum(usages) / len(usages) if usages else 0.0)
        self._refresh_ledger_gauges()
        self._refresh_drift_gauges()

    def _refresh_ledger_gauges(self) -> None:
        """Sum the frontends' ledger series into the fleet aggregates.

        Works off the raw scraped texts (not the watcher) because the
        phase histograms and goodput counters live on the FRONTEND
        registries, which only reach the aggregator as scrape targets.
        """
        sums: Dict[str, float] = {}
        counts: Dict[str, float] = {}
        good = total = 0.0
        for entry in self._scraped.values():
            for line in entry["text"].splitlines():
                if line.startswith("dynamo_request_phase_seconds_"):
                    m = _PHASE_RE.match(line)
                    if not m:
                        continue
                    kind, phase = m.group(1), m.group(2)
                    try:
                        val = float(m.group(3))
                    except ValueError:
                        continue
                    bucket = sums if kind == "sum" else counts
                    bucket[phase] = bucket.get(phase, 0.0) + val
                elif line.startswith("dynamo_goodput_"):
                    name_labels, _, raw = line.rpartition(" ")
                    try:
                        val = float(raw)
                    except ValueError:
                        continue
                    if name_labels.startswith(
                            "dynamo_goodput_good_tokens_total"):
                        good += val
                    elif name_labels.startswith(
                            "dynamo_goodput_tokens_total"):
                        total += val
        for phase, val in sums.items():
            self._g_phase_sum.set(val, labels={"phase": phase})
        for phase, val in counts.items():
            self._g_phase_count.set(val, labels={"phase": phase})
        self._g_goodput_good.set(good)
        self._g_goodput_total.set(total)
        self._g_goodput.set(good / total if total > 0 else 0.0)

    def _refresh_drift_gauges(self) -> None:
        """Pre-sum the workers' device-truth drift series into fleet
        aggregates (dashboards alert on ONE series, not per-worker
        fan-out).  Ratios average per series; registry sizes sum.  Works
        off the raw scraped texts like the ledger gauges — the drift
        series live on the WORKER registries."""
        ratios: Dict[str, list] = {}
        registry_total = 0.0
        for entry in self._scraped.values():
            for line in entry["text"].splitlines():
                m = _DRIFT_RE.match(line)
                if m:
                    try:
                        ratios.setdefault(m.group(1), []).append(
                            float(m.group(2)))
                    except ValueError:
                        continue
                    continue
                m = _REGISTRY_SIZE_RE.match(line)
                if m:
                    try:
                        registry_total += float(m.group(1))
                    except ValueError:
                        continue
        for series, vals in ratios.items():
            self._g_drift_ratio.set(sum(vals) / len(vals),
                                    labels={"series": series})
        self._g_registry_size.set(registry_total)

    @staticmethod
    def _relabel(text: str, addr: str, seen_meta: set) -> str:
        """Stamp an `instance` label on every scraped sample so two
        processes of the same component (both exposing, say, an
        unlabeled dynamo_router_requests_total) stay distinct series —
        verbatim concatenation made Prometheus reject the whole
        exposition as duplicate samples.  # HELP/# TYPE lines pass
        through once per metric name across all targets."""
        out = []
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 2)   # '#', 'HELP|TYPE', 'name...'
                key = tuple(parts[:3])
                if key in seen_meta:
                    continue
                seen_meta.add(key)
                out.append(line)
                continue
            name_labels, _, value = line.rpartition(" ")
            if not name_labels:
                out.append(line)
                continue
            if name_labels.endswith("}"):
                out.append(f'{name_labels[:-1]},instance="{addr}"}} {value}')
            else:
                out.append(f'{name_labels}{{instance="{addr}"}} {value}')
        return "\n".join(out) + "\n" if out else ""

    def expose(self) -> str:
        self._refresh_gauges()
        text = self.registry.expose()
        seen_meta: set = set()
        now = time.monotonic()
        for addr in sorted(self._scraped):
            entry = self._scraped[addr]
            header = f"# scraped from {addr}\n"
            if entry.get("stale"):
                age = now - entry["last_ok"]
                header = (f"# scraped from {addr} "
                          f"(STALE: last success {age:.1f}s ago)\n")
            text += header + self._relabel(entry["text"], addr, seen_meta)
        return text


async def serve(cp, host: str = "127.0.0.1", port: int = 0):
    """Start aggregator + HTTP /metrics; returns (aggregator, runner, port)."""
    agg = MetricsAggregator(cp)
    await agg.start()

    async def metrics(_req):
        return web.Response(text=agg.expose(),
                            content_type="text/plain")

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    bound = site._server.sockets[0].getsockname()[1]
    logger.info("metrics aggregator on %s:%d", host, bound)
    return agg, runner, bound
