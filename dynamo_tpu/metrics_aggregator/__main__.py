"""CLI entry: `python -m dynamo_tpu.metrics_aggregator`."""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from dynamo_tpu.metrics_aggregator import serve
from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient


def main(argv=None) -> None:
    p = argparse.ArgumentParser("dynamo_tpu.metrics_aggregator")
    p.add_argument("--control-plane", required=True, help="HOST:PORT")
    p.add_argument("--http-host", default="127.0.0.1")
    p.add_argument("--http-port", type=int, default=8081)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    async def run():
        host, port = args.control_plane.rsplit(":", 1)
        cp = ControlPlaneClient(host, int(port))
        await cp.start()
        agg, runner, bound = await serve(cp, args.http_host, args.http_port)
        print(f"metrics aggregator serving :{bound}/metrics", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await agg.stop()
        await runner.cleanup()
        await cp.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
