"""Model families served by the TPU engine.

The reference delegates model execution to external engines (vLLM/SGLang/
TRT-LLM — SURVEY.md §2.3); here the engine is ours, so model definitions
live in-tree: pure-JAX functional transformers (params as pytrees) whose
forward steps are jit/shard_map-friendly (static shapes, no Python control
flow on traced values).
"""

from dynamo_tpu.models.config import ModelConfig, PRESETS

__all__ = ["ModelConfig", "PRESETS"]
