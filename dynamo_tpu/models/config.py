"""Model architecture configs.

Plays the role of the reference's `ModelDeploymentCard` model-info slice
(`lib/llm/src/model_card.rs:90-120` — context length, vocab, etc.) plus the
engine-side architecture hyperparameters the reference leaves to vLLM.

Presets cover the BASELINE.md ladder: Llama-3-8B → Llama-3-70B →
Mixtral-8x7B (MoE) → DeepSeek-R1-class, plus tiny configs for CPU tests.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    """Architecture of a Llama-family (optionally MoE) decoder-only LM.

    All shapes are chosen TPU-first: `head_dim` a multiple of 128 where the
    real models allow it, activations in bfloat16, and sizes that tile onto
    the MXU without padding.
    """

    name: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    max_context: int = 8192
    rope_theta: float = 500_000.0
    rms_norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    # MoE (Mixtral-style). num_experts == 0 means dense MLP.
    num_experts: int = 0
    num_experts_per_token: int = 2
    # Per-expert dispatch capacity (tokens per expert per source shard)
    # for moe_mode="dispatch".  None = exact (nothing can overflow —
    # serving default).  A bounded capacity trades exactness for a
    # smaller all-to-all buffer; overflow assignments are DROPPED and
    # counted in the stats vector's tail slot
    # (dynamo_moe_dropped_tokens_total), never silent.
    moe_capacity: Optional[int] = None
    # Tie input embedding and LM head (small models).
    tie_embeddings: bool = False
    # Gemma-family knobs (all default to the Llama conventions):
    activation: str = "silu"              # "silu" | "gelu_tanh"
    attn_soft_cap: Optional[float] = None  # attention-logit soft cap
    final_soft_cap: Optional[float] = None  # lm-head-logit soft cap
    post_norms: bool = False              # post-attn/post-mlp RMSNorms
    rms_offset: bool = False              # norm scales by (1 + w)
    embed_scale: bool = False             # embeddings x sqrt(hidden)
    query_scale: Optional[float] = None   # replaces head_dim**-0.5

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)

    def validate(self) -> None:
        if self.num_heads % self.num_kv_heads:
            raise ValueError("num_heads must be a multiple of num_kv_heads (GQA)")
        if self.is_moe and self.num_experts_per_token > self.num_experts:
            raise ValueError("num_experts_per_token > num_experts")
        if self.moe_capacity is not None and self.moe_capacity <= 0:
            raise ValueError("moe_capacity must be positive (None = exact)")
        if self.activation not in ("silu", "gelu_tanh"):
            raise ValueError(f"unknown activation {self.activation!r}")

    def param_count(self) -> int:
        """Approximate parameter count (for memory planning / bench labels)."""
        h, v = self.hidden_size, self.vocab_size
        attn = h * self.q_size + 2 * h * self.kv_size + self.q_size * h
        if self.is_moe:
            mlp = self.num_experts * 3 * h * self.intermediate_size + h * self.num_experts
        else:
            mlp = 3 * h * self.intermediate_size
        per_layer = attn + mlp + 2 * h
        emb = v * h * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + emb + h


# Tiny configs for CPU tests: small enough to run a full correctness check
# on the 8-device virtual mesh in milliseconds, but with GQA + enough heads
# to exercise every sharding axis.
TINY = ModelConfig(
    name="tiny-test",
    vocab_size=256,
    hidden_size=64,
    num_layers=2,
    num_heads=8,
    num_kv_heads=4,
    head_dim=16,
    intermediate_size=128,
    max_context=512,
    rope_theta=10_000.0,
    dtype=jnp.float32,
    tie_embeddings=True,
)

TINY_MOE = TINY.replace(name="tiny-moe", num_experts=8, num_experts_per_token=2)

LLAMA3_1B = ModelConfig(
    name="llama-3-1b",
    vocab_size=128_256,
    hidden_size=2048,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    head_dim=64,
    intermediate_size=8192,
    max_context=8192,
    tie_embeddings=True,
)

LLAMA3_8B = ModelConfig(
    name="llama-3-8b",
    vocab_size=128_256,
    hidden_size=4096,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    intermediate_size=14_336,
    max_context=8192,
)

LLAMA3_70B = ModelConfig(
    name="llama-3-70b",
    vocab_size=128_256,
    hidden_size=8192,
    num_layers=80,
    num_heads=64,
    num_kv_heads=8,
    head_dim=128,
    intermediate_size=28_672,
    max_context=8192,
)

MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    vocab_size=32_000,
    hidden_size=4096,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    intermediate_size=14_336,
    max_context=32_768,
    rope_theta=1_000_000.0,
    num_experts=8,
    num_experts_per_token=2,
)

TINY_GEMMA = TINY.replace(
    name="tiny-gemma",
    activation="gelu_tanh",
    attn_soft_cap=50.0,
    final_soft_cap=30.0,
    post_norms=True,
    rms_offset=True,
    embed_scale=True,
    query_scale=16.0 ** -0.5,
)

GEMMA2_9B = ModelConfig(
    name="gemma-2-9b",
    vocab_size=256_000,
    hidden_size=3584,
    num_layers=42,
    num_heads=16,
    num_kv_heads=8,
    head_dim=256,
    intermediate_size=14_336,
    # Gemma-2 alternates sliding-window (4096) and global layers; this
    # engine runs every layer global, which is EXACT while context stays
    # within the window — max_context is clamped accordingly.
    max_context=4096,
    rope_theta=10_000.0,
    rms_norm_eps=1e-6,
    tie_embeddings=True,
    activation="gelu_tanh",
    attn_soft_cap=50.0,
    final_soft_cap=30.0,
    post_norms=True,
    rms_offset=True,
    embed_scale=True,
    query_scale=224.0 ** -0.5,
)

PRESETS = {
    c.name: c
    for c in (TINY, TINY_MOE, TINY_GEMMA, LLAMA3_1B, LLAMA3_8B,
              LLAMA3_70B, MIXTRAL_8X7B, GEMMA2_9B)
}


def get_config(name: str) -> ModelConfig:
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(f"unknown model preset {name!r}; have {sorted(PRESETS)}") from None
