"""GGUF checkpoint loading: parse → dequantize → engine param pytree.

Role of the reference's `lib/llm/src/gguf/` (922 LoC: header/metadata
parser incl. tokenizer extraction, `gguf_metadata.rs`) — a local-file
model format the no-egress environment fully supports.  The reader
implements GGUF v2/v3:

    magic "GGUF" | version u32 | n_tensors u64 | n_kv u64
    metadata kv*: key (u64-len string), type u32, value
    tensor info*: name, n_dims u32, dims u64[n] (ne order: fastest
                  first), ggml_type u32, offset u64
    padding to `general.alignment` (default 32), then tensor data

Supported tensor types: F32, F16, Q8_0 (32-element blocks of one f16
scale + 32 int8), and the K-quant family people actually serve —
Q4_K / Q5_K / Q6_K (256-element superblocks with 6-bit sub-scales; bit
layouts follow ggml's `dequantize_row_q{4,5,6}_K`).  All dequantise to
f32 on load; other quants raise with the type name.

Weight conventions: GGML `ne` lists dims fastest-first, so a linear
layer y = W @ x is stored [n_in (ne0), n_out (ne1)] row-major by out —
i.e. the numpy view is [n_out, n_in], transposed on load into our
x @ W convention like the HF loader.  attn_q/attn_k carry llama.cpp's
interleaved-rope permutation (convert_hf_to_gguf.py `permute`); the
inverse permutation restores the HF half-rotation layout our
`models.llama.rope` uses (tests lock the round trip).

The tokenizer metadata (`tokenizer.ggml.*`: tokens, scores, types,
special token ids) is extracted and returned alongside the params — the
`gguf_metadata.rs` tokenizer-extraction parity point.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu.models.config import ModelConfig

Params = Dict

GGUF_MAGIC = b"GGUF"

# ggml tensor types we materialise.
GGML_F32 = 0
GGML_F16 = 1
GGML_Q8_0 = 8
GGML_Q4_K = 12
GGML_Q5_K = 13
GGML_Q6_K = 14
_TYPE_NAMES = {0: "F32", 1: "F16", 2: "Q4_0", 3: "Q4_1", 6: "Q5_0",
               7: "Q5_1", 8: "Q8_0", 9: "Q8_1", 10: "Q2_K", 11: "Q3_K",
               12: "Q4_K", 13: "Q5_K", 14: "Q6_K", 15: "Q8_K"}
QK_K = 256  # K-quant superblock length
# bytes per block: (block_bytes, block_elems)
_BLOCK_GEOM = {
    GGML_Q8_0: (34, 32),
    GGML_Q4_K: (144, QK_K),   # d f16 + dmin f16 + 12 scale bytes + 128 qs
    GGML_Q5_K: (176, QK_K),   # ... + 32 qh bytes
    GGML_Q6_K: (210, QK_K),   # 128 ql + 64 qh + 16 scales + d f16
}

# metadata value types
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, \
    _F64 = range(13)
_SCALAR_FMT = {_U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I",
               _I32: "<i", _F32: "<f", _U64: "<Q", _I64: "<q", _F64: "<d"}


def _read_str(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype in _SCALAR_FMT:
        fmt = _SCALAR_FMT[vtype]
        (v,) = struct.unpack(fmt, f.read(struct.calcsize(fmt)))
        return v
    if vtype == _BOOL:
        return bool(f.read(1)[0])
    if vtype == _STR:
        return _read_str(f)
    if vtype == _ARR:
        (etype,) = struct.unpack("<I", f.read(4))
        (n,) = struct.unpack("<Q", f.read(8))
        if etype in _SCALAR_FMT:
            fmt = _SCALAR_FMT[etype]
            size = struct.calcsize(fmt)
            raw = f.read(size * n)
            return list(np.frombuffer(
                raw, dtype=np.dtype(fmt[1:]).newbyteorder("<")))
        return [_read_value(f, etype) for _ in range(n)]
    raise ValueError(f"unknown gguf metadata type {vtype}")


def _scale_min_k4(scales: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Unpack the 12-byte K-quant scale block into 8 six-bit (scale, min)
    pairs per superblock (ggml `get_scale_min_k4`): j<4 reads the low 6
    bits of bytes j / j+4; j>=4 combines the low nibble of byte j+4 with
    the top 2 bits of byte j-4 (scale) / j (min).

    scales: [n_blocks, 12] u8 → (sc, mn): [n_blocks, 8] f32."""
    q = scales.astype(np.uint16)
    sc = np.empty(q.shape[:-1] + (8,), np.float32)
    mn = np.empty_like(sc)
    for j in range(4):
        sc[..., j] = (q[..., j] & 63).astype(np.float32)
        mn[..., j] = (q[..., j + 4] & 63).astype(np.float32)
    for j in range(4, 8):
        sc[..., j] = ((q[..., j + 4] & 0x0F)
                      | ((q[..., j - 4] >> 6) << 4)).astype(np.float32)
        mn[..., j] = ((q[..., j + 4] >> 4)
                      | ((q[..., j] >> 6) << 4)).astype(np.float32)
    return sc, mn


def _dequant_q4_k(raw: bytes, n_blocks: int) -> np.ndarray:
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)),
         ("qs", "u1", (128,))]), count=n_blocks)
    d = rec["d"].astype(np.float32)[:, None]          # [B, 1]
    dmin = rec["dmin"].astype(np.float32)[:, None]
    sc, mn = _scale_min_k4(rec["scales"])             # [B, 8]
    # qs: 4 chunks of 32 bytes; each byte holds (low nibble → sub-block
    # 2c, high nibble → sub-block 2c+1).
    qs = rec["qs"].reshape(n_blocks, 4, 32)
    lo = (qs & 0x0F).astype(np.float32)               # [B, 4, 32]
    hi = (qs >> 4).astype(np.float32)
    out = np.empty((n_blocks, 8, 32), np.float32)
    out[:, 0::2] = lo
    out[:, 1::2] = hi
    return ((d * sc)[:, :, None] * out
            - (dmin * mn)[:, :, None]).reshape(-1)


def _dequant_q5_k(raw: bytes, n_blocks: int) -> np.ndarray:
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)),
         ("qh", "u1", (32,)), ("qs", "u1", (128,))]), count=n_blocks)
    d = rec["d"].astype(np.float32)[:, None]
    dmin = rec["dmin"].astype(np.float32)[:, None]
    sc, mn = _scale_min_k4(rec["scales"])
    qs = rec["qs"].reshape(n_blocks, 4, 32)
    qh = rec["qh"]                                    # [B, 32]
    out = np.empty((n_blocks, 8, 32), np.float32)
    for j in range(4):
        u1, u2 = 1 << (2 * j), 2 << (2 * j)
        out[:, 2 * j] = ((qs[:, j] & 0x0F)
                         + np.where(qh & u1, 16, 0)).astype(np.float32)
        out[:, 2 * j + 1] = ((qs[:, j] >> 4)
                             + np.where(qh & u2, 16, 0)).astype(np.float32)
    return ((d * sc)[:, :, None] * out
            - (dmin * mn)[:, :, None]).reshape(-1)


def _dequant_q6_k(raw: bytes, n_blocks: int) -> np.ndarray:
    rec = np.frombuffer(raw, dtype=np.dtype(
        [("ql", "u1", (128,)), ("qh", "u1", (64,)),
         ("scales", "i1", (16,)), ("d", "<f2")]), count=n_blocks)
    d = rec["d"].astype(np.float32)                   # [B]
    sc = rec["scales"].astype(np.float32)             # [B, 16]
    out = np.empty((n_blocks, 2, 4, 32), np.float32)  # halves x rows x l
    for h in range(2):                                # two 128-elem halves
        ql = rec["ql"][:, 64 * h:64 * (h + 1)]        # [B, 64]
        qh = rec["qh"][:, 32 * h:32 * (h + 1)]        # [B, 32]
        q1 = ((ql[:, :32] & 0x0F) | ((qh >> 0) & 3) << 4).astype(
            np.int8)
        q2 = ((ql[:, 32:] & 0x0F) | ((qh >> 2) & 3) << 4).astype(np.int8)
        q3 = ((ql[:, :32] >> 4) | ((qh >> 4) & 3) << 4).astype(np.int8)
        q4 = ((ql[:, 32:] >> 4) | ((qh >> 6) & 3) << 4).astype(np.int8)
        for r, q in enumerate((q1, q2, q3, q4)):
            # row r covers elements [128h + 32r, 128h + 32(r+1)); its
            # 16-elem groups use scales[8h + 2r + l//16].
            g0 = sc[:, 8 * h + 2 * r][:, None]
            g1 = sc[:, 8 * h + 2 * r + 1][:, None]
            scale = np.concatenate(
                [np.repeat(g0, 16, axis=1), np.repeat(g1, 16, axis=1)],
                axis=1)                               # [B, 32]
            out[:, h, r] = (q.astype(np.float32) - 32.0) * scale
    return (d[:, None, None, None] * out).reshape(-1)


def _dequant(raw: bytes, ggml_type: int, n_elems: int) -> np.ndarray:
    if ggml_type == GGML_F32:
        return np.frombuffer(raw, np.float32, count=n_elems).copy()
    if ggml_type == GGML_F16:
        return np.frombuffer(raw, np.float16,
                             count=n_elems).astype(np.float32)
    if ggml_type == GGML_Q8_0:
        # blocks of [f16 scale][32 x i8]; value = scale * q
        n_blocks = n_elems // 32
        rec = np.frombuffer(
            raw, dtype=np.dtype([("d", "<f2"), ("q", "i1", (32,))]),
            count=n_blocks)
        return (rec["d"].astype(np.float32)[:, None]
                * rec["q"].astype(np.float32)).reshape(n_elems)
    if ggml_type == GGML_Q4_K:
        return _dequant_q4_k(raw, n_elems // QK_K)
    if ggml_type == GGML_Q5_K:
        return _dequant_q5_k(raw, n_elems // QK_K)
    if ggml_type == GGML_Q6_K:
        return _dequant_q6_k(raw, n_elems // QK_K)
    raise ValueError(
        f"unsupported ggml tensor type "
        f"{_TYPE_NAMES.get(ggml_type, ggml_type)}; supported: F32, F16, "
        "Q8_0, Q4_K, Q5_K, Q6_K")


class GgufFile:
    """Parsed GGUF: metadata dict + lazy tensor loading."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.metadata: Dict[str, Any] = {}
        self.tensors: Dict[str, Tuple[List[int], int, int]] = {}
        with open(path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            (version,) = struct.unpack("<I", f.read(4))
            if version not in (2, 3):
                raise ValueError(f"{path}: unsupported GGUF v{version}")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = _read_str(f)
                (vtype,) = struct.unpack("<I", f.read(4))
                self.metadata[key] = _read_value(f, vtype)
            infos = []
            for _ in range(n_tensors):
                name = _read_str(f)
                (n_dims,) = struct.unpack("<I", f.read(4))
                dims = list(struct.unpack(f"<{n_dims}Q",
                                          f.read(8 * n_dims)))
                ggml_type, offset = struct.unpack("<IQ", f.read(12))
                infos.append((name, dims, ggml_type, offset))
            align = int(self.metadata.get("general.alignment", 32))
            base = f.tell()
            base += (-base) % align
            self._data_base = base
            for name, dims, ggml_type, offset in infos:
                self.tensors[name] = (dims, ggml_type, base + offset)

    def tensor(self, name: str) -> np.ndarray:
        """Dequantised tensor as f32, numpy shape [ne_last, ..., ne0]
        (row-major view of GGML's fastest-first dims)."""
        if name not in self.tensors:
            raise KeyError(f"tensor {name!r} not in {self.path} "
                           f"(have e.g. {sorted(self.tensors)[:5]})")
        dims, ggml_type, pos = self.tensors[name]
        n = 1
        for d in dims:
            n *= d
        if ggml_type == GGML_F32:
            nbytes = 4 * n
        elif ggml_type == GGML_F16:
            nbytes = 2 * n
        elif ggml_type in _BLOCK_GEOM:
            block_bytes, block_elems = _BLOCK_GEOM[ggml_type]
            nbytes = (n // block_elems) * block_bytes
        else:
            raise ValueError(
                f"unsupported ggml tensor type "
                f"{_TYPE_NAMES.get(ggml_type, ggml_type)}")
        with open(self.path, "rb") as f:
            f.seek(pos)
            raw = f.read(nbytes)
        return _dequant(raw, ggml_type, n).reshape(list(reversed(dims)))

    # -- tokenizer extraction (gguf_metadata.rs parity) --------------------

    def tokenizer(self) -> Optional[dict]:
        """The embedded tokenizer, or None: model kind, vocab (tokens +
        scores + types) and special token ids."""
        tokens = self.metadata.get("tokenizer.ggml.tokens")
        if tokens is None:
            return None
        out = {
            "model": self.metadata.get("tokenizer.ggml.model", "llama"),
            "tokens": list(tokens),
            "scores": list(self.metadata.get("tokenizer.ggml.scores", [])),
            "token_types": list(
                self.metadata.get("tokenizer.ggml.token_type", [])),
        }
        for k in ("bos", "eos", "unknown", "padding"):
            v = self.metadata.get(f"tokenizer.ggml.{k}_token_id")
            if v is not None:
                out[f"{k}_token_id"] = int(v)
        return out


def _unpermute(w: np.ndarray, n_head: int) -> np.ndarray:
    """Invert llama.cpp's rope permutation on a [out, in] q/k weight:
    the converter reshapes [n_head, 2, out/head/2, in] and swaps axes
    1 and 2; the inverse swaps from the POST-permute grouping
    [n_head, out/head/2, 2, in]."""
    out, in_ = w.shape
    return (w.reshape(n_head, out // n_head // 2, 2, in_)
             .swapaxes(1, 2).reshape(out, in_))


def config_from_gguf(g: GgufFile, name: str = "") -> ModelConfig:
    md = g.metadata
    arch = md.get("general.architecture", "llama")

    def key(suffix, default=None):
        return md.get(f"{arch}.{suffix}", default)

    n_heads = int(key("attention.head_count"))
    emb = int(key("embedding_length"))
    head_dim = int(key("attention.key_length", emb // n_heads))
    vocab = md.get("tokenizer.ggml.tokens")
    vocab_size = int(key("vocab_size", len(vocab) if vocab else 0))
    return ModelConfig(
        name=name or md.get("general.name", "gguf-model"),
        vocab_size=vocab_size,
        hidden_size=emb,
        num_layers=int(key("block_count")),
        num_heads=n_heads,
        num_kv_heads=int(key("attention.head_count_kv", n_heads)),
        head_dim=head_dim,
        intermediate_size=int(key("feed_forward_length")),
        max_context=int(key("context_length", 8192)),
        rope_theta=float(key("rope.freq_base", 10_000.0)),
        rms_norm_eps=float(key("attention.layer_norm_rms_epsilon", 1e-5)),
        tie_embeddings="output.weight" not in g.tensors,
    )


def load_gguf(path: str, dtype=None
              ) -> Tuple[ModelConfig, Params, Optional[dict]]:
    """Load a GGUF file → (config, params, tokenizer dict or None)."""
    import jax.numpy as jnp

    g = GgufFile(path)
    cfg = config_from_gguf(g)
    cfg.validate()
    dtype = dtype or cfg.dtype

    def lin(name: str, unpermute_heads: int = 0) -> "jnp.ndarray":
        w = g.tensor(name)           # [out, in]
        if unpermute_heads:
            w = _unpermute(w, unpermute_heads)
        return jnp.asarray(w.T).astype(dtype)     # ours: [in, out]

    def vec(name: str) -> "jnp.ndarray":
        return jnp.asarray(g.tensor(name)).astype(dtype)

    layers = []
    for i in range(cfg.num_layers):
        p = f"blk.{i}."
        layers.append({
            "attn": {
                "wq": lin(p + "attn_q.weight", cfg.num_heads),
                "wk": lin(p + "attn_k.weight", cfg.num_kv_heads),
                "wv": lin(p + "attn_v.weight"),
                "wo": lin(p + "attn_output.weight"),
            },
            "attn_norm": vec(p + "attn_norm.weight"),
            "mlp_norm": vec(p + "ffn_norm.weight"),
            "mlp": {
                "w_gate": lin(p + "ffn_gate.weight"),
                "w_up": lin(p + "ffn_up.weight"),
                "w_down": lin(p + "ffn_down.weight"),
            },
        })
    params: Params = {
        "embed": jnp.asarray(g.tensor("token_embd.weight")).astype(dtype),
        "final_norm": vec("output_norm.weight"),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = lin("output.weight")
    return cfg, params, g.tokenizer()
