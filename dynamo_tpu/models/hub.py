"""Local model-hub resolution (the `lib/llm/src/hub.rs` analog).

The reference resolves `org/repo` model names by downloading from the HF
hub with a local cache (`hub.rs`, `local_model.rs:144-190`).  This
environment has no egress, so resolution is CACHE-ONLY: an `org/repo`
name maps into the standard huggingface_hub cache layout

    $HF_HOME/hub/models--{org}--{repo}/snapshots/{revision}/

picking the revision `refs/main` points at (falling back to the most
recently modified snapshot).  The resolved directory then loads through
the normal HF-layout path (models/loader.py).  A cache miss raises with
the looked-up paths, not a silent fallback — downloading is the
operator's job in an egress-less deployment.
"""

from __future__ import annotations

import os
from typing import Optional


def hub_cache_dir() -> str:
    """The huggingface_hub cache root, honoring its env overrides."""
    if os.environ.get("HF_HUB_CACHE"):
        return os.environ["HF_HUB_CACHE"]
    hf_home = os.environ.get("HF_HOME",
                             os.path.expanduser("~/.cache/huggingface"))
    return os.path.join(hf_home, "hub")


def resolve_cached_repo(repo_id: str,
                        cache_dir: Optional[str] = None) -> str:
    """`org/repo` → local snapshot directory, or FileNotFoundError."""
    cache = cache_dir or hub_cache_dir()
    folder = os.path.join(cache, "models--" + repo_id.replace("/", "--"))
    snapshots = os.path.join(folder, "snapshots")
    if not os.path.isdir(snapshots):
        raise FileNotFoundError(
            f"model {repo_id!r} not in the local hub cache "
            f"(looked in {snapshots}; no-egress environment — "
            "pre-populate the cache or pass a checkpoint directory)")
    # refs/main holds the commit hash the default revision points at.
    ref = os.path.join(folder, "refs", "main")
    if os.path.isfile(ref):
        with open(ref) as f:
            rev = f.read().strip()
        path = os.path.join(snapshots, rev)
        if os.path.isdir(path):
            return path
    revs = [os.path.join(snapshots, d) for d in os.listdir(snapshots)]
    revs = [d for d in revs if os.path.isdir(d)]
    if not revs:
        raise FileNotFoundError(
            f"model {repo_id!r}: cache folder exists but holds no "
            f"snapshots ({snapshots})")
    return max(revs, key=os.path.getmtime)
