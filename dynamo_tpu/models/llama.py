"""Llama-family decoder (dense + Mixtral-style MoE) as pure JAX functions.

Params are plain pytrees (nested dicts of arrays) so sharding is a pytree of
`NamedSharding`s (dynamo_tpu/parallel/sharding.py) and the forward step jits
under any mesh.  The reference has no model code (it delegates to vLLM —
SURVEY.md §2.3); this module is the TPU replacement for that delegation.

Forward contract (unified prefill/decode, see dynamo_tpu/ops/attention.py):

    logits, cache = forward_step(cfg, params, cache, tokens, positions,
                                 seq_lens, block_tables, sample_positions)

- tokens/positions: [B, T] — T is the chunk length (1 for decode).
- seq_lens: [B] total valid context length *after* this chunk.
- block_tables: [B, P] page ids into the paged cache.
- sample_positions: [B] index WITHIN the chunk whose logits the caller
  wants (chunk_len - 1 for a completing prefill, 0 for decode); logits
  come back [B, V] for exactly those positions.  Materialising the full
  [B, T, V] f32 logits of a batched 512-token prefill is a multi-GB
  allocation for nothing — the LM head runs on one hidden row per
  sequence.
- The chunk's K/V are scattered into the cache first, then the chunk
  attends to all cached context with an absolute-position causal mask, so
  the same compiled function serves prefill, chunked prefill and decode.

MoE layers run the dense | grouped | dispatch ladder (ops/moe.py): the
exact dense oracle, the meshless grouped-GEMM fast path, or all-to-all
token dispatch over the `ep` mesh axis (tp-sharding each expert's MLP
under ep × tp meshes) — see `_moe_block`.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.contracts import hot_path
from dynamo_tpu.runtime.jax_compat import axis_size, shard_map
from dynamo_tpu.ops.attention import paged_attention

Params = Dict


# ---------------------------------------------------------------------------
# Init


def init_params(cfg: ModelConfig, key: jax.Array, dtype=None) -> Params:
    """Random-init params (bench/tests); real checkpoints load via
    dynamo_tpu.models.loader with the same pytree structure."""
    cfg.validate()
    dtype = dtype or cfg.dtype
    h = cfg.hidden_size

    def dense(key, fan_in, *shape):
        std = fan_in ** -0.5
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)

    # Key budget: a stride of 8 keys per layer (dense uses 7, MoE 5), plus
    # embed + lm_head at the tail — per-layer strides keep keys unique
    # without branch-dependent bookkeeping.
    keys = jax.random.split(key, cfg.num_layers * 8 + 2)

    layers = []
    for li in range(cfg.num_layers):
        ki = iter(range(li * 8, (li + 1) * 8))
        layer = {
            "attn": {
                "wq": dense(keys[next(ki)], h, h, cfg.q_size),
                "wk": dense(keys[next(ki)], h, h, cfg.kv_size),
                "wv": dense(keys[next(ki)], h, h, cfg.kv_size),
                "wo": dense(keys[next(ki)], cfg.q_size, cfg.q_size, h),
            },
            "attn_norm": jnp.ones((h,), dtype),
            "mlp_norm": jnp.ones((h,), dtype),
        }
        if cfg.post_norms:
            layer["post_attn_norm"] = jnp.ones((h,), dtype)
            layer["post_mlp_norm"] = jnp.ones((h,), dtype)
        if cfg.is_moe:
            e, f = cfg.num_experts, cfg.intermediate_size
            kk = jax.random.split(keys[next(ki)], 4)
            layer["moe"] = {
                "router": dense(kk[0], h, h, e),
                "w_gate": dense(kk[1], h, e, h, f),
                "w_up": dense(kk[2], h, e, h, f),
                "w_down": dense(kk[3], f, e, f, h),
            }
        else:
            f = cfg.intermediate_size
            layer["mlp"] = {
                "w_gate": dense(keys[next(ki)], h, h, f),
                "w_up": dense(keys[next(ki)], h, h, f),
                "w_down": dense(keys[next(ki)], f, f, h),
            }
        layers.append(layer)

    params: Params = {
        "embed": dense(keys[-2], h, cfg.vocab_size, h),
        "final_norm": jnp.ones((h,), dtype),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(keys[-1], h, h, cfg.vocab_size)
    return params


# ---------------------------------------------------------------------------
# Building blocks


def rms_norm(x: jax.Array, w: jax.Array, eps: float,
             offset: bool = False) -> jax.Array:
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    wf = w.astype(jnp.float32)
    if offset:
        wf = wf + 1.0  # Gemma convention: scale is (1 + w)
    return (norm * wf).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding, interleaved-half convention.  x: [B, T, H, D]."""
    D = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


@hot_path
def _sp_ring_attention(cfg, q, k, v, positions, ring_quant, sp_mesh,
                       sp_pallas):
    """Sequence-parallel whole-prompt attention dispatch: the Pallas
    flash ring kernel (double-buffered RDMA exchange hidden under the
    local flash fold — ops/pallas/ring_attention.py) when selected and
    eligible, else the XLA ppermute ring, which stays the oracle.

    Selection is static at trace time (shapes and mesh are): the SAME
    `ring_kernel_supported` predicate the engine's kernel-path counter
    and the measurement tools consult, so the served path and every
    tool agree on which ring a geometry runs.  Ineligible geometry
    under `sp_pallas` falls back LOUDLY here rather than silently
    wrong-shaping inside Mosaic."""
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.ops.pallas.ring_attention import (
        ring_flash_attention, ring_kernel_supported)
    from dynamo_tpu.ops.ring_attention import ring_causal_attention

    interp = jax.default_backend() != "tpu"
    sp = sp_mesh.shape["sp"]
    tp = sp_mesh.shape["tp"]
    B, T = positions.shape
    feat = cfg.num_kv_heads * cfg.head_dim // max(tp, 1)
    use_kernel = sp_pallas and ring_kernel_supported(feat, T // sp,
                                                     interp)

    # Heads stay tp-sharded inside the ring (attention is
    # head-independent): without "tp" in the specs GSPMD would
    # all-gather the column-parallel q/k/v projections and every tp
    # shard would redo all heads' attention.
    spec4 = P("dp", "sp", "tp", None)
    if use_kernel:
        def ring(qs, ks, vs, ps, ksc=None, vsc=None):
            return ring_flash_attention(
                qs, ks, vs, ps, mesh=sp_mesh, scale=cfg.query_scale,
                soft_cap=cfg.attn_soft_cap, k_scale=ksc, v_scale=vsc,
                interpret=interp)
    else:
        def ring(qs, ks, vs, ps, ksc=None, vsc=None):
            return ring_causal_attention(
                qs, ks, vs, ps, axis_name="sp", scale=cfg.query_scale,
                soft_cap=cfg.attn_soft_cap, k_scale=ksc, v_scale=vsc)

    if ring_quant is not None:
        # Quantized exchange: int8 chunk rows + per-token-per-head
        # scales ride the ring together and each hop dequantizes
        # in-register (both ring paths share kv_cache.dequantize_rows
        # numerics) — the per-hop ICI payload drops to F + 4·Hkv
        # bytes/token.
        spec3 = P("dp", "sp", "tp")
        kq4, vq4, ks3, vs3 = ring_quant
        return shard_map(
            lambda qs, ks_, vs_, ksc, vsc, ps: ring(
                qs, ks_, vs_, ps, ksc, vsc),
            mesh=sp_mesh,
            in_specs=(spec4, spec4, spec4, spec3, spec3,
                      P("dp", "sp")),
            out_specs=spec4,
            check_vma=False,
        )(q, kq4, vq4, ks3, vs3, positions)
    return shard_map(
        lambda qs, ks, vs, ps: ring(qs, ks, vs, ps),
        mesh=sp_mesh,
        in_specs=(spec4, spec4, spec4, P("dp", "sp")),
        out_specs=spec4,
        check_vma=False,
    )(q, k, v, positions)


def _attention_block(
    cfg: ModelConfig,
    p_attn: Params,
    x: jax.Array,            # [B, T, H]
    positions: jax.Array,    # [B, T]
    seq_lens: jax.Array,     # [B]
    write_slots: jax.Array,  # [B*T] flat cache slots for this chunk
    ctx_slots,               # [B, C] context slots, or None (pallas decode)
    kv_positions,            # [B, C], or None
    block_tables: jax.Array, # [B, P]
    block_size: int,
    k_cache: jax.Array,      # [S, F] this layer's cache buffer (flat feat)
    v_cache: jax.Array,
    sp_mesh=None,            # mesh → ring attention over its sp axis
    sp_pallas=False,         # sp branch: Pallas flash ring when eligible
    pallas_mesh=None,        # mesh → shard_map the decode kernel (dp, tp)
    dp_local_mesh=None,      # mesh → device-local dp-attention decode
    dp_local_pallas=False,   # dp-local body: pallas kernel on local slots
    k_scale_cache=None,      # [S, Hkv] f32 (int8 cache) or None
    v_scale_cache=None,
) -> Tuple:
    """Returns (attn_out, k_cache', v_cache', k_scale', v_scale') — the
    scale entries are None for unquantized caches.  The layer cache
    buffers are standalone arrays (not slices of a stacked cache) so the
    scatter in `write_kv` aliases in place under donation / loop carries."""
    B, T, _ = x.shape
    quant = k_scale_cache is not None
    q = (x @ p_attn["wq"]).reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = (x @ p_attn["wk"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = (x @ p_attn["wv"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)

    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)

    if dp_local_mesh is not None:
        # Device-local dp-attention decode (VERDICT r3 weak #4): cache
        # slots shard over the flat (dp, tp) grid, rows ride their slot's
        # device, and the locality-aware allocator guarantees every live
        # page of a row is in that device's slot range — so write, gather
        # and attend all run shard-locally with ZERO cross-chip traffic.
        # Out-of-range rebased slots are exactly (a) pad writes to the
        # null block (dropped; they land in the real null block on the
        # device that owns it) and (b) pad-context gathers already masked
        # by seq_lens.
        #
        # `dp_local_pallas` (ISSUE 9 leg 2): block tables rebase to the
        # shard's LOCAL page range and the Pallas kernel streams pages
        # from the local cache shard — the "global slot indexing" that
        # used to force the gather path becomes local indexing inside
        # the body.  Clamped out-of-range entries (other shards' null
        # block in pad columns) sit past each row's ceil(seq_len/bs)
        # real pages, which is all the kernel ever reads.  Quantized
        # caches thread their scale shards the same way and reuse the
        # kernel's k_scale/v_scale variant.
        from jax.sharding import PartitionSpec as P

        interp = jax.default_backend() != "tpu"

        def body(qs, ks, vs, kc, vc, bts, pos_s, sls, *scales):
            b_loc, t_loc = qs.shape[0], qs.shape[1]
            s_local = kc.shape[0]
            tp_sz = axis_size("tp")
            flat = jax.lax.axis_index("dp") * tp_sz + jax.lax.axis_index("tp")
            offset = flat * s_local
            wslots = kvc.slots_for_positions(bts, pos_s, block_size)
            wslots = wslots.reshape(b_loc * t_loc) - offset
            kr = ks.reshape(b_loc * t_loc, cfg.kv_size)
            vr = vs.reshape(b_loc * t_loc, cfg.kv_size)
            if scales:
                ksc, vsc = scales
                kc, vc, ksc, vsc = kvc.write_kv_quant(
                    kc, vc, ksc, vsc, wslots, kr, vr)
            else:
                kc, vc = kvc.write_kv(kc, vc, wslots, kr, vr)
                ksc = vsc = None
            if dp_local_pallas:
                from dynamo_tpu.ops.pallas import paged_decode_attention

                pages_local = s_local // block_size
                bt_local = jnp.clip(bts - flat * pages_local,
                                    0, pages_local - 1)
                o = paged_decode_attention(
                    qs[:, 0], kc, vc, bt_local, sls,
                    block_size=block_size, scale=cfg.query_scale,
                    soft_cap=cfg.attn_soft_cap, interpret=interp,
                    k_scale=ksc, v_scale=vsc)[:, None]
            else:
                Pw = bts.shape[1]
                C = Pw * block_size
                ctx_pos = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32),
                                           (b_loc, C))
                cslots = kvc.slots_for_positions(bts, ctx_pos, block_size)
                cslots = jnp.clip(cslots - offset, 0, s_local - 1)
                if scales:
                    k_ctx, v_ctx = kvc.gather_kv_quant(
                        kc, vc, ksc, vsc, cslots, cfg.num_kv_heads,
                        out_dtype=qs.dtype)
                else:
                    k_ctx, v_ctx = kvc.gather_kv(kc, vc, cslots,
                                                 cfg.num_kv_heads)
                o = paged_attention(qs, k_ctx, v_ctx, pos_s, ctx_pos, sls,
                                    scale=cfg.query_scale,
                                    soft_cap=cfg.attn_soft_cap)
            if scales:
                return o, kc, vc, ksc, vsc
            return o, kc, vc

        row = P(("dp", "tp"))
        slot = P(("dp", "tp"), None)
        in_specs = [P(("dp", "tp"), None, None, None),
                    P(("dp", "tp"), None, None, None),
                    P(("dp", "tp"), None, None, None),
                    slot, slot, slot, P(("dp", "tp"), None), row]
        out_specs = [P(("dp", "tp"), None, None, None), slot, slot]
        args = [q, k, v, k_cache, v_cache, block_tables, positions,
                seq_lens]
        if quant:
            in_specs += [slot, slot]
            out_specs += [slot, slot]
            args += [k_scale_cache, v_scale_cache]
        res = shard_map(
            body,
            mesh=dp_local_mesh,
            in_specs=tuple(in_specs),
            out_specs=tuple(out_specs),
            check_vma=False,
        )(*args)
        if quant:
            out, k_layer, v_layer, ks_layer, vs_layer = res
        else:
            out, k_layer, v_layer = res
            ks_layer = vs_layer = None
        out = out.reshape(B, T, cfg.q_size) @ p_attn["wo"]
        return out, k_layer, v_layer, ks_layer, vs_layer

    ring_quant = None
    if quant and sp_mesh is not None:
        # ISSUE 12 leg 1 (int8 × ring-SP): quantize the chunk ONCE — the
        # same int8 rows + [chunk, Hkv] scales are scattered into the
        # cache AND rotated around the ring, so ring attention attends
        # exactly the values every dequantized cache-read path sees.
        # (Attending the pre-quantization chunk, as the pre-ISSUE-12
        # raise documented, would silently diverge from decode.)
        kq, ksc = kvc.quantize_kv_rows(k.reshape(B * T, cfg.kv_size),
                                       cfg.num_kv_heads)
        vq, vsc = kvc.quantize_kv_rows(v.reshape(B * T, cfg.kv_size),
                                       cfg.num_kv_heads)
        k_layer, v_layer, ks_layer, vs_layer = kvc.scatter_kv_quant(
            k_cache, v_cache, k_scale_cache, v_scale_cache, write_slots,
            kq, vq, ksc, vsc)
        ring_quant = (
            kq.reshape(B, T, cfg.num_kv_heads, cfg.head_dim),
            vq.reshape(B, T, cfg.num_kv_heads, cfg.head_dim),
            ksc.reshape(B, T, cfg.num_kv_heads),
            vsc.reshape(B, T, cfg.num_kv_heads),
        )
    elif quant:
        k_layer, v_layer, ks_layer, vs_layer = kvc.write_kv_quant(
            k_cache, v_cache, k_scale_cache, v_scale_cache, write_slots,
            k.reshape(B * T, cfg.kv_size),
            v.reshape(B * T, cfg.kv_size),
        )
    else:
        k_layer, v_layer = kvc.write_kv(
            k_cache,
            v_cache,
            write_slots,
            k.reshape(B * T, cfg.kv_size),
            v.reshape(B * T, cfg.kv_size),
        )
        ks_layer = vs_layer = None

    if sp_mesh is not None:
        # Sequence-parallel full-prompt prefill: the chunk IS the whole
        # sequence, sharded over sp — ring attention visits every K/V
        # block over the ICI ring (the Pallas flash kernel or the XLA
        # ppermute oracle, picked in _sp_ring_attention); no cached
        # context is read (chunked continuation stays on the paths
        # below).  Cache writes above remain GSPMD-managed.
        out = _sp_ring_attention(cfg, q, k, v, positions, ring_quant,
                                 sp_mesh, sp_pallas)
    elif ctx_slots is None:
        # Decode hot path: stream pages via the Pallas kernel — no
        # materialised context gather (ops/pallas/paged_attention.py).
        from dynamo_tpu.ops.pallas import paged_decode_attention

        interp = jax.default_backend() != "tpu"
        if pallas_mesh is not None:
            # Sharded serving: GSPMD can't partition a custom call, so
            # the kernel runs under shard_map — heads over tp (each shard
            # sees its [S, F/tp] cache slice, a self-consistent smaller
            # GQA geometry), batch over dp.  Quantized caches shard the
            # [S, Hkv] scale buffers over the SAME head axis (tp | Hkv),
            # so each shard dequantizes its own heads with local scales
            # — the kernel's existing k_scale/v_scale variant, per shard.
            from jax.sharding import PartitionSpec as P

            head = P(None, "tp")
            if quant:
                out = shard_map(
                    lambda qs, ks, vs, ksc, vsc, bts, sls:
                        paged_decode_attention(
                            qs, ks, vs, bts, sls, block_size=block_size,
                            scale=cfg.query_scale,
                            soft_cap=cfg.attn_soft_cap,
                            interpret=interp, k_scale=ksc, v_scale=vsc),
                    mesh=pallas_mesh,
                    in_specs=(P("dp", "tp", None), head, head, head, head,
                              P("dp", None), P("dp")),
                    out_specs=P("dp", "tp", None),
                    check_vma=False,
                )(q[:, 0], k_layer, v_layer, ks_layer, vs_layer,
                  block_tables, seq_lens)[:, None]
            else:
                out = shard_map(
                    lambda qs, ks, vs, bts, sls: paged_decode_attention(
                        qs, ks, vs, bts, sls, block_size=block_size,
                        scale=cfg.query_scale, soft_cap=cfg.attn_soft_cap,
                        interpret=interp),
                    mesh=pallas_mesh,
                    in_specs=(P("dp", "tp", None), head, head,
                              P("dp", None), P("dp")),
                    out_specs=P("dp", "tp", None),
                    check_vma=False,
                )(q[:, 0], k_layer, v_layer, block_tables, seq_lens)[:, None]
        else:
            out = paged_decode_attention(
                q[:, 0], k_layer, v_layer, block_tables, seq_lens,
                block_size=block_size, scale=cfg.query_scale,
                soft_cap=cfg.attn_soft_cap, interpret=interp,
                k_scale=ks_layer, v_scale=vs_layer,
            )[:, None]
    elif quant:
        # Gather + in-register dequant (prefill attention and the
        # non-Pallas decode fallback): same dequant numerics as the
        # kernel's VMEM path (kv_cache.dequantize_rows), cast to q's
        # compute dtype.
        k_ctx, v_ctx = kvc.gather_kv_quant(
            k_layer, v_layer, ks_layer, vs_layer, ctx_slots,
            cfg.num_kv_heads, out_dtype=q.dtype)
        out = paged_attention(q, k_ctx, v_ctx, positions, kv_positions,
                              seq_lens, scale=cfg.query_scale,
                              soft_cap=cfg.attn_soft_cap)
    else:
        k_ctx, v_ctx = kvc.gather_kv(k_layer, v_layer, ctx_slots,
                                     cfg.num_kv_heads)
        out = paged_attention(q, k_ctx, v_ctx, positions, kv_positions,
                              seq_lens, scale=cfg.query_scale,
                              soft_cap=cfg.attn_soft_cap)
    out = out.reshape(B, T, cfg.q_size) @ p_attn["wo"]
    return out, k_layer, v_layer, ks_layer, vs_layer


def _dense_mlp(p: Params, x: jax.Array,
               activation: str = "silu") -> jax.Array:
    act = (jax.nn.silu if activation == "silu"
           else lambda v: jax.nn.gelu(v, approximate=True))
    return (act(x @ p["w_gate"]) * (x @ p["w_up"])) @ p["w_down"]


def _moe_block(cfg: ModelConfig, p: Params, x: jax.Array,
               moe_mode: str, mesh) -> Tuple[jax.Array, jax.Array]:
    """One MoE layer → (out, stats [E+1]: per-expert assignment counts
    plus the dropped-assignments tail slot — ops/moe.py contract).

    The mode ladder (parallel/sharding.resolve_moe_mode):
    - "dense": exact dense-compute (oracle; expert einsums carry an
      explicit E axis so an `ep` mesh axis can shard them under GSPMD).
    - "grouped": meshless fast path — ragged grouped GEMM over
      expert-sorted assignments (ops/pallas/moe_grouped.py), exact and
      byte-identical to the dense oracle.
    - "dispatch": all-to-all token dispatch under shard_map over the
      mesh's dp/ep axes; under ep × tp meshes each expert's MLP is
      additionally tp-sharded on the intermediate dim (partial down
      projection + psum inside the body).  Capacity comes from
      `cfg.moe_capacity` (None = exact, the serving default; bounded
      capacities drop overflow assignments into the counted tail)."""
    from dynamo_tpu.ops import moe as moe_ops

    if mesh is None:
        if moe_mode == "grouped":
            return moe_ops.moe_grouped(
                cfg, p, x, interpret=jax.default_backend() != "tpu")
        return moe_ops.moe_dense(cfg, p, x)
    if moe_mode == "dense":
        return moe_ops.moe_dense(cfg, p, x)

    from jax.sharding import PartitionSpec as P

    # tp > 1: expert weight slices arrive F-sharded ([E_local, H, F/tp] /
    # [E_local, F/tp, H]) and the body psums the partial down projection.
    # tp == 1 keeps the exact pre-ISSUE-17 program (specs with a size-1
    # "tp" axis partition nothing and tp_axis=None adds no collective).
    tp_axis = "tp" if mesh.shape.get("tp", 1) > 1 else None
    wrapped = shard_map(
        lambda xs, ps: moe_ops.moe_dispatch(
            cfg, ps, xs, capacity=cfg.moe_capacity, ep_axis="ep",
            load_psum_axes=("dp", "ep"), tp_axis=tp_axis),
        mesh=mesh,
        in_specs=(P(("dp", "ep"), None, None),
                  {"router": P(None, None),
                   "w_gate": P("ep", None, "tp"),
                   "w_up": P("ep", None, "tp"),
                   "w_down": P("ep", "tp", None)}),
        out_specs=(P(("dp", "ep"), None, None), P(None)),
        check_vma=False,
    )
    return wrapped(x, p)


# ---------------------------------------------------------------------------
# Fused decode window


def make_decode_window(cfg: ModelConfig, block_size: int, window: int,
                       use_pallas_decode: bool = False,
                       greedy_only: bool = False,
                       mesh=None,
                       dp_local: bool = False,
                       moe_mode: str = "dense",
                       with_expert_load: bool = False):
    """K decode steps in ONE device dispatch, tokens fed back on-device.

    The per-token host loop costs a host↔device round-trip per step — the
    latency SURVEY §7 flags as the decode hard part (and which a tunneled
    TPU turns into ~170 ms/step).  `lax.fori_loop` keeps K steps on device:
    each iteration writes the fed token's KV, computes one-position logits,
    samples the next token, and feeds it to the next iteration.  The host
    reads the [K, B] token block lazily, windows behind the dispatch
    (engine pipelining), so steady-state decode never blocks on the wire.

    Sampling: per-row (temperature, top_k, top_p) are fixed across the
    window; per-row keys derive on-device as fold_in(base_key, offset + i)
    so seeded streams stay reproducible across window boundaries and
    batch mixes.  `greedy_only` compiles the argmax-only variant (no sort,
    no keys — the common serving mix).

    Returns run(params, cache, last_tokens[B], positions0[B], seq_lens0[B],
                block_tables[B,P], temp[B], top_k[B], top_p[B],
                base_key_data[B,2] uint32, key_offsets[B])
        -> (cache, tokens[K, B], positions0+K, seq_lens0+K, key_offsets+K).

    The advanced positions/seq_lens/offsets come back as DEVICE arrays so
    the engine can feed the next window with zero host→device transfers —
    on a tunneled chip each small-array upload is a blocking RPC, and r4
    measured ~300 ms/dispatch of pure upload latency before this existed.
    """
    from dynamo_tpu.engine.sampling import sample

    step = make_forward_step(cfg, block_size, use_pallas_decode,
                             mesh=mesh, dp_local=dp_local,
                             moe_mode=moe_mode,
                             with_expert_load=with_expert_load)

    def run(params, cache, last_tokens, positions0, seq_lens0, block_tables,
            temp, top_k, top_p, base_key_data, key_offsets):
        B = last_tokens.shape[0]
        zero_pos = jnp.zeros((B,), jnp.int32)
        # Keys travel as RAW uint32 key data [B, 2] and wrap on device:
        # host code can then build them as plain numpy, which the
        # multihost path requires (typed key arrays can't cross the
        # host→global-array boundary).
        base_keys = (None if greedy_only
                     else jax.random.wrap_key_data(base_key_data))
        # Padding rows (seq_lens0 == 0) must stay dead across device-side
        # advances: their seq_lens pin at 0 (attention loop skipped, no
        # unbounded block-table indices) and their positions pin at the
        # null-resolving pad position.
        live = seq_lens0 > 0

        def body(i, carry):
            cache, toks, out, load = carry
            adv = jnp.where(live, i, 0)
            res = step(
                params, cache, toks[:, None],
                (positions0 + adv)[:, None], seq_lens0 + adv,
                block_tables, zero_pos)
            if with_expert_load:
                # MoE telemetry threads THROUGH the loop carry (the
                # reason windows were dense-only before r5): per-step
                # assignment counts accumulate on device.
                logits, cache, step_load = res
                load = load + step_load
            else:
                logits, cache = res
            if greedy_only:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                keys = jax.vmap(jax.random.fold_in)(base_keys,
                                                    key_offsets + i)
                nxt = sample(logits, temp, top_k, top_p, keys)
            return cache, nxt, out.at[i].set(nxt), load

        out0 = jnp.zeros((window, B), jnp.int32)
        # [E+1]: per-expert counts + dropped tail (ops/moe.py contract).
        load0 = jnp.zeros((cfg.num_experts + 1,), jnp.int32) \
            if with_expert_load else jnp.zeros((), jnp.int32)
        cache, _, out, load = jax.lax.fori_loop(
            0, window, body, (cache, last_tokens, out0, load0))
        adv = jnp.where(live, window, 0)
        base = (cache, out, positions0 + adv, seq_lens0 + adv,
                key_offsets + window)
        return base + (load,) if with_expert_load else base

    return run


# ---------------------------------------------------------------------------
# Packed ragged prefill


def make_packed_prefill_step(cfg: ModelConfig, block_size: int,
                             moe_mode: str = "dense"):
    """Build the packed ragged prefill step (ISSUE 10 tentpole leg 2).

    Several sequences' prefill chunks ride ONE flat `[T]` token axis
    ("segments") instead of padded `[R, T]` rows, and attention streams
    K/V pages straight from the block pool through the Pallas
    flash-prefill kernel (ops/pallas/paged_prefill.py) — no `gather_kv`
    materialisation, no per-(rows, chunk) bucket lattice.  One compiled
    shape per (packed-token bucket, page bucket) serves any mix of chunk
    lengths, so the cold-prefill shape set collapses to a handful the
    worker can prewarm at startup.

    Signature:

        logits, cache = step(params, cache, tokens[T], positions[T],
                             seg_ids[T], block_tables[R, P], q_starts[R],
                             q_lens[R], seq_lens[R], sample_positions[R])

    - tokens/positions: the packed chunks; pad rows (alignment gaps,
      tail) carry the engine's pad position, which resolves to the null
      block.
    - seg_ids: owning segment per token (selects the block-table row for
      the KV scatter); pad rows may carry any id — their pad position
      nulls the write.
    - q_starts/q_lens: each segment's packed row range (PACK_ALIGN'd
      starts); q_len 0 marks a pad segment.
    - seq_lens: total valid context per segment AFTER this chunk —
      cached-prefix residual prefill just starts the chunk positions
      past the resident prefix.
    - sample_positions: packed row whose logits each segment wants (its
      last real token); logits come back `[R, V]`.

    int8 pools route through the kernel's dequant-in-VMEM variant
    (static branch on the cache pytree, like the padded step).  MoE
    models compose (ISSUE 17 killed the old exclusion): the packed
    [1, T, H] hidden rides `_moe_block` with the meshless `moe_mode`
    ("dense" oracle or "grouped" fast path — packed prefill is a
    meshless-engine plane) and the step returns a THIRD output, the
    [E+1] expert-load stats vector.  The kernel runs in interpret mode
    off-TPU, so the packed plane is CPU-testable like the decode kernel.
    """
    cfg.validate()
    from dynamo_tpu.ops.pallas import paged_prefill_attention

    def step(params, cache, tokens, positions, seg_ids, block_tables,
             q_starts, q_lens, seq_lens, sample_positions):
        T = tokens.shape[0]
        interp = jax.default_backend() != "tpu"
        quant = kvc.cache_is_quantized(cache)
        # Per-token write slots through the owning segment's table.
        bt_tok = jnp.take(block_tables, seg_ids, axis=0)        # [T, P]
        write_slots = kvc.slots_for_positions(
            bt_tok, positions[:, None], block_size).reshape(T)

        x = jnp.take(params["embed"], tokens, axis=0)[None]     # [1, T, H]
        if cfg.embed_scale:
            x = x * jnp.asarray(cfg.hidden_size ** 0.5, x.dtype)
        pos2 = positions[None]                                  # [1, T]
        k_layers = list(cache["k"])
        v_layers = list(cache["v"])
        ks_layers = (list(cache["k_scale"]) if quant
                     else [None] * cfg.num_layers)
        vs_layers = (list(cache["v_scale"]) if quant
                     else [None] * cfg.num_layers)
        expert_load = jnp.zeros(
            (cfg.num_experts + 1 if cfg.is_moe else 1,), jnp.int32)
        off = cfg.rms_offset
        for i, layer in enumerate(params["layers"]):
            p_attn = layer["attn"]
            h_in = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps, off)
            q = (h_in @ p_attn["wq"]).reshape(1, T, cfg.num_heads,
                                              cfg.head_dim)
            k = (h_in @ p_attn["wk"]).reshape(1, T, cfg.num_kv_heads,
                                              cfg.head_dim)
            v = (h_in @ p_attn["wv"]).reshape(1, T, cfg.num_kv_heads,
                                              cfg.head_dim)
            q = rope(q, pos2, cfg.rope_theta)
            k = rope(k, pos2, cfg.rope_theta)
            if quant:
                (k_layers[i], v_layers[i],
                 ks_layers[i], vs_layers[i]) = kvc.write_kv_quant(
                    k_layers[i], v_layers[i], ks_layers[i], vs_layers[i],
                    write_slots,
                    k.reshape(T, cfg.kv_size), v.reshape(T, cfg.kv_size))
            else:
                k_layers[i], v_layers[i] = kvc.write_kv(
                    k_layers[i], v_layers[i], write_slots,
                    k.reshape(T, cfg.kv_size), v.reshape(T, cfg.kv_size))
            # Write-then-attend: the chunk's own K/V are pool-resident
            # rows now, so cached prefix and in-chunk causality are one
            # position mask inside the kernel.
            attn = paged_prefill_attention(
                q[0], k_layers[i], v_layers[i], block_tables, seq_lens,
                q_starts, q_lens, block_size=block_size,
                scale=cfg.query_scale, soft_cap=cfg.attn_soft_cap,
                interpret=interp,
                k_scale=ks_layers[i], v_scale=vs_layers[i])
            attn = attn.reshape(1, T, cfg.q_size) @ p_attn["wo"]
            if cfg.post_norms:
                attn = rms_norm(attn, layer["post_attn_norm"],
                                cfg.rms_norm_eps, off)
            x = x + attn
            h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps, off)
            if cfg.is_moe:
                moe_out, load = _moe_block(cfg, layer["moe"], h,
                                           moe_mode, None)
                x = x + moe_out
                expert_load = expert_load + load
            else:
                mlp_out = _dense_mlp(layer["mlp"], h, cfg.activation)
                if cfg.post_norms:
                    mlp_out = rms_norm(mlp_out, layer["post_mlp_norm"],
                                       cfg.rms_norm_eps, off)
                x = x + mlp_out

        x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps, off)
        # LM head on one packed row per segment ([R, H] @ [H, V]).
        sel = jnp.take(x[0], sample_positions.astype(jnp.int32), axis=0)
        head = params.get("lm_head")
        if head is None:
            head = params["embed"].T
        logits = (sel @ head).astype(jnp.float32)
        if cfg.final_soft_cap is not None:
            logits = cfg.final_soft_cap * jnp.tanh(
                logits / cfg.final_soft_cap)
        new_cache = {"k": k_layers, "v": v_layers}
        if quant:
            new_cache["k_scale"] = ks_layers
            new_cache["v_scale"] = vs_layers
        if cfg.is_moe:
            return logits, new_cache, expert_load
        return logits, new_cache

    return step


# ---------------------------------------------------------------------------
# Forward


def make_forward_step(cfg: ModelConfig, block_size: int,
                      use_pallas_decode: bool = False,
                      moe_mode: str = "dense",
                      mesh=None,
                      with_expert_load: bool = False,
                      sp_ring: bool = False,
                      sp_ring_pallas: bool = False,
                      return_hidden: bool = False,
                      with_input_embeds: bool = False,
                      dp_local: bool = False):
    """Build the jitted unified step for a given cache geometry.

    Separate factory (rather than passing block_size as a traced value)
    because slot math needs the block size statically for XLA to fold the
    index arithmetic.  With `use_pallas_decode`, T==1 traces route
    attention through the Pallas paged-decode kernel instead of the
    gathered-context XLA path (chunk length is static at trace time, so
    the same factory serves both prefill and decode compilations).

    MoE: `moe_mode` "dense" (exact oracle), "grouped" (meshless ragged
    grouped GEMM) or "dispatch" (all-to-all over the mesh's ep axis —
    needs `mesh`).  `with_expert_load=True` makes the step return
    (logits, cache, stats[E+1]) — per-expert assignment counts plus the
    dropped-assignments tail, the telemetry the reference exposes per
    worker (`base_handlers.py:40-62`); the default 2-tuple return keeps
    every non-MoE call site unchanged.

    `sp_ring`: sequence-parallel FULL-PROMPT prefill — the T axis shards
    over the mesh's sp axis and attention runs on the ICI ring.  The
    chunk must be the whole sequence (no prior cached context is read);
    build via parallel.sharding.make_sp_prefill_step.  With
    `sp_ring_pallas`, eligible geometry runs the Pallas flash ring
    kernel (ops/pallas/ring_attention.py — RDMA exchange hidden under
    the fold) instead of the XLA ppermute ring.
    """
    cfg.validate()

    def step(
        params: Params,
        cache: Dict,
        tokens: jax.Array,            # [B, T]
        positions: jax.Array,         # [B, T]
        seq_lens: jax.Array,          # [B]
        block_tables: jax.Array,      # [B, P]
        sample_positions=None,        # [B] chunk-local index, or None = all
        input_embeds=None,            # [B, T, H] (with_input_embeds only)
        embed_mask=None,              # [B, T] bool: row uses input_embeds
    ) -> Tuple[jax.Array, Dict]:
        B, T = tokens.shape
        P = block_tables.shape[1]
        C = P * block_size  # max context representable by the table

        write_slots = kvc.slots_for_positions(block_tables, positions, block_size)
        write_slots = write_slots.reshape(B * T)

        if ((use_pallas_decode or dp_local) and T == 1) \
                or (sp_ring and T > 1):
            ctx_positions = ctx_slots = None  # no materialised ctx gather
        else:
            ctx_positions = jnp.broadcast_to(
                jnp.arange(C, dtype=jnp.int32), (B, C)
            )
            ctx_slots = kvc.slots_for_positions(
                block_tables, ctx_positions, block_size)

        x = jnp.take(params["embed"], tokens, axis=0)
        if with_input_embeds:
            # Multimodal prefill: masked chunk positions take provided
            # embeddings (the encode worker's vision-tower output) in
            # place of the token lookup (llm/multimodal.py).
            x = jnp.where(embed_mask[:, :, None],
                          input_embeds.astype(x.dtype), x)
        if cfg.embed_scale:
            # Gemma convention: embeddings scale by sqrt(hidden), with
            # the multiplier cast to the model dtype first (HF parity).
            x = x * jnp.asarray(cfg.hidden_size ** 0.5, x.dtype)
        k_layers = list(cache["k"])
        v_layers = list(cache["v"])
        # int8 cache: sibling per-layer scale buffers ride the same pytree
        # (kv_cache.init_cache) — their presence selects the quantized
        # write/read paths statically at trace time.
        quant = kvc.cache_is_quantized(cache)
        ks_layers = (list(cache["k_scale"]) if quant
                     else [None] * cfg.num_layers)
        vs_layers = (list(cache["v_scale"]) if quant
                     else [None] * cfg.num_layers)
        expert_load = jnp.zeros(
            (cfg.num_experts + 1 if cfg.is_moe else 1,), jnp.int32)
        off = cfg.rms_offset
        for i, layer in enumerate(params["layers"]):
            (attn_out, k_layers[i], v_layers[i],
             ks_layers[i], vs_layers[i]) = _attention_block(
                cfg, layer["attn"],
                rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps, off),
                positions, seq_lens, write_slots, ctx_slots, ctx_positions,
                block_tables, block_size,
                k_layers[i], v_layers[i],
                sp_mesh=mesh if (sp_ring and T > 1) else None,
                sp_pallas=sp_ring_pallas,
                # dp_local owns its own shard_map body; pallas routing
                # there happens INSIDE it (local slot rebase), not via
                # the head-sharded pallas_mesh wrapper.
                pallas_mesh=(mesh if (use_pallas_decode and T == 1
                                      and mesh is not None
                                      and not dp_local) else None),
                dp_local_mesh=(mesh if (dp_local and T == 1
                                        and mesh is not None) else None),
                dp_local_pallas=use_pallas_decode and dp_local,
                k_scale_cache=ks_layers[i], v_scale_cache=vs_layers[i],
            )
            if cfg.post_norms:
                attn_out = rms_norm(attn_out, layer["post_attn_norm"],
                                    cfg.rms_norm_eps, off)
            x = x + attn_out
            h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps, off)
            if cfg.is_moe:
                moe_out, load = _moe_block(cfg, layer["moe"], h,
                                           moe_mode, mesh)
                x = x + moe_out
                expert_load = expert_load + load
            else:
                mlp_out = _dense_mlp(layer["mlp"], h, cfg.activation)
                if cfg.post_norms:
                    mlp_out = rms_norm(mlp_out, layer["post_mlp_norm"],
                                       cfg.rms_norm_eps, off)
                x = x + mlp_out

        x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps, off)
        # LM head on the one sampled row per sequence ([B, H] @ [H, V]) —
        # full [B, T, V] logits of a batched 512-token prefill would be a
        # multi-GB f32 allocation for nothing.  None keeps every position
        # (tests, logprob paths).
        if sample_positions is not None:
            x = jnp.take_along_axis(
                x, sample_positions[:, None, None].astype(jnp.int32), axis=1
            )[:, 0]
        new_cache = {"k": k_layers, "v": v_layers}
        if quant:
            new_cache["k_scale"] = ks_layers
            new_cache["v_scale"] = vs_layers
        if return_hidden:
            # Embeddings path: the last-token final-norm hidden state IS
            # the embedding (causal-LM convention, e5-mistral-style); the
            # LM head is skipped entirely.
            return x.astype(jnp.float32), new_cache
        head = params.get("lm_head")
        if head is None:
            head = params["embed"].T
        logits = (x @ head).astype(jnp.float32)
        if cfg.final_soft_cap is not None:
            logits = cfg.final_soft_cap * jnp.tanh(
                logits / cfg.final_soft_cap)
        if with_expert_load:
            return logits, new_cache, expert_load
        return logits, new_cache

    return step
