"""Checkpoint loading: HF-format safetensors → engine param pytree.

Role of the reference's `lib/llm/src/local_model.rs:39-236` + `hub.rs`
(resolve a model path, build the deployment card, hand real weights to the
engine) — minus the hub download (no egress in this environment; a local
directory in HF layout is the contract, which is also what a mounted model
cache looks like in deployment).

Name mapping (HF Llama/Mixtral → dynamo_tpu.models.llama pytree):

    model.embed_tokens.weight            embed                [V, H]
    model.norm.weight                    final_norm           [H]
    lm_head.weight                       lm_head (transposed) [H, V]
    model.layers.N.input_layernorm       layers[N].attn_norm
    model.layers.N.post_attention_ln     layers[N].mlp_norm
    ...self_attn.{q,k,v}_proj.weight     attn.w{q,k,v} (transposed)
    ...self_attn.o_proj.weight           attn.wo       (transposed)
    ...mlp.{gate,up,down}_proj.weight    mlp.w_{gate,up,down} (transposed)
    ...block_sparse_moe.gate.weight      moe.router    (transposed)
    ...block_sparse_moe.experts.E.w{1,3,2}  moe.w_{gate,up,down}[E]

HF stores `nn.Linear` weights as [out, in]; our pytree multiplies x @ W so
every projection transposes on load.  GQA head order: HF q head h shares
kv head h // G (blocked) — ops/attention.py uses the same convention, and
our RoPE is the half-split (NeoX/Llama) rotation HF uses, so logits match
a `transformers` forward to float tolerance (locked by
tests/test_loader.py).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models.config import ModelConfig

Params = Dict


def config_from_hf(hf: dict, name: str = "") -> ModelConfig:
    """Map an HF config.json dict to our ModelConfig (Llama/Mistral/Qwen
    family, Mixtral MoE, Gemma-2)."""
    arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
    gemma2 = "Gemma2" in arch or hf.get("model_type") == "gemma2"
    num_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hf["hidden_size"] // num_heads
    max_context = hf.get("max_position_embeddings", 8192)
    if gemma2 and hf.get("sliding_window"):
        # Gemma-2 alternates sliding-window and global layers; this
        # engine runs every layer global, which is EXACT while context
        # stays within the window — clamp rather than silently diverge.
        max_context = min(max_context, int(hf["sliding_window"]))
    query_scale = None
    if gemma2 and hf.get("query_pre_attn_scalar"):
        query_scale = float(hf["query_pre_attn_scalar"]) ** -0.5
    return ModelConfig(
        name=name or hf.get("model_type", "hf-model"),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf.get("num_key_value_heads", num_heads),
        head_dim=head_dim,
        intermediate_size=hf["intermediate_size"],
        max_context=max_context,
        rope_theta=float(hf.get("rope_theta", 10_000.0)),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        num_experts=hf.get("num_local_experts", 0),
        num_experts_per_token=hf.get("num_experts_per_tok", 2),
        # HF omits defaulted keys from config.json; Gemma-2's default is
        # TIED embeddings (Llama's is untied).
        tie_embeddings=bool(hf.get("tie_word_embeddings", gemma2)),
        activation="gelu_tanh" if gemma2 else "silu",
        attn_soft_cap=hf.get("attn_logit_softcapping") if gemma2 else None,
        final_soft_cap=(hf.get("final_logit_softcapping")
                        if gemma2 else None),
        post_norms=gemma2,
        rms_offset=gemma2,
        embed_scale=gemma2,
        query_scale=query_scale,
    )


class _TensorSource:
    """All safetensors shards of a checkpoint, keyed by tensor name."""

    def __init__(self, model_dir: str) -> None:
        from safetensors import safe_open

        self._handles = []
        self._where: Dict[str, int] = {}
        shards = sorted(f for f in os.listdir(model_dir)
                        if f.endswith(".safetensors"))
        if not shards:
            raise FileNotFoundError(f"no .safetensors files in {model_dir}")
        for i, fname in enumerate(shards):
            h = safe_open(os.path.join(model_dir, fname), framework="np")
            self._handles.append(h)
            for key in h.keys():
                self._where[key] = i

    def get(self, name: str) -> np.ndarray:
        idx = self._where.get(name)
        if idx is None:
            raise KeyError(f"tensor {name!r} not in checkpoint "
                           f"(have e.g. {sorted(self._where)[:5]})")
        return self._handles[idx].get_tensor(name)

    def __contains__(self, name: str) -> bool:
        return name in self._where


def load_params(model_dir: str,
                cfg: Optional[ModelConfig] = None,
                dtype=None) -> Tuple[ModelConfig, Params]:
    """Load an HF-layout checkpoint directory into (config, params).

    `dtype=None` keeps the config's dtype (bf16 for real models).  Arrays
    land as jnp arrays on the default device; for sharded serving the
    engine re-places them with shard_pytree (device_put moves, no copy
    through host when layouts agree).
    """
    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = config_from_hf(json.load(f),
                                 name=os.path.basename(model_dir.rstrip("/")))
    cfg.validate()
    dtype = dtype or cfg.dtype
    src = _TensorSource(model_dir)

    def lin(name: str) -> jnp.ndarray:
        # HF nn.Linear [out, in] -> ours [in, out].
        return jnp.asarray(src.get(name)).T.astype(dtype)

    def vec(name: str) -> jnp.ndarray:
        return jnp.asarray(src.get(name)).astype(dtype)

    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        layer = {
            "attn": {
                "wq": lin(p + "self_attn.q_proj.weight"),
                "wk": lin(p + "self_attn.k_proj.weight"),
                "wv": lin(p + "self_attn.v_proj.weight"),
                "wo": lin(p + "self_attn.o_proj.weight"),
            },
            "attn_norm": vec(p + "input_layernorm.weight"),
        }
        if cfg.post_norms:
            # Gemma-2 naming: post_attention_layernorm is a TRUE
            # post-norm; the pre-MLP norm is pre_feedforward_layernorm
            # (in Llama, post_attention_layernorm is the pre-MLP norm).
            layer["post_attn_norm"] = vec(
                p + "post_attention_layernorm.weight")
            layer["mlp_norm"] = vec(p + "pre_feedforward_layernorm.weight")
            layer["post_mlp_norm"] = vec(
                p + "post_feedforward_layernorm.weight")
        else:
            layer["mlp_norm"] = vec(p + "post_attention_layernorm.weight")
        if cfg.is_moe:
            experts_gate = []
            experts_up = []
            experts_down = []
            for e in range(cfg.num_experts):
                ep = p + f"block_sparse_moe.experts.{e}."
                experts_gate.append(lin(ep + "w1.weight"))
                experts_up.append(lin(ep + "w3.weight"))
                experts_down.append(lin(ep + "w2.weight"))
            layer["moe"] = {
                "router": lin(p + "block_sparse_moe.gate.weight"),
                "w_gate": jnp.stack(experts_gate),
                "w_up": jnp.stack(experts_up),
                "w_down": jnp.stack(experts_down),
            }
        else:
            layer["mlp"] = {
                "w_gate": lin(p + "mlp.gate_proj.weight"),
                "w_up": lin(p + "mlp.up_proj.weight"),
                "w_down": lin(p + "mlp.down_proj.weight"),
            }
        layers.append(layer)

    params: Params = {
        "embed": jnp.asarray(src.get("model.embed_tokens.weight")).astype(dtype),
        "final_norm": vec("model.norm.weight"),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" in src:
            params["lm_head"] = lin("lm_head.weight")
        else:
            cfg = cfg.replace(tie_embeddings=True)
    return cfg, params


def resolve_model(path_or_preset: str):
    """Resolve a --model argument: an HF-layout directory (real weights) or
    a preset name (random weights; bench/test mode).

    Returns (cfg, params_or_None, tokenizer_spec, chat_template_or_None).
    """
    from dynamo_tpu.models import config as mcfg

    if path_or_preset.endswith(".gguf") and os.path.isfile(path_or_preset):
        from dynamo_tpu.models.gguf import load_gguf

        cfg, params, tok = load_gguf(path_or_preset)
        # Serving tokenizer: GGUF embeds a sentencepiece-style vocab; the
        # byte tokenizer keeps the surface functional while the vocab
        # (extracted — the gguf_metadata.rs parity point) rides the card
        # for clients that want it.
        spec = {"kind": "byte"}
        if tok:
            spec["gguf_tokenizer"] = {k: tok[k] for k in
                                      ("model", "bos_token_id",
                                       "eos_token_id") if k in tok}
        return cfg, params, spec, None
    if (not os.path.exists(path_or_preset)
            and path_or_preset.count("/") == 1
            and not path_or_preset.startswith(".")):
        # `org/repo` → local HF hub cache (models/hub.py; the reference's
        # hub.rs resolution, cache-only in a no-egress environment).
        # Preset names never contain '/', so this cannot shadow them.
        from dynamo_tpu.models.hub import resolve_cached_repo

        path_or_preset = resolve_cached_repo(path_or_preset)
    if os.path.isdir(path_or_preset):
        cfg, params = load_params(path_or_preset)
        spec = {"kind": "byte"}
        tok_path = os.path.join(path_or_preset, "tokenizer.json")
        if os.path.exists(tok_path):
            with open(tok_path) as f:
                spec = {"kind": "hf_inline", "json": f.read()}
        template = None
        cfg_path = os.path.join(path_or_preset, "tokenizer_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                tok_cfg = json.load(f)
            template = tok_cfg.get("chat_template")
            eos = tok_cfg.get("eos_token")
            if isinstance(eos, dict):
                eos = eos.get("content")
            if eos and spec.get("kind") == "hf_inline":
                spec["eos_token"] = eos
        return cfg, params, spec, template
    return mcfg.get_config(path_or_preset), None, {"kind": "byte"}, None
