"""Loader for the native (C++) components under csrc/.

SURVEY §2.4: where the reference runs native code (Rust `lib/tokens`,
the indexer's block hashing), we ship C++ — not Python stand-ins.  The
shared library is compiled on first use with the system g++ (the image's
baked toolchain); if compilation fails the pure-Python implementations
keep working and a warning records the degradation.

Binding is ctypes (no pybind11 in the image); the ABI is the short
extern-C surface of csrc/block_hash.cpp.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc")
BUILD_DIR = os.path.join(CSRC, "build")
LIB_PATH = os.path.join(BUILD_DIR, "libblockhash.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    src = os.path.join(CSRC, "block_hash.cpp")
    if not os.path.exists(src):
        return False
    os.makedirs(BUILD_DIR, exist_ok=True)
    # Compile to a process-unique temp name, then rename atomically:
    # several processes on one host may race to build the shared path,
    # and CDLL-ing a half-written .so is a crash, not an error.
    tmp = f"{LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, LIB_PATH)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError, OSError) as e:
        detail = getattr(e, "stderr", b"")
        logger.warning("native block-hash build failed (%s); using the "
                       "Python path: %s", e,
                       detail.decode()[:500] if detail else "")
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


async def warmup() -> bool:
    """Build/load the native library OFF the event loop.  Server
    entrypoints call this before serving: the lazy first-use build would
    otherwise run a multi-second g++ on the loop thread mid-request,
    freezing streams and lease keep-alives."""
    import asyncio

    return await asyncio.to_thread(lambda: get_lib() is not None)


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first call; None when
    unavailable (callers fall back to Python)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(LIB_PATH) and not _compile():
            return None
        try:
            lib = ctypes.CDLL(LIB_PATH)
        except OSError as e:
            logger.warning("native block-hash load failed: %s", e)
            return None
        lib.chained_block_hashes.restype = ctypes.c_int64
        lib.chained_block_hashes.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.hash_one_block.restype = ctypes.c_uint64
        lib.hash_one_block.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
            ctypes.c_uint64]
        _lib = lib
        return _lib


def chained_block_hashes(tokens_u32: np.ndarray, block_size: int,
                         parent: int) -> Optional[np.ndarray]:
    """Native chained hashing; returns uint64 hashes for full blocks, or
    None when the native path is unavailable.  `tokens_u32` must already
    be a contiguous uint32 array (tokens._as_u32 output)."""
    lib = get_lib()
    if lib is None:
        return None
    arr = np.ascontiguousarray(tokens_u32, dtype=np.uint32)
    n_full = len(arr) // block_size
    out = np.empty((n_full,), np.uint64)
    got = lib.chained_block_hashes(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(arr),
        block_size, parent & 0xFFFFFFFFFFFFFFFF,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    if got != n_full:
        logger.warning("native chained_block_hashes returned %d != %d",
                       got, n_full)
        return None
    return out


def hash_one_block(tokens_u32: np.ndarray, parent: int) -> Optional[int]:
    lib = get_lib()
    if lib is None:
        return None
    arr = np.ascontiguousarray(tokens_u32, dtype=np.uint32)
    return int(lib.hash_one_block(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(arr),
        parent & 0xFFFFFFFFFFFFFFFF))
