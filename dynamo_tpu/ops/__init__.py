"""TPU compute ops: attention over the paged cache, Pallas kernels.

Each op ships two implementations with identical semantics:
- a pure-`jnp` reference (runs anywhere, used by CPU-mesh tests), and
- a Pallas TPU kernel for the hot path (the analog of the reference's only
  CUDA kernel, `lib/llm/src/kernels/block_copy.cu`, plus the paged-attention
  kernels vLLM supplies on GPU).
"""
