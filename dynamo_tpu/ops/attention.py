"""Attention over the paged KV cache — unified prefill/decode step.

One op serves chunked prefill, full prefill and single-token decode: the
current chunk's Q attends to every cached context slot (the chunk's own K/V
having just been written), with a mask `kv_pos <= q_pos` on absolute
positions.  With chunk length T=1 this is decode; with T=prompt length it is
full prefill; anything between is the chunked-prefill path the reference
models in its mocker (`lib/llm/src/mocker/scheduler.rs`, chunked prefill
budget) and delegates to vLLM for real.

Design notes (TPU-first):
- Gather-based context reads: the whole batch's context K/V is materialised
  as `[B, C, H, D]` via one `take` on the flat slot axis.  XLA fuses the
  gather into the attention einsum's operand pipeline; a dedicated Pallas
  paged-attention kernel (dynamo_tpu/ops/pallas/) replaces this on the
  decode hot path to avoid the HBM round-trip.
- GQA grouping stays explicit (`[B, G, Hkv, ...]` einsums) instead of
  `repeat`ing KV heads — avoids materialising repeated KV.
- Softmax in float32 regardless of cache dtype; logits scaled pre-softmax.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def paged_attention(
    q: jax.Array,           # [B, T, Hq, D] current chunk queries
    k_ctx: jax.Array,       # [B, C, Hkv, D] gathered context keys
    v_ctx: jax.Array,       # [B, C, Hkv, D] gathered context values
    q_positions: jax.Array, # [B, T] absolute position of each query token
    kv_positions: jax.Array,# [B, C] absolute position of each context slot
    seq_lens: jax.Array,    # [B] valid context length per sequence
    scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
) -> jax.Array:
    """Masked GQA attention of chunk queries against gathered context.

    Mask: a context slot c is visible to query t iff
    `kv_positions[c] < seq_lens` (slot is real) and
    `kv_positions[c] <= q_positions[t]` (causality on absolute positions).

    Returns [B, T, Hq, D] in q's dtype.
    """
    B, T, Hq, D = q.shape
    _, C, Hkv, _ = k_ctx.shape
    G = Hq // Hkv
    if scale is None:
        scale = D ** -0.5

    # Blocked GQA convention (HF Llama): q head h shares kv head h // G —
    # the reshape keeps kv as the SLOW axis.  (An interleaved reshape is
    # self-consistent for random weights but silently wrong for real
    # checkpoints.)
    #
    # K/V stay in cache dtype (bf16 on TPU) with f32 MXU accumulation —
    # casting the gathered context to f32 (r2) materialised 2x the bytes
    # per layer per step for no accuracy the f32 accumulator doesn't
    # already provide.  Softmax itself runs in f32.
    qg = q.reshape(B, T, Hkv, G, D)

    # [B, Hkv, G, T, C]
    scores = jnp.einsum("btkgd,bckd->bkgtc", qg, k_ctx,
                        preferred_element_type=jnp.float32) * scale
    if soft_cap is not None:
        scores = soft_cap * jnp.tanh(scores / soft_cap)

    valid = kv_positions[:, None, :] < seq_lens[:, None, None]        # [B, 1, C]
    causal = kv_positions[:, None, :] <= q_positions[:, :, None]      # [B, T, C]
    mask = (valid & causal)[:, None, None, :, :]                      # [B,1,1,T,C]
    scores = jnp.where(mask, scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    # Fully-masked rows (padding queries) produce uniform probs over junk;
    # callers discard padding-token outputs, so no NaN guard is needed
    # beyond softmax's own max-subtraction.
    out = jnp.einsum("bkgtc,bckd->btkgd", probs.astype(v_ctx.dtype), v_ctx,
                     preferred_element_type=jnp.float32)
    return out.reshape(B, T, Hq, D).astype(q.dtype)


def causal_attention(
    q: jax.Array,  # [B, T, Hq, D]
    k: jax.Array,  # [B, T, Hkv, D]
    v: jax.Array,  # [B, T, Hkv, D]
    scale: Optional[float] = None,
) -> jax.Array:
    """Plain causal self-attention (no cache) — used by tests as the ground
    truth the paged path must reproduce, and by ring attention as the
    per-shard inner op."""
    B, T, Hq, D = q.shape
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    seq_lens = jnp.full((B,), T, dtype=jnp.int32)
    return paged_attention(q, k, v, positions, positions, seq_lens, scale=scale)
