"""Mixture-of-Experts compute paths: the dense | grouped | dispatch ladder.

The reference orchestrates wide-EP engines (SGLang wide-EP container,
`container/Dockerfile.sglang-wideep`; expert-distribution telemetry
`components/backends/sglang/src/dynamo/sglang/common/base_handlers.py:
40-62`) but owns no MoE math.  Here the engine is ours, so EP is a
first-class compute path (SURVEY §2.5 row "EP / MoE"):

- `moe_dense` — every device runs ALL tokens through its local experts and
  zero-gates the non-selected ones.  Always exact; the CPU-test oracle.
  Costs E/k× the minimal FLOPs *and weight bytes* (VERDICT r2 weak #4) —
  that waste is precisely what the other two rungs remove.
- `moe_grouped` — the single-chip/per-shard fast path: assignments are
  sorted by expert on device, each expert's group padded to a row-tile
  multiple, and ONE ragged grouped GEMM (ops/pallas/moe_grouped.py)
  runs only the selected (token, expert) work, streaming each active
  expert's weights HBM→VMEM once in the decode regime.  bf16/f32
  weights or the int8-weight pytree (`quantize_moe_params` — static
  structure branch, same discipline as kv_quant).
- `moe_dispatch` — Switch-Transformer-style token dispatch with a STATIC
  per-expert capacity (XLA needs fixed shapes): tokens are scattered into
  per-expert buffers, `jax.lax.all_to_all` moves buffers to the shard
  owning each expert over the `ep` mesh axis, local experts run one
  batched einsum, and a second all_to_all brings outputs home for the
  gate-weighted combine.  Under ep × tp meshes each expert's MLP is
  additionally tp-sharded on the intermediate dim (`tp_axis`): gate/up
  project into a local F/tp slice, the down projection partial-sums, and
  one psum over tp completes it — tokens and routing stay replicated
  across tp, the all_to_all stays an ep-only collective.
- Capacity semantics: `capacity` = tokens per expert per source shard.
  With `capacity >= tokens_per_shard` routing is EXACT (an expert can
  receive at most every local token once — top-k choices are distinct
  experts).  Smaller capacities drop overflow assignments (their gate
  mass is lost, Switch convention): the throughput/exactness knob is the
  deployment's (`ModelConfig.moe_capacity`), not the kernel's — serving
  defaults to exact, and drops are COUNTED, never silent.

Expert-load telemetry: every path returns an int32 stats vector of
length E+1 — per-expert assignment counts plus a dropped-assignments
tail slot (always 0 for the exact paths) — so the worker can publish
the expert distribution the reference exposes AND an honest drop
counter when a bounded capacity is configured.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.runtime import jax_compat
from dynamo_tpu.runtime.contracts import hot_path

from dynamo_tpu.models.config import ModelConfig

Params = dict


def router_topk(cfg: ModelConfig, p_moe: Params, x: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
    """Top-k routing (Mixtral convention: softmax over the selected
    experts' logits).  x: [N, H] → (expert_ids [N, k], gates [N, k])."""
    logits = (x @ p_moe["router"]).astype(jnp.float32)       # [N, E]
    k = cfg.num_experts_per_token
    top_vals, top_idx = jax.lax.top_k(logits, k)             # [N, k]
    gates = jax.nn.softmax(top_vals, axis=-1)                # renormalised
    return top_idx, gates.astype(x.dtype)


def expert_ffn(p_moe: Params, h: jax.Array) -> jax.Array:
    """Batched expert MLPs: h [E, C, H] with weights [E, H, F]."""
    up = jax.nn.silu(jnp.einsum("ech,ehf->ecf", h, p_moe["w_gate"]))
    up = up * jnp.einsum("ech,ehf->ecf", h, p_moe["w_up"])
    return jnp.einsum("ecf,efh->ech", up, p_moe["w_down"])


def _with_drop_tail(load: jax.Array, dropped=None) -> jax.Array:
    """[E] per-expert counts → [E+1] stats vector with the dropped-
    assignments tail slot (0 for exact paths)."""
    tail = (jnp.zeros((1,), jnp.int32) if dropped is None
            else jnp.reshape(dropped.astype(jnp.int32), (1,)))
    return jnp.concatenate([load.astype(jnp.int32), tail])


def moe_dense(cfg: ModelConfig, p_moe: Params, x: jax.Array
              ) -> Tuple[jax.Array, jax.Array]:
    """Exact dense-compute MoE.  x: [B, T, H] → (out, stats [E+1]).

    Routing/gating go through the SAME `router_topk` the grouped and
    dispatch paths use (not a masked full-E softmax, whose tie handling
    at the k-th logit differs — bf16 actually produces such ties), and
    the combine reduces over the k selected experts in EXPERT-INDEX
    order — the one combine structure every path in this module shares,
    which is what lets the grouped output be byte-identical to this
    oracle instead of 1 ulp away."""
    B, T, H = x.shape
    top_idx, gates = router_topk(cfg, p_moe, x.reshape(B * T, H))
    top_idx = top_idx.reshape(B, T, -1)                      # [B, T, k]
    gates = gates.reshape(B, T, -1)

    hidden = jax.nn.silu(jnp.einsum("bth,ehf->betf", x, p_moe["w_gate"]))
    hidden = hidden * jnp.einsum("bth,ehf->betf", x, p_moe["w_up"])
    expert_out = jnp.einsum("betf,efh->beth", hidden, p_moe["w_down"])
    kord = jnp.argsort(top_idx, axis=-1, stable=True)        # [B, T, k]
    idx_sorted = jnp.take_along_axis(top_idx, kord, axis=-1)
    picked = jnp.take_along_axis(
        expert_out.transpose(0, 2, 1, 3),                    # [B, T, E, H]
        idx_sorted[..., None], axis=2)                       # [B, T, k, H]
    g_sel = jnp.take_along_axis(gates, kord, axis=-1)        # [B, T, k]
    out = jnp.einsum("btkh,btk->bth", picked, g_sel)
    load = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.num_experts, dtype=jnp.int32),
        axis=(0, 1, 2))
    return out, _with_drop_tail(load)


@hot_path
def moe_grouped(cfg: ModelConfig, p_moe: Params, x: jax.Array,
                *, block_rows: Optional[int] = None,
                interpret: bool = False
                ) -> Tuple[jax.Array, jax.Array]:
    """Grouped-GEMM MoE (the single-chip fast path).  x: [B, T, H] →
    (out, stats [E+1]).  Exact — no capacity, nothing dropped.

    Device-side plumbing around ops/pallas/moe_grouped.py:
    sort the N*k (token, expert) assignments by expert (stable argsort),
    pad each expert's group to a `block_rows` multiple (padding rows are
    zero and compute harmless zeros), hand the packed buffer plus a
    tile→expert map to the ragged kernel, then gather each assignment's
    output row back and combine with the top-k gates — the same
    f32-free, x-dtype combine `moe_dense`'s gate einsum performs, which
    is what keeps the two paths byte-comparable."""
    from dynamo_tpu.ops.pallas.moe_grouped import (
        DEFAULT_BLOCK_ROWS, grouped_expert_ffn, moe_params_quantized)

    B, T, H = x.shape
    N = B * T
    E = cfg.num_experts
    k = cfg.num_experts_per_token
    bm = block_rows or DEFAULT_BLOCK_ROWS
    S = N * k

    x2 = x.reshape(N, H)
    expert_ids, gates = router_topk(cfg, p_moe, x2)          # [N, k]
    flat_e = expert_ids.reshape(-1)                          # [S]
    counts = jnp.sum(
        jax.nn.one_hot(flat_e, E, dtype=jnp.int32), axis=0)  # [E]

    # Static padded buffer: each expert's group rounds up to bm rows, so
    # the total is at most S + E*(bm-1), itself rounded to a bm multiple.
    padded = -(-counts // bm) * bm                           # [E]
    S_pad = max(bm, (S + E * (bm - 1)) // bm * bm)
    n_tiles = S_pad // bm
    pend = jnp.cumsum(padded)                                # [E]
    offs = pend - padded                                     # exclusive

    # Destination row of each assignment: its expert's group offset plus
    # its rank within the expert (ranks read off the stable sort).
    order = jnp.argsort(flat_e, stable=True)                 # [S]
    es = flat_e[order]
    rank = (jnp.arange(S, dtype=jnp.int32)
            - (jnp.cumsum(counts) - counts)[es])
    dest_sorted = offs[es] + rank                            # [S]
    token_of = jnp.repeat(jnp.arange(N, dtype=jnp.int32), k)
    x_pad = jnp.zeros((S_pad, H), x.dtype).at[dest_sorted].set(
        x2[token_of[order]])

    # tile→expert map (scalar prefetch): the expert whose padded span
    # covers the tile's first row.  Tiles past the last span clamp to
    # E-1 and chew zeros nobody gathers.
    tile_expert = jnp.clip(
        jnp.searchsorted(pend, jnp.arange(n_tiles, dtype=jnp.int32) * bm,
                         side="right"),
        0, E - 1).astype(jnp.int32)

    kw = {}
    if moe_params_quantized(p_moe):
        kw = {"w_gate_scale": p_moe["w_gate_scale"],
              "w_up_scale": p_moe["w_up_scale"],
              "w_down_scale": p_moe["w_down_scale"]}
    y_pad = grouped_expert_ffn(
        x_pad, tile_expert, p_moe["w_gate"], p_moe["w_up"],
        p_moe["w_down"], block_rows=bm, interpret=interpret, **kw)

    # Gather each assignment's output back and gate-combine.  The k
    # choices are re-sorted by EXPERT INDEX first: the dense oracle's
    # combine einsum reduces over the expert axis in index order (an FMA
    # chain where the zero-gated terms are exact no-ops), and matching
    # that accumulation order is what makes the two paths byte-identical
    # rather than 1-ulp apart.
    dest = jnp.zeros((S,), jnp.int32).at[order].set(dest_sorted)
    kord = jnp.argsort(expert_ids, axis=1, stable=True)      # [N, k]
    picked = jnp.take_along_axis(
        y_pad[dest].reshape(N, k, H), kord[:, :, None], axis=1)
    g_ord = jnp.take_along_axis(gates.reshape(N, k), kord, axis=1)
    out = jnp.einsum("nkh,nk->nh", picked, g_ord)
    return out.reshape(B, T, H).astype(x.dtype), _with_drop_tail(counts)


@hot_path
def _dispatch_one_shard(cfg: ModelConfig, p_moe: Params, x: jax.Array,
                        capacity: int, ep_axis: Optional[str],
                        tp_axis: Optional[str] = None
                        ) -> Tuple[jax.Array, jax.Array]:
    """Per-shard dispatch body.  x: [N, H] local tokens; expert weights
    local slices [E_local, ...].  Runs standalone (ep_axis None → E_local
    == E, no collective) or inside shard_map over `ep_axis`.  With
    `tp_axis`, each expert's MLP is additionally tp-sharded on the
    intermediate dim: the weight slices are [E_local, H, F/tp] /
    [E_local, F/tp, H], the down projection produces a partial sum, and
    ONE psum over tp completes it — tokens, routing and the all_to_all
    are tp-replicated, so the collective stays ep-only."""
    N, H = x.shape
    E = cfg.num_experts
    k = cfg.num_experts_per_token
    C = capacity
    ep = 1 if ep_axis is None else jax_compat.axis_size(ep_axis)
    E_local = p_moe["w_gate"].shape[0]

    # The router weight is replicated (every shard routes its own tokens
    # over ALL experts); only the expert weights are E-sharded.
    expert_ids, gates = router_topk(cfg, p_moe, x)

    # Position of each (token, choice) within its expert's buffer.
    flat_e = expert_ids.reshape(-1)                          # [N*k]
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)     # [N*k, E]
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1) - 1  # [N*k]
    keep = pos < C
    load = onehot.sum(0)                                     # [E] pre-drop
    dropped = jnp.sum(~keep).astype(jnp.int32)               # capacity honesty

    token_of = jnp.repeat(jnp.arange(N, dtype=jnp.int32), k)
    # Scatter kept tokens into per-destination-expert buffers.  Dropped
    # assignments scatter to an out-of-range row (mode="drop").
    send = jnp.zeros((E, C, H), x.dtype)
    rows = jnp.where(keep, flat_e, E)
    cols = jnp.where(keep, pos, 0)
    send = send.at[rows, cols].set(x[token_of], mode="drop")

    if ep_axis is not None and ep > 1:
        # [E, C, H] = [ep, E_local, C, H]: dim 0 indexes destination shard.
        send = send.reshape(ep, E_local * C, H)
        recv = jax.lax.all_to_all(send, ep_axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        # recv dim 0 now indexes SOURCE shard.
        h_in = recv.reshape(ep, E_local, C, H).transpose(1, 0, 2, 3)
        h_in = h_in.reshape(E_local, ep * C, H)
    else:
        h_in = send                                          # [E, C, H]

    h_out = expert_ffn(p_moe, h_in)                          # [E_l, ep*C, H]
    if tp_axis is not None:
        # F-sharded expert MLPs: each tp member computed a partial down
        # projection over its F/tp slice.
        h_out = jax.lax.psum(h_out, tp_axis)

    if ep_axis is not None and ep > 1:
        back = h_out.reshape(E_local, ep, C, H).transpose(1, 0, 2, 3)
        back = back.reshape(ep, E_local * C, H)
        got = jax.lax.all_to_all(back, ep_axis, split_axis=0,
                                 concat_axis=0, tiled=False)
        out_buf = got.reshape(E, C, H)
    else:
        out_buf = h_out                                      # [E, C, H]

    # Combine: out[t] = sum_j gate[t,j] * out_buf[e(t,j), pos(t,j)],
    # dropped assignments contribute zero.
    picked = out_buf[rows.clip(0, E - 1), cols]              # [N*k, H]
    picked = jnp.where(keep[:, None], picked, 0).reshape(N, k, H)
    out = jnp.einsum("nkh,nk->nh", picked.astype(jnp.float32),
                     gates.reshape(N, k).astype(jnp.float32))
    return out.astype(x.dtype), _with_drop_tail(load, dropped)


def moe_dispatch(cfg: ModelConfig, p_moe: Params, x: jax.Array,
                 capacity: Optional[int] = None,
                 ep_axis: Optional[str] = None,
                 load_psum_axes: Tuple[str, ...] = (),
                 tp_axis: Optional[str] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """All-to-all MoE.  x: [B, T, H] → (out [B, T, H], stats [E+1]).

    Call either outside any mesh (single shard, `ep_axis=None`) or inside
    `shard_map` with the token batch sharded over `ep_axis` (and possibly
    dp) and expert weights' E axis sharded over `ep_axis`.  `tp_axis`:
    the mesh axis each expert MLP's intermediate dim is sharded over
    (ep × tp meshes) — see _dispatch_one_shard.
    `load_psum_axes`: mesh axes to sum the per-shard stats over so the
    returned load/dropped counts are the global distribution
    (replicated).  NEVER include tp_axis here — routing is tp-replicated
    and summing over tp would multiply every count by tp."""
    B, T, H = x.shape
    N = B * T
    if capacity is None:
        capacity = N  # exact: no assignment can overflow
    out, stats = _dispatch_one_shard(
        cfg, p_moe, x.reshape(N, H), capacity, ep_axis, tp_axis)
    if load_psum_axes:
        stats = jax.lax.psum(stats, load_psum_axes)
    return out.reshape(B, T, H), stats
