"""Mixture-of-Experts compute paths: dense oracle + all-to-all dispatch.

The reference orchestrates wide-EP engines (SGLang wide-EP container,
`container/Dockerfile.sglang-wideep`; expert-distribution telemetry
`components/backends/sglang/src/dynamo/sglang/common/base_handlers.py:
40-62`) but owns no MoE math.  Here the engine is ours, so EP is a
first-class compute path (SURVEY §2.5 row "EP / MoE"):

- `moe_dense` — every device runs ALL tokens through its local experts and
  zero-gates the non-selected ones.  Always exact; the CPU-test oracle and
  the single-chip path.  Costs E/k× the minimal FLOPs (VERDICT r2 weak #4)
  — that waste is precisely what dispatch removes.
- `moe_dispatch` — Switch-Transformer-style token dispatch with a STATIC
  per-expert capacity (XLA needs fixed shapes): tokens are scattered into
  per-expert buffers, `jax.lax.all_to_all` moves buffers to the shard
  owning each expert over the `ep` mesh axis, local experts run one
  batched einsum, and a second all_to_all brings outputs home for the
  gate-weighted combine.
- Capacity semantics: `capacity` = tokens per expert per source shard.
  With `capacity >= tokens_per_shard` routing is EXACT (an expert can
  receive at most every local token once — top-k choices are distinct
  experts).  Smaller capacities drop overflow assignments (their gate
  mass is lost, Switch convention): the throughput/exactness knob is the
  deployment's, not the kernel's — serving defaults to exact.

Expert-load telemetry: both paths return per-expert assignment counts so
the worker can publish the expert-distribution the reference exposes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from dynamo_tpu.runtime import jax_compat

from dynamo_tpu.models.config import ModelConfig

Params = dict


def router_topk(cfg: ModelConfig, p_moe: Params, x: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
    """Top-k routing (Mixtral convention: softmax over the selected
    experts' logits).  x: [N, H] → (expert_ids [N, k], gates [N, k])."""
    logits = (x @ p_moe["router"]).astype(jnp.float32)       # [N, E]
    k = cfg.num_experts_per_token
    top_vals, top_idx = jax.lax.top_k(logits, k)             # [N, k]
    gates = jax.nn.softmax(top_vals, axis=-1)                # renormalised
    return top_idx, gates.astype(x.dtype)


def expert_ffn(p_moe: Params, h: jax.Array) -> jax.Array:
    """Batched expert MLPs: h [E, C, H] with weights [E, H, F]."""
    up = jax.nn.silu(jnp.einsum("ech,ehf->ecf", h, p_moe["w_gate"]))
    up = up * jnp.einsum("ech,ehf->ecf", h, p_moe["w_up"])
    return jnp.einsum("ecf,efh->ech", up, p_moe["w_down"])


def moe_dense(cfg: ModelConfig, p_moe: Params, x: jax.Array
              ) -> Tuple[jax.Array, jax.Array]:
    """Exact dense-compute MoE.  x: [B, T, H] → (out, expert_load [E])."""
    B, T, H = x.shape
    logits = (x @ p_moe["router"]).astype(jnp.float32)       # [B, T, E]
    k = cfg.num_experts_per_token
    top_vals, top_idx = jax.lax.top_k(logits, k)
    kth = top_vals[..., -1:]
    masked = jnp.where(logits >= kth, logits, -jnp.inf)
    gates = jax.nn.softmax(masked, axis=-1).astype(x.dtype)  # [B, T, E]

    hidden = jax.nn.silu(jnp.einsum("bth,ehf->betf", x, p_moe["w_gate"]))
    hidden = hidden * jnp.einsum("bth,ehf->betf", x, p_moe["w_up"])
    expert_out = jnp.einsum("betf,efh->beth", hidden, p_moe["w_down"])
    out = jnp.einsum("beth,bte->bth", expert_out, gates)
    load = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.num_experts, dtype=jnp.int32),
        axis=(0, 1, 2))
    return out, load


def _dispatch_one_shard(cfg: ModelConfig, p_moe: Params, x: jax.Array,
                        capacity: int, ep_axis: Optional[str]
                        ) -> Tuple[jax.Array, jax.Array]:
    """Per-shard dispatch body.  x: [N, H] local tokens; expert weights
    local slices [E_local, ...].  Runs standalone (ep_axis None → E_local
    == E, no collective) or inside shard_map over `ep_axis`."""
    N, H = x.shape
    E = cfg.num_experts
    k = cfg.num_experts_per_token
    C = capacity
    ep = 1 if ep_axis is None else jax_compat.axis_size(ep_axis)
    E_local = p_moe["w_gate"].shape[0]

    # The router weight is replicated (every shard routes its own tokens
    # over ALL experts); only the expert weights are E-sharded.
    expert_ids, gates = router_topk(cfg, p_moe, x)

    # Position of each (token, choice) within its expert's buffer.
    flat_e = expert_ids.reshape(-1)                          # [N*k]
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)     # [N*k, E]
    pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(-1) - 1  # [N*k]
    keep = pos < C
    load = onehot.sum(0)                                     # [E] pre-drop

    token_of = jnp.repeat(jnp.arange(N, dtype=jnp.int32), k)
    # Scatter kept tokens into per-destination-expert buffers.  Dropped
    # assignments scatter to an out-of-range row (mode="drop").
    send = jnp.zeros((E, C, H), x.dtype)
    rows = jnp.where(keep, flat_e, E)
    cols = jnp.where(keep, pos, 0)
    send = send.at[rows, cols].set(x[token_of], mode="drop")

    if ep_axis is not None and ep > 1:
        # [E, C, H] = [ep, E_local, C, H]: dim 0 indexes destination shard.
        send = send.reshape(ep, E_local * C, H)
        recv = jax.lax.all_to_all(send, ep_axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        # recv dim 0 now indexes SOURCE shard.
        h_in = recv.reshape(ep, E_local, C, H).transpose(1, 0, 2, 3)
        h_in = h_in.reshape(E_local, ep * C, H)
    else:
        h_in = send                                          # [E, C, H]

    h_out = expert_ffn(p_moe, h_in)                          # [E_l, ep*C, H]

    if ep_axis is not None and ep > 1:
        back = h_out.reshape(E_local, ep, C, H).transpose(1, 0, 2, 3)
        back = back.reshape(ep, E_local * C, H)
        got = jax.lax.all_to_all(back, ep_axis, split_axis=0,
                                 concat_axis=0, tiled=False)
        out_buf = got.reshape(E, C, H)
    else:
        out_buf = h_out                                      # [E, C, H]

    # Combine: out[t] = sum_j gate[t,j] * out_buf[e(t,j), pos(t,j)],
    # dropped assignments contribute zero.
    picked = out_buf[rows.clip(0, E - 1), cols]              # [N*k, H]
    picked = jnp.where(keep[:, None], picked, 0).reshape(N, k, H)
    out = jnp.einsum("nkh,nk->nh", picked.astype(jnp.float32),
                     gates.reshape(N, k).astype(jnp.float32))
    return out.astype(x.dtype), load


def moe_dispatch(cfg: ModelConfig, p_moe: Params, x: jax.Array,
                 capacity: Optional[int] = None,
                 ep_axis: Optional[str] = None,
                 load_psum_axes: Tuple[str, ...] = ()
                 ) -> Tuple[jax.Array, jax.Array]:
    """All-to-all MoE.  x: [B, T, H] → (out [B, T, H], expert_load [E]).

    Call either outside any mesh (single shard, `ep_axis=None`) or inside
    `shard_map` with the token batch sharded over `ep_axis` (and possibly
    dp) and expert weights' E axis sharded over `ep_axis`.
    `load_psum_axes`: mesh axes to sum the per-shard expert counts over so
    the returned load is the global distribution (replicated)."""
    B, T, H = x.shape
    N = B * T
    if capacity is None:
        capacity = N  # exact: no assignment can overflow
    out, load = _dispatch_one_shard(
        cfg, p_moe, x.reshape(N, H), capacity, ep_axis)
    if load_psum_axes:
        load = jax.lax.psum(load, load_psum_axes)
    return out.reshape(B, T, H), load
