"""Pallas TPU kernels for the serving hot path.

The reference's one mandated CUDA kernel is the KV block scatter/gather
(`lib/llm/src/kernels/block_copy.cu:41`); on TPU the block copies compile
to XLA dynamic slices (engine/kv_cache.py:make_block_ops) and the kernel
budget goes where it pays: paged-attention decode, which would otherwise
materialise a full gathered context per step.
"""

from dynamo_tpu.ops.pallas.moe_grouped import (
    dequantize_moe_params,
    grouped_expert_ffn,
    moe_grouped_geometry_ok,
    moe_params_quantized,
    quantize_moe_params,
)
from dynamo_tpu.ops.pallas.paged_attention import (
    mosaic_geometry_ok,
    paged_decode_attention,
)
from dynamo_tpu.ops.pallas.paged_prefill import (
    PACK_ALIGN,
    paged_prefill_attention,
)
from dynamo_tpu.ops.pallas.ring_attention import (
    ring_flash_attention,
    ring_geometry_ok,
    ring_kernel_supported,
)

__all__ = ["paged_decode_attention", "paged_prefill_attention",
           "mosaic_geometry_ok", "PACK_ALIGN",
           "grouped_expert_ffn", "moe_grouped_geometry_ok",
           "quantize_moe_params", "dequantize_moe_params",
           "moe_params_quantized", "ring_flash_attention",
           "ring_geometry_ok", "ring_kernel_supported"]
