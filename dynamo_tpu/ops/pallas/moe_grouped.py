"""Grouped MoE expert-FFN Pallas kernel (the MoE fast-decode compute).

`moe_dense` (ops/moe.py) runs EVERY expert over EVERY token and
zero-gates the non-selected ones — E/k× the minimal FLOPs and, worse for
decode, E/k× the minimal HBM weight traffic (decode MoE is weight-
bandwidth-bound exactly like decode attention is KV-bandwidth-bound).
This kernel computes only the selected (token, expert) assignments:

- the caller sorts assignments by expert on device and pads each expert's
  group to a `block_rows` multiple (ops/moe.py moe_grouped — sort /
  scatter / combine live there; this module is just the ragged GEMM);
- the grid walks row tiles; a scalar-prefetch `tile_expert` map drives
  the weight BlockSpec index_maps, so consecutive tiles of the same
  expert REUSE the VMEM-resident weight block (Pallas skips the DMA when
  the block index repeats) — in the decode regime (≤ block_rows
  assignments per expert) each active expert's weights stream HBM→VMEM
  exactly once, and experts with no assigned tokens are never read;
- the intermediate dim F is blocked (`block_f`) with an f32 VMEM
  accumulator so serving-size experts (H×F ≫ VMEM) still fit: per grid
  step the kernel holds one [H, bf] gate/up slice, one [bf, H] down
  slice, and the [bm, H] accumulator.

int8-weight variant (mirrors the PR 6 KV-cache discipline): expert
weights quantize per-expert-per-output-column (`quantize_moe_params`),
the int8 blocks and their f32 scale slivers DMA together, and
dequantization happens on the VMEM-resident block — HBM weight traffic
halves vs bf16.  Dequant reproduces `dequantize_moe_params` numerics
element-for-element, so the grouped int8 output is byte-identical to
`moe_dense` run on the host-dequantized weights.

Numerics contract: each matmul accumulates in f32 and casts back to the
activation dtype (`preferred_element_type` then `.astype`), mirroring
what XLA's einsum does inside `moe_dense` — with a single F block (the
tiny CPU test geometry) the grouped output is byte-identical to the
dense oracle's per-expert outputs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row-tile default: big enough that one MXU pass amortises the weight
# DMA, small enough that a decode batch (N*k assignments over E experts)
# doesn't drown in per-expert padding.
DEFAULT_BLOCK_ROWS = 64
# VMEM budget for the weight working set (gate + up [H, bf] + down
# [bf, H], double-buffered by the pipeline) — leave most of ~16 MB for
# the accumulator and the compiler's own staging.
_WEIGHT_BUDGET = 8 * 1024 * 1024
_TARGET_BLOCK_F = 2048


def moe_grouped_geometry_ok(hidden: int, intermediate: int,
                            itemsize: int = 2,
                            block_rows: int = DEFAULT_BLOCK_ROWS) -> bool:
    """THE Mosaic eligibility rule for the grouped kernel, shared by
    every auto-selection site (engine moe_mode auto, profile_decode
    --moe, bench/moe_decode) — same discipline as
    `mosaic_geometry_ok` for the attention kernels.  Lane dims (H for
    the row tiles and the down-projection, F for gate/up) must be
    128-aligned and the row tile 8-aligned; the smallest F block must
    fit the weight budget."""
    return (hidden % 128 == 0 and intermediate % 128 == 0
            and block_rows % 8 == 0
            and 2 * 3 * hidden * min(intermediate, 128) * itemsize
            <= _WEIGHT_BUDGET)


def auto_block_f(hidden: int, intermediate: int, itemsize: int = 2) -> int:
    """F-block sizing: grow toward `_TARGET_BLOCK_F` (fewer accumulator
    passes), halve while the double-buffered gate+up+down working set
    would exceed the weight budget, floor at the 128 lane quantum."""
    bf = min(intermediate, _TARGET_BLOCK_F)
    while bf > 128 and 2 * 3 * hidden * bf * itemsize > _WEIGHT_BUDGET:
        bf //= 2
    return bf


def _ffn_kernel(n_blocks_f: int, quant: bool,
                # scalar prefetch
                te_ref,
                # inputs
                x_ref, wg_ref, wu_ref, wd_ref, *rest):
    if quant:
        sg_ref, su_ref, sd_ref, o_ref, acc = rest
    else:
        o_ref, acc = rest
        sg_ref = su_ref = sd_ref = None
    f = pl.program_id(1)
    x = x_ref[...]                                   # [bm, H]

    def load_w(ref, s_ref):
        w = ref[0]                                   # [H, bf] / [bf, H]
        if not quant:
            return w
        # Dequant on the VMEM-resident block, reproducing
        # dequantize_moe_params element-for-element: f32 multiply by the
        # per-output-column scale, then cast to the activation dtype.
        return (w.astype(jnp.float32) * s_ref[...]).astype(x.dtype)

    wg = load_w(wg_ref, sg_ref)                      # [H, bf]
    wu = load_w(wu_ref, su_ref)                      # [H, bf]
    wd = load_w(wd_ref, sd_ref)                      # [bf, H]
    # f32 MXU accumulation then cast back to the activation dtype —
    # exactly what XLA does inside moe_dense's einsums, which is what
    # makes the grouped output byte-comparable to the oracle.
    h = jnp.dot(x, wg, preferred_element_type=jnp.float32).astype(x.dtype)
    u = jnp.dot(x, wu, preferred_element_type=jnp.float32).astype(x.dtype)
    act = jax.nn.silu(h) * u                         # [bm, bf]
    # Pin the activation's cast-to-x-dtype rounding: fused end-to-end,
    # XLA would elide the bf16 round-trip into the next matmul's f32
    # upcast, putting the kernel 1 ulp off the oracle (whose einsums
    # materialise each intermediate).
    act = jax.lax.optimization_barrier(act)
    part = jax.lax.dot_general(
        act, wd, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [bm, H] f32

    @pl.when(f == 0)
    def _():
        acc[...] = part

    @pl.when(f > 0)
    def _():
        acc[...] += part

    @pl.when(f == n_blocks_f - 1)
    def _():
        o_ref[...] = acc[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_rows", "block_f", "interpret"))
def grouped_expert_ffn(
    x_pad: jax.Array,        # [S_pad, H] expert-sorted, group-padded rows
    tile_expert: jax.Array,  # [S_pad // block_rows] int32 tile→expert map
    w_gate: jax.Array,       # [E, H, F] (bf16/f32, or int8 with scales)
    w_up: jax.Array,         # [E, H, F]
    w_down: jax.Array,       # [E, F, H]
    *,
    w_gate_scale: Optional[jax.Array] = None,  # [E, F] f32 (int8 weights)
    w_up_scale: Optional[jax.Array] = None,    # [E, F] f32
    w_down_scale: Optional[jax.Array] = None,  # [E, H] f32
    block_rows: int = DEFAULT_BLOCK_ROWS,
    block_f: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Ragged grouped expert FFN: row tile t runs expert
    `tile_expert[t]`'s SwiGLU MLP.  Returns [S_pad, H] in x's dtype.
    Padding rows are all-zero by construction (ops/moe.py) and compute
    harmless zeros that the caller never gathers."""
    S_pad, H = x_pad.shape
    E, _, F = w_gate.shape
    quant = w_gate_scale is not None
    if quant != (w_up_scale is not None) or quant != (
            w_down_scale is not None):
        raise ValueError("pass all three weight scales or none")
    if quant and w_gate.dtype != jnp.int8:
        raise ValueError(f"scales imply int8 weights; got {w_gate.dtype}")
    if S_pad % block_rows:
        raise ValueError(
            f"S_pad={S_pad} must be a block_rows={block_rows} multiple")
    itemsize = jnp.dtype(w_gate.dtype).itemsize
    if not interpret and not moe_grouped_geometry_ok(
            H, F, itemsize, block_rows):
        raise ValueError(
            f"grouped MoE kernel needs H % 128 == 0, F % 128 == 0 and "
            f"block_rows % 8 == 0; got H={H}, F={F}, "
            f"block_rows={block_rows} (use moe_mode='dense' for this "
            "geometry)")
    if block_f is None:
        block_f = min(F, auto_block_f(H, F, itemsize)) if not interpret \
            else F
    if F % block_f:
        raise ValueError(f"F={F} must divide by block_f={block_f}")
    nf = F // block_f
    T = S_pad // block_rows

    # Index maps see the scalar-prefetch tile_expert array: consecutive
    # tiles of one expert map to the SAME weight block, so the pipeline
    # skips the refetch — the "stream each expert's weights exactly
    # once" property in the decode regime.
    in_specs = [
        pl.BlockSpec((block_rows, H), lambda t, f, te: (t, 0)),
        pl.BlockSpec((1, H, block_f), lambda t, f, te: (te[t], 0, f)),
        pl.BlockSpec((1, H, block_f), lambda t, f, te: (te[t], 0, f)),
        pl.BlockSpec((1, block_f, H), lambda t, f, te: (te[t], f, 0)),
    ]
    inputs = [tile_expert, x_pad, w_gate, w_up, w_down]
    if quant:
        in_specs += [
            pl.BlockSpec((1, block_f), lambda t, f, te: (te[t], f)),
            pl.BlockSpec((1, block_f), lambda t, f, te: (te[t], f)),
            pl.BlockSpec((1, H), lambda t, f, te: (te[t], 0)),
        ]
        inputs += [w_gate_scale, w_up_scale, w_down_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T, nf),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_rows, H), lambda t, f, te: (t, 0)),
        scratch_shapes=[pltpu.VMEM((block_rows, H), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_ffn_kernel, nf, quant),
        out_shape=jax.ShapeDtypeStruct((S_pad, H), x_pad.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)


# -- int8 expert weights (static params-pytree branch, like kv_quant) ----

def moe_params_quantized(p_moe: dict) -> bool:
    """Static branch predicate: quantized expert params carry sibling
    `*_scale` entries (the same pytree-shape discipline the int8 KV
    cache uses — the compiled program branches on structure, never on
    values)."""
    return "w_gate_scale" in p_moe


def quantize_moe_params(p_moe: dict) -> dict:
    """int8-quantize the expert weights per-expert-per-output-column
    (absmax over the contraction dim), keeping the router full-precision
    — routing decides token placement and is tiny.  Returns a new pytree
    with int8 `w_gate`/`w_up`/`w_down` plus f32 `*_scale` siblings."""
    out = {"router": p_moe["router"]}
    for name in ("w_gate", "w_up", "w_down"):
        w = p_moe[name].astype(jnp.float32)          # [E, in, out]
        scale = jnp.maximum(jnp.max(jnp.abs(w), axis=1) / 127.0, 1e-8)
        out[name] = jnp.round(w / scale[:, None, :]).astype(jnp.int8)
        out[name + "_scale"] = scale                 # [E, out]
    return out


def dequantize_moe_params(p_moe: dict, dtype) -> dict:
    """Host-side inverse (the oracle path): reproduces the kernel's
    in-VMEM dequant element-for-element, so `moe_dense` on the result is
    the byte-exact reference for the grouped int8 output."""
    out = {"router": p_moe["router"]}
    for name in ("w_gate", "w_up", "w_down"):
        out[name] = (p_moe[name].astype(jnp.float32)
                     * p_moe[name + "_scale"][:, None, :]).astype(dtype)
    return out
