"""Pallas TPU paged-attention decode kernel.

The XLA fallback (ops/attention.py) materialises every sequence's context
K/V — [B, width*block_size, Hkv, D] in f32 — per layer per decode step.
Context-length bucketing bounds that width, but the gather still reads and
converts the full bucket for every sequence regardless of its own length.
This kernel streams exactly `ceil(seq_len/block_size)` KV pages per
sequence from HBM through VMEM with an online-softmax (flash-attention)
accumulator, so decode attention cost is per-sequence-length, and no
gathered context array ever exists in HBM.

Role in the reference: the engines it delegates to (vLLM) run paged
attention CUDA kernels; the one kernel the reference itself ships is the
block-copy scatter/gather (`lib/llm/src/kernels/block_copy.cu:41`).  This
is the TPU-native equivalent of that layer of the stack.

Layout strategy: Mosaic DMA wants 128-aligned trailing dims, and head_dim
is 64 on small Llamas — so the kernel sees the cache as 2D
`[S, F = Hkv * head_dim]` (a free reshape of the engine's [S, Hkv, D]
layout) and GQA head selection is algebraic instead of indexed:

- queries are pre-scattered (in XLA, outside the kernel) into zero-padded
  rows `qp[B, Hq, F]` where row h occupies only its KV head's column band,
  so `qp @ k_page.T` contracts to exactly the right per-head scores;
- `probs @ v_page` produces [Hq, F] whose band h is the right output;
  the band extraction is again XLA outside the kernel.

The padded matmuls do Hkv x the minimal attention FLOPs, but decode
attention is HBM-bandwidth-bound, and bytes moved is what the kernel
minimises; the MXU eats the extra zeros for free at these sizes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(block_size: int, soft_cap: Optional[float],
                   # refs
                   bt_ref, len_ref,          # scalar-prefetch (SMEM)
                   qp_ref, k_hbm, v_hbm,     # inputs (2D cache views)
                   o_ref,                    # output [1, Hq, F]
                   k_vmem, v_vmem, sem):     # scratch
    b = pl.program_id(0)
    seq_len = len_ref[b]
    n_pages = pl.cdiv(seq_len, block_size)

    Hq, F = qp_ref.shape[1], qp_ref.shape[2]
    qp = qp_ref[0].astype(jnp.float32)                # [Hq, F] (pre-scaled)

    m0 = jnp.full((Hq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((Hq, 1), jnp.float32)
    a0 = jnp.zeros((Hq, F), jnp.float32)

    # Double-buffered page pipeline: fetch page p+1 while computing on p.
    def get_k(slot, p):
        return pltpu.make_async_copy(
            k_hbm.at[pl.ds(bt_ref[b, p] * block_size, block_size)],
            k_vmem.at[slot], sem.at[slot, 0])

    def get_v(slot, p):
        return pltpu.make_async_copy(
            v_hbm.at[pl.ds(bt_ref[b, p] * block_size, block_size)],
            v_vmem.at[slot], sem.at[slot, 1])

    @pl.when(n_pages > 0)
    def _():
        get_k(0, 0).start()
        get_v(0, 0).start()

    def body(p, carry):
        m, l, acc = carry
        slot = jax.lax.rem(p, 2)
        nxt = jax.lax.rem(p + 1, 2)

        @pl.when(p + 1 < n_pages)
        def _():
            get_k(nxt, p + 1).start()
            get_v(nxt, p + 1).start()

        get_k(slot, p).wait()
        get_v(slot, p).wait()

        k = k_vmem[slot].astype(jnp.float32)          # [bs, F]
        v = v_vmem[slot].astype(jnp.float32)
        # Zero bands in qp make this the per-KV-head score despite the
        # full-F contraction: [Hq, F] x [bs, F] -> [Hq, bs].
        s = jax.lax.dot_general(
            qp, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        pos = p * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        s = jnp.where(pos < seq_len, s, -jnp.inf)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        probs = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(probs, axis=-1, keepdims=True)
        # [Hq, bs] x [bs, F] -> [Hq, F]; band h carries head h's output.
        pv = jax.lax.dot_general(
            probs, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv

    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, a0))
    # Padding rows (seq_len 0) skip the loop: l stays 0; guard the divide —
    # their output rows are discarded by the engine anyway.
    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "scale", "soft_cap", "interpret"))
def paged_decode_attention(
    q: jax.Array,             # [B, Hq, D] current (single) decode queries
    k_cache: jax.Array,       # [S, Hkv, D] one layer's flat-slot keys
    v_cache: jax.Array,       # [S, Hkv, D]
    block_tables: jax.Array,  # [B, P] int32 page ids
    seq_lens: jax.Array,      # [B] int32 valid context length
    *,
    block_size: int,
    scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """Decode-step attention over the paged cache; returns [B, Hq, D].

    Numerics match ops/attention.py's masked gather path for T=1 (the
    decode query at position seq_len-1 sees exactly slots pos < seq_len).
    """
    B, Hq, D = q.shape
    S, Hkv, _ = k_cache.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} not a multiple of Hkv={Hkv}")
    G = Hq // Hkv
    F = Hkv * D
    if scale is None:
        scale = D ** -0.5

    # Scatter each query row into its KV head's column band (XLA side).
    head_of_q = jnp.arange(Hq, dtype=jnp.int32) // G           # [Hq]
    sel = jax.nn.one_hot(head_of_q, Hkv, dtype=jnp.float32)    # [Hq, Hkv]
    qp = jnp.einsum(
        "bhd,hk->bhkd", q.astype(jnp.float32) * scale, sel
    ).reshape(B, Hq, F)

    kernel = functools.partial(_decode_kernel, block_size, soft_cap)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, F), lambda b, bt, sl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # V stays in HBM
        ],
        out_specs=pl.BlockSpec((1, Hq, F), lambda b, bt, sl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, block_size, F), k_cache.dtype),
            pltpu.VMEM((2, block_size, F), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    out_full = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, Hq, F), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(block_tables, seq_lens, qp, k_cache.reshape(S, F),
      v_cache.reshape(S, F))

    # Extract each head's band: [B, Hq, Hkv, D] -> [B, Hq, D].
    out = out_full.reshape(B, Hq, Hkv, D)
    return jnp.take_along_axis(
        out, head_of_q[None, :, None, None], axis=2)[:, :, 0]
