"""Pallas TPU paged-attention decode kernel.

The XLA fallback (ops/attention.py) materialises every sequence's context
K/V — [B, width*block_size, Hkv, D] in f32 — per layer per decode step.
Context-length bucketing bounds that width, but the gather still reads and
converts the full bucket for every sequence regardless of its own length.
This kernel streams exactly `ceil(seq_len/block_size)` KV pages per
sequence from HBM through VMEM with an online-softmax (flash-attention)
accumulator, so decode attention cost is per-sequence-length, and no
gathered context array ever exists in HBM.

Role in the reference: the engines it delegates to (vLLM) run paged
attention CUDA kernels; the one kernel the reference itself ships is the
block-copy scatter/gather (`lib/llm/src/kernels/block_copy.cu:41`).  This
is the TPU-native equivalent of that layer of the stack.

Layout strategy: Mosaic DMA wants 128-aligned trailing dims, and head_dim
is 64 on small Llamas — so the kernel sees the cache as 2D
`[S, F = Hkv * head_dim]` (the engine's native storage layout — see
kv_cache.init_cache) and GQA head selection is algebraic instead of
indexed: each query row h is masked into its KV head's column band, so
`qp @ k_tile.T` contracts to exactly the right per-head scores, and the
band of `probs @ v_tile` is head h's output.  Banding and band-extraction
happen INSIDE the kernel on VMEM-resident tiles (v3; earlier revisions
did them in XLA, costing an extra [B, Hq, F] materialisation per layer
per step).

The padded matmuls do Hkv x the minimal attention FLOPs, but decode
attention is HBM-bandwidth-bound, and bytes moved is what the kernel
minimises; the MXU eats the extra zeros nearly for free at these sizes.

Perf structure (v4):
- bf16 x bf16 MXU passes with f32 accumulation (f32 operands cost ~4x
  the passes for accuracy the f32 accumulator already provides);
- `pair` pages per tile, AUTO-SIZED per (feature width, block_size): one
  MXU pass over a 256-token tile costs barely more than over a 64-token
  page (the F-contraction dominates), and fewer, larger DMA bursts sit
  closer to the HBM streaming rate than many page-sized ones — so the
  tile grows toward `_TARGET_TILE` tokens until the 3-slot double-buffer
  scratch would crowd VMEM (`_SCRATCH_BUDGET`), then halves.  r5 ran a
  fixed pair=2 (128-token tiles): at serving geometry (block 64,
  ctx 512) that is 4 loop iterations per sequence where 2 suffice, and
  per-iteration fixed costs (semaphore waits, control flow) were a
  visible slice of the 0.70-MBU gap;
- double-buffered tile DMA pipeline within a sequence, PLUS cross-program
  prefetch: a sequence's last-tile compute overlaps the first-tile fetch
  of the NEXT sequence (slot 2), so the 64 grid-program boundaries don't
  each drain the pipeline.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Auto `pair` sizing targets: tiles of ~256 tokens keep the MXU's
# F-contraction efficiency while cutting per-tile fixed costs, bounded so
# the K+V scratch (2 buffers x 3 slots x tile x F) leaves most of the
# ~16 MB VMEM for the compiler's own staging.  int8 tiles halve the
# scratch bytes per token, so the quantized kernel targets 2x the tile —
# same VMEM budget, half the per-tile fixed costs per byte moved.
_TARGET_TILE = 256
_TARGET_TILE_INT8 = 512
_SCRATCH_BUDGET = 4 * 1024 * 1024


def mosaic_geometry_ok(feat: int, block_size: int) -> bool:
    """THE Mosaic DMA-tiling eligibility rule for this kernel: the cache
    view's lane (feature) dim must be 128-aligned and the sublane
    (block) dim 8-aligned, or compilation dies deep in the DMA lowering.
    One predicate shared by every auto-selection site (engine auto rule,
    profile_decode, bench/sharded_decode) so the served engine, the
    profiler and the gated bench can never silently diverge on which
    attention path a geometry runs.  `feat` is the PER-SHARD feature
    width (F/tp under head-sharded tensor parallelism, full F under
    dp_attention's slot sharding)."""
    return feat % 128 == 0 and block_size % 8 == 0


def auto_pair(block_size: int, feat: int, itemsize: int = 2,
              target: Optional[int] = None) -> int:
    """Pages per DMA tile for a (block_size, feature-width) geometry:
    grow toward the target tile tokens (`_TARGET_TILE`, doubled for int8
    caches whose bytes/token halve), halve while the two 3-slot
    double-buffer scratch arrays would exceed `_SCRATCH_BUDGET`."""
    if target is None:
        target = _TARGET_TILE_INT8 if itemsize == 1 else _TARGET_TILE
    pair = max(1, target // block_size)
    while pair > 1 and (2 * 3 * pair * block_size * feat * itemsize
                        > _SCRATCH_BUDGET):
        pair //= 2
    return pair


def _decode_kernel(block_size: int, pair: int, n_kv: int,
                   soft_cap: Optional[float], quant: bool,
                   # refs
                   bt_ref, len_ref,          # scalar-prefetch (SMEM)
                   q_ref, k_hbm, v_hbm,      # q [1, Hq, D]; 2D cache views
                   *rest):
    if quant:
        # int8 cache: per-token-per-head f32 scales ride their own HBM
        # arrays [S, Hkv] and DMA alongside the int8 pages; dequant
        # happens here on the VMEM-resident tile, AFTER the fetch — HBM
        # moves ~half the bytes, VMEM holds int8 + a tiny scale tile.
        (ks_hbm, vs_hbm, o_ref, k_vmem, v_vmem,
         ks_vmem, vs_vmem, sem) = rest
    else:
        o_ref, k_vmem, v_vmem, sem = rest
        ks_hbm = vs_hbm = ks_vmem = vs_vmem = None
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    seq_len = len_ref[b]
    n_pages = pl.cdiv(seq_len, block_size)
    n_iters = pl.cdiv(seq_len, block_size * pair)

    Hq, D = q_ref.shape[1], q_ref.shape[2]
    F = n_kv * D
    G = Hq // n_kv
    W = block_size * pair

    # Band mask [Hq, F]: query row h owns columns [D*(h//G), D*(h//G+1)).
    row_head = jax.lax.broadcasted_iota(jnp.int32, (Hq, F), 0) // G
    col_head = jax.lax.broadcasted_iota(jnp.int32, (Hq, F), 1) // D
    band = row_head == col_head
    # qp [Hq, F]: q tiled across kv-head bands (lane concat — Mosaic has
    # no 3D broadcast reshape), off-band zeroed (bf16).
    q = q_ref[0]                                        # [Hq, D] pre-scaled
    qp = jnp.where(band, jnp.concatenate([q] * n_kv, axis=1),
                   jnp.zeros((Hq, F), q.dtype))

    def dequant(tile_i8, scale_tile):
        # [W, F] int8 x [W, Hkv] f32 -> [W, F] in q's dtype: each column
        # band h multiplies by its head's per-token scale (static concat
        # of per-head broadcasts — Mosaic has no 3D reshape-broadcast).
        mult = jnp.concatenate(
            [jnp.broadcast_to(scale_tile[:, h:h + 1], (W, D))
             for h in range(n_kv)], axis=1)
        return (tile_i8.astype(jnp.float32) * mult).astype(qp.dtype)

    m0 = jnp.full((Hq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((Hq, 1), jnp.float32)
    a0 = jnp.zeros((Hq, F), jnp.float32)

    def fetch(buf, hbm, slot, seq, t, j, kv):
        # page index p = t*pair + j for sequence row `seq`; clamp to that
        # row's last real page so a tail tile's extra DMA is a harmless
        # re-fetch (its positions are masked in compute).
        last = jnp.maximum(pl.cdiv(len_ref[seq], block_size) - 1, 0)
        p = jnp.minimum(t * pair + j, last)
        return pltpu.make_async_copy(
            hbm.at[pl.ds(bt_ref[seq, p] * block_size, block_size)],
            buf.at[slot, pl.ds(j * block_size, block_size)],
            sem.at[slot, j, kv])

    # (buffer, hbm array, semaphore lane) per DMA stream: K, V, then the
    # two tiny scale streams in quant mode (their tiles are [W, Hkv] f32 —
    # ~3% of the K+V bytes at serving geometry).
    streams = [(k_vmem, k_hbm, 0), (v_vmem, v_hbm, 1)]
    if quant:
        streams += [(ks_vmem, ks_hbm, 2), (vs_vmem, vs_hbm, 3)]

    def start_tile(slot, seq, t):
        for j in range(pair):
            for buf, hbm, lane in streams:
                fetch(buf, hbm, slot, seq, t, j, lane).start()

    def wait_tile(slot, seq, t):
        for j in range(pair):
            for buf, hbm, lane in streams:
                fetch(buf, hbm, slot, seq, t, j, lane).wait()

    # Tile 0 lives in slot 2: the PREVIOUS program prefetched it during its
    # last tile's compute (see below) iff it had 2+ tiles itself (a
    # single-tile program is still READING slot 2 at its last tile — a
    # prefetch there would overwrite live data); otherwise fetch it now.
    # Slots 0/1 double-buffer tiles 1..n-1.
    prev_iters = pl.cdiv(len_ref[jnp.maximum(b - 1, 0)], block_size * pair)
    prefetched = jnp.logical_and(b > 0, prev_iters > 1)

    @pl.when(jnp.logical_and(n_iters > 0, jnp.logical_not(prefetched)))
    def _():
        start_tile(2, b, 0)

    def slot_of(t):
        return jnp.where(t == 0, 2, jax.lax.rem(t, 2))

    def body(t, carry):
        m, l, acc = carry
        slot = slot_of(t)

        @pl.when(t + 1 < n_iters)
        def _():
            start_tile(jax.lax.rem(t + 1, 2), b, t + 1)

        # Last tile (and not tile 0 — slot 2 is still live there): overlap
        # the NEXT program's tile-0 fetch (slot 2) with this tile's
        # compute — kills the per-program pipeline drain.  The issue
        # condition must mirror `prefetched` above exactly: issued iff
        # this program has 2+ tiles and the next program has pages.
        @pl.when(jnp.logical_and(
            jnp.logical_and(t + 1 >= n_iters, t >= 1),
            jnp.logical_and(b + 1 < nb,
                            len_ref[jnp.minimum(b + 1, nb - 1)] > 0)))
        def _():
            start_tile(2, jnp.minimum(b + 1, nb - 1), 0)

        wait_tile(slot, b, t)

        if quant:
            k = dequant(k_vmem[slot], ks_vmem[slot])  # [W, F] deq in-VMEM
            v = dequant(v_vmem[slot], vs_vmem[slot])
        else:
            k = k_vmem[slot]                          # [W, F] bf16
            v = v_vmem[slot]
        # Zero bands in qp make this the per-KV-head score despite the
        # full-F contraction: [Hq, F] x [W, F] -> [Hq, W].
        s = jax.lax.dot_general(
            qp, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        pos = t * W + jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
        s = jnp.where(pos < seq_len, s, -jnp.inf)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        probs = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(probs, axis=-1, keepdims=True)
        # [Hq, W] x [W, F] -> [Hq, F]; band h carries head h's output.
        pv = jax.lax.dot_general(
            probs.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv

    m, l, acc = jax.lax.fori_loop(0, n_iters, body, (m0, l0, a0))
    # Padding rows (seq_len 0) skip the loop: l stays 0; guard the divide —
    # their output rows are discarded by the engine anyway.
    out = acc / jnp.maximum(l, 1e-30)
    # Band extraction on VMEM: head h's output is its own band of `out`;
    # zero the off-bands and fold the D-wide column groups (static slices
    # — Mosaic has no 3D reshape-reduce).
    outm = jnp.where(band, out, 0.0)
    out_d = outm[:, 0:D]
    for kk in range(1, n_kv):
        out_d = out_d + outm[:, kk * D:(kk + 1) * D]
    o_ref[0] = out_d.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "scale", "soft_cap", "interpret", "pair"))
def paged_decode_attention(
    q: jax.Array,             # [B, Hq, D] current (single) decode queries
    k_cache: jax.Array,       # [S, F = Hkv * D] one layer's flat-slot keys
    v_cache: jax.Array,       # [S, F]
    block_tables: jax.Array,  # [B, P] int32 page ids
    seq_lens: jax.Array,      # [B] int32 valid context length
    *,
    block_size: int,
    scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    interpret: bool = False,
    pair: Optional[int] = None,
    k_scale: Optional[jax.Array] = None,  # [S, Hkv] f32 (int8 cache)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Decode-step attention over the paged cache; returns [B, Hq, D].

    The cache is the engine's native 2D layout [S, F] with F flat
    head-major (kv_cache.init_cache) — exactly the view the kernel's DMA
    wants, no relayout at the boundary.  Numerics match ops/attention.py's
    masked gather path for T=1 (the decode query at position seq_len-1
    sees exactly slots pos < seq_len): bf16 MXU passes with f32
    accumulation on both paths.

    Quantized variant: pass an int8 cache with `k_scale`/`v_scale`
    ([S, Hkv] f32, kv_cache.init_cache's `k_scale`/`v_scale` buffers).
    Pages AND scales stream HBM→VMEM; dequantization happens on the
    VMEM-resident tile (kv_cache.dequantize_rows numerics), so the HBM
    read per context token drops from 2*F*2 to 2*(F + 4*Hkv) bytes and
    the auto tile target doubles (auto_pair int8 path).
    """
    B, Hq, D = q.shape
    S, Fc = k_cache.shape
    Hkv = Fc // D
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if quant and k_cache.dtype != jnp.int8:
        raise ValueError(
            f"scales imply an int8 cache; got {k_cache.dtype}")
    if Fc % D or Hq % Hkv:
        raise ValueError(f"bad geometry: q {q.shape}, cache {k_cache.shape}")
    if not interpret and not mosaic_geometry_ok(Fc, block_size):
        # Mosaic DMA tiling: the cache's lane dim must be 128-aligned and
        # the sublane (block) dim 8-aligned, or compilation dies deep in
        # the DMA lowering.  Callers (engine auto-selection) should fall
        # back to the gather path for such geometries.  (The quant scale
        # arrays' Hkv lane dim is exempt from the 128 rule: Mosaic pads
        # small-lane DMAs, and at [W, Hkv] f32 the padded burst is still
        # ~3% of the K+V bytes.)
        raise ValueError(
            f"pallas paged decode needs F % 128 == 0 and block_size % 8 "
            f"== 0; got F={Fc}, block_size={block_size} (use the XLA "
            "gather path for this geometry)")
    F = Hkv * D
    if pair is None:
        # Clamp to the table width: a tile wider than the whole table
        # would only re-fetch the clamped last page.
        pair = min(auto_pair(block_size, F,
                             jnp.dtype(k_cache.dtype).itemsize),
                   block_tables.shape[1])
    if scale is None:
        scale = D ** -0.5

    # int8 caches must not drag q down to int8 — the dequantized tiles
    # come back in q's dtype (see _decode_kernel.dequant), so contract
    # in q's dtype; bf16 caches keep the original cast-to-cache-dtype.
    q_scaled = (q.astype(jnp.float32) * scale).astype(
        q.dtype if quant else k_cache.dtype)

    kernel = functools.partial(_decode_kernel, block_size, pair, Hkv,
                               soft_cap, quant)
    in_specs = [
        pl.BlockSpec((1, Hq, D), lambda b, bt, sl: (b, 0, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),   # K stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),   # V stays in HBM
    ]
    scratch = [
        pltpu.VMEM((3, pair * block_size, F), k_cache.dtype),
        pltpu.VMEM((3, pair * block_size, F), v_cache.dtype),
    ]
    inputs = [block_tables, seq_lens, q_scaled, k_cache, v_cache]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY),  # k scales
                     pl.BlockSpec(memory_space=pltpu.ANY)]  # v scales
        scratch += [pltpu.VMEM((3, pair * block_size, Hkv), jnp.float32),
                    pltpu.VMEM((3, pair * block_size, Hkv), jnp.float32)]
        inputs += [k_scale, v_scale]
    scratch.append(pltpu.SemaphoreType.DMA((3, pair, 4 if quant else 2)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, bt, sl: (b, 0, 0)),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)
