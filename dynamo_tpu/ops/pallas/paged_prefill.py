"""Pallas TPU paged flash-prefill attention over the KV block pool.

Prefill attention used to be the one path that still materialised context:
`gather_kv` in models/llama.py pulled every cached slot of every row into
a dense `[R, C, Hkv, D]` buffer per layer per chunk — a full copy of up to
`max_pages_per_seq * block_size` tokens of K/V through HBM for each of R
rows, regardless of how much context each row really has.  This kernel is
the prefill sibling of `paged_attention.py`: K/V pages stream straight
from the pool's 2D `[S, F]` layer buffers through VMEM tiles with an
online-softmax accumulator, so no gathered context array ever exists and
the read cost is per-sequence-length.

Packed ragged layout (the engine's packed prefill plane): the query axis
is ONE flat `[T]` token axis holding several sequences' chunks
back-to-back ("segments"), described by per-segment
(q_start, q_len, seq_len, block-table row).  One compiled program then
serves any mix of chunk lengths — the engine stops padding `[R, T]`
buckets, and the prefill shape lattice collapses to the packed token
buckets × page buckets (the cold-prefill cliff shrinks with it).

Semantics per segment r (grid program r):

- its queries are packed rows [q_start[r], q_start[r] + q_len[r]) and
  carry absolute positions [seq_len[r] - q_len[r], seq_len[r]);
- each query attends to every pool slot of its own block table at
  positions `kv_pos < seq_len` AND `kv_pos <= q_pos` — so CACHED-PREFIX
  attention (chunked/residual prefill: prior context is resident pages)
  and in-chunk causal masking are the same position test.  The chunk's
  own K/V must be scattered into the pool before the kernel runs (the
  engine's standing write-then-attend discipline);
- segments never see each other: masking is by construction (each
  program reads only its own table's pages), not a soft segment-id
  compare.

Compute structure: per q tile (`q_tile` rows, default 128) the segment's
KV tiles stream once (double-buffered `pair`-page DMAs, the decode
kernel's fetch discipline); scores run as a static per-q-head loop of
`[TQ, D] x [D, W]` MXU passes — minimal FLOPs (no Hkv-fold banding: the
decode kernel's banding trick trades FLOPs for bytes, correct for
bandwidth-bound decode but wrong for compute-bound prefill; the cost
here is the D=64 contraction running the MXU at half fill, which the
docstring owns rather than hides).  Flash state (m, l, acc) lives
per-head as loop-carried VMEM values.

int8 variant: pass the pool's int8 buffers with their `[S, Hkv]` f32
scale siblings (PR 6 layout) — pages and scale tiles DMA together and
dequantize on the VMEM-resident tile per head, same numerics as
`kv_cache.dequantize_rows`.

Eligibility is `mosaic_geometry_ok` — THE shared predicate with the
decode kernel (F % 128, block_size % 8), plus packed-axis alignment
(T % 8, segment starts % 8, handled by the engine's pack builder).
Ineligible geometries take the gather path (the padded-bucket plane);
`interpret=True` runs anywhere (CPU tests).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.ops.pallas.paged_attention import auto_pair, mosaic_geometry_ok

# Matches ops/attention.py NEG_INF: finite so fully-masked (discarded)
# rows produce finite junk instead of NaN-poisoned accumulators.
_NEG_INF = -1e30

# Packed-axis alignment the engine's pack builder must honor: segment
# starts and the packed bucket length are multiples of this, so the
# kernel's dynamic sublane slices stay tile-aligned.
PACK_ALIGN = 8


def _prefill_kernel(block_size: int, pair: int, n_kv: int, n_q: int,
                    q_tile: int, soft_cap: Optional[float], quant: bool,
                    # scalar-prefetch refs (SMEM)
                    bt_ref, len_ref, qstart_ref, qlen_ref,
                    # tensor refs
                    q_ref, k_hbm, v_hbm, *rest):
    if quant:
        (ks_hbm, vs_hbm, o_ref, k_vmem, v_vmem,
         ks_vmem, vs_vmem, sem) = rest
    else:
        o_ref, k_vmem, v_vmem, sem = rest
        ks_hbm = vs_hbm = ks_vmem = vs_vmem = None
    r = pl.program_id(0)
    seq_len = len_ref[r]
    q_start = qstart_ref[r]
    q_len = qlen_ref[r]
    chunk_start = seq_len - q_len

    T, Fq = q_ref.shape
    D = Fq // n_q
    G = n_q // n_kv
    W = block_size * pair
    TQ = q_tile

    # The out block has a constant index map (revisited across programs,
    # written back once): zero it before the first segment so pad rows
    # and inter-segment alignment gaps emit zeros, not uninitialised VMEM.
    @pl.when(r == 0)
    def _():
        o_ref[:] = jnp.zeros(o_ref.shape, o_ref.dtype)

    def fetch(buf, hbm, slot, t, j, lane):
        # Page p = t*pair + j of segment r, clamped to its last real page
        # so a tail tile's extra DMA is a harmless re-fetch (those
        # positions are masked in compute).
        last = jnp.maximum(pl.cdiv(seq_len, block_size) - 1, 0)
        p = jnp.minimum(t * pair + j, last)
        return pltpu.make_async_copy(
            hbm.at[pl.ds(bt_ref[r, p] * block_size, block_size)],
            buf.at[slot, pl.ds(j * block_size, block_size)],
            sem.at[slot, j, lane])

    streams = [(k_vmem, k_hbm, 0), (v_vmem, v_hbm, 1)]
    if quant:
        streams += [(ks_vmem, ks_hbm, 2), (vs_vmem, vs_hbm, 3)]

    def start_tile(slot, t):
        for j in range(pair):
            for buf, hbm, lane in streams:
                fetch(buf, hbm, slot, t, j, lane).start()

    def wait_tile(slot, t):
        for j in range(pair):
            for buf, hbm, lane in streams:
                fetch(buf, hbm, slot, t, j, lane).wait()

    n_q_tiles = pl.cdiv(q_len, TQ)

    def q_tile_body(qi, _):
        # Clamp the tile window into [0, T - TQ]: a tail tile re-covers
        # rows the previous tile already wrote (recomputed identically),
        # and rows outside this segment are masked out of the store.
        base = jnp.clip(q_start + qi * TQ, 0, T - TQ)
        idx0 = base - q_start                    # first row's chunk index
        qp = q_ref[pl.ds(base, TQ), :]           # [TQ, Fq] pre-scaled
        row_idx = idx0 + jax.lax.broadcasted_iota(jnp.int32, (TQ, 1), 0)
        row_ok = jnp.logical_and(row_idx >= 0, row_idx < q_len)
        q_pos = chunk_start + row_idx            # [TQ, 1] absolute
        # Causality bounds the KV sweep: this tile's last query sees at
        # most position chunk_start + idx0 + TQ - 1.
        kv_hi = jnp.minimum(seq_len, chunk_start + idx0 + TQ)
        n_kv_iters = pl.cdiv(jnp.maximum(kv_hi, 0), W)

        @pl.when(n_kv_iters > 0)
        def _():
            start_tile(0, 0)

        m0 = tuple(jnp.full((TQ, 1), _NEG_INF, jnp.float32)
                   for _ in range(n_q))
        l0 = tuple(jnp.zeros((TQ, 1), jnp.float32) for _ in range(n_q))
        a0 = tuple(jnp.zeros((TQ, D), jnp.float32) for _ in range(n_q))

        def kv_body(t, carry):
            ms, ls, accs = carry
            slot = jax.lax.rem(t, 2)

            @pl.when(t + 1 < n_kv_iters)
            def _():
                start_tile(jax.lax.rem(t + 1, 2), t + 1)

            wait_tile(slot, t)
            kv_pos = t * W + jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
            mask = jnp.logical_and(
                jnp.logical_and(kv_pos < seq_len, kv_pos <= q_pos), row_ok)

            new_m, new_l, new_a = [], [], []
            for j in range(n_q):
                h = j // G
                if quant:
                    k_h = (k_vmem[slot, :, h * D:(h + 1) * D]
                           .astype(jnp.float32)
                           * ks_vmem[slot, :, h:h + 1]).astype(qp.dtype)
                    v_h = (v_vmem[slot, :, h * D:(h + 1) * D]
                           .astype(jnp.float32)
                           * vs_vmem[slot, :, h:h + 1]).astype(qp.dtype)
                else:
                    k_h = k_vmem[slot, :, h * D:(h + 1) * D]  # [W, D]
                    v_h = v_vmem[slot, :, h * D:(h + 1) * D]
                q_j = qp[:, j * D:(j + 1) * D]                # [TQ, D]
                s = jax.lax.dot_general(
                    q_j, k_h, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)       # [TQ, W]
                if soft_cap is not None:
                    s = soft_cap * jnp.tanh(s / soft_cap)
                s = jnp.where(mask, s, _NEG_INF)
                m_new = jnp.maximum(ms[j],
                                    jnp.max(s, axis=-1, keepdims=True))
                alpha = jnp.exp(ms[j] - m_new)
                probs = jnp.exp(s - m_new)
                # Fully-masked rows: probs == 1 uniformly (finite junk);
                # their store is masked by row_ok below.
                new_m.append(m_new)
                new_l.append(ls[j] * alpha
                             + jnp.sum(probs, axis=-1, keepdims=True))
                pv = jax.lax.dot_general(
                    probs.astype(v_h.dtype), v_h, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)       # [TQ, D]
                new_a.append(accs[j] * alpha + pv)
            return tuple(new_m), tuple(new_l), tuple(new_a)

        ms, ls, accs = jax.lax.fori_loop(0, n_kv_iters, kv_body,
                                         (m0, l0, a0))
        outs = [accs[j] / jnp.maximum(ls[j], 1e-30) for j in range(n_q)]
        res = jnp.concatenate(outs, axis=1).astype(o_ref.dtype)
        cur = o_ref[pl.ds(base, TQ), :]
        # Masked store: rows outside this segment keep their value (an
        # earlier tile's output on overlap, zeros on padding) — grid
        # programs run sequentially, so segment order is respected.
        o_ref[pl.ds(base, TQ), :] = jnp.where(row_ok, res, cur)
        return 0

    jax.lax.fori_loop(0, n_q_tiles, q_tile_body, 0)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "scale", "soft_cap", "interpret",
                     "pair", "q_tile"))
def paged_prefill_attention(
    q: jax.Array,             # [T, Hq, D] packed chunk queries
    k_cache: jax.Array,       # [S, F = Hkv * D] one layer's pool keys
    v_cache: jax.Array,       # [S, F]
    block_tables: jax.Array,  # [R, P] int32 page ids per segment
    seq_lens: jax.Array,      # [R] valid context AFTER this chunk
    q_starts: jax.Array,      # [R] packed row offset of each segment
    q_lens: jax.Array,        # [R] real query rows per segment (0 = pad)
    *,
    block_size: int,
    scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    interpret: bool = False,
    pair: Optional[int] = None,
    q_tile: Optional[int] = None,
    k_scale: Optional[jax.Array] = None,  # [S, Hkv] f32 (int8 pool)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Packed ragged prefill attention over the paged pool; [T, Hq, D].

    Each segment's queries attend to its own block table's pool slots at
    `kv_pos < seq_len AND kv_pos <= q_pos` — cached-prefix attention for
    chunked/residual prefill and in-chunk causality in one mask.  The
    chunk's own K/V must already be scattered into the pool.  Numerics
    match the gather path (`kv_cache.gather_kv` + `ops.attention.
    paged_attention`) per segment: bf16 MXU passes, f32 accumulation,
    f32 softmax.

    Layout contract (the engine's pack builder provides it): T and every
    q_start are multiples of `PACK_ALIGN` (8), and T >= the q tile.  Pad
    segments carry q_len == 0.  Rows not owned by any segment come back
    zero.

    Quantized variant: int8 pool buffers plus `k_scale`/`v_scale`
    ([S, Hkv] f32) — dequantization happens on the VMEM tile after the
    DMA, `kv_cache.dequantize_rows` numerics.
    """
    T, Hq, D = q.shape
    S, Fc = k_cache.shape
    Hkv = Fc // D
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if quant and k_cache.dtype != jnp.int8:
        raise ValueError(f"scales imply an int8 cache; got {k_cache.dtype}")
    if Fc % D or Hq % Hkv:
        raise ValueError(f"bad geometry: q {q.shape}, cache {k_cache.shape}")
    if T % PACK_ALIGN:
        raise ValueError(f"packed token axis T={T} must be a multiple of "
                         f"{PACK_ALIGN} (see pack builder alignment)")
    if not interpret and not mosaic_geometry_ok(Fc, block_size):
        raise ValueError(
            f"pallas paged prefill needs F % 128 == 0 and block_size % 8 "
            f"== 0; got F={Fc}, block_size={block_size} (use the gather "
            "path for this geometry)")
    if pair is None:
        pair = min(auto_pair(block_size, Fc,
                             jnp.dtype(k_cache.dtype).itemsize),
                   block_tables.shape[1])
    if q_tile is None:
        q_tile = min(128, T)
    if T < q_tile:
        raise ValueError(f"T={T} smaller than q_tile={q_tile}")
    if scale is None:
        scale = D ** -0.5
    R = block_tables.shape[0]

    # Pre-scale and flatten the queries to the kernel's 2D token-major
    # [T, Fq] view; int8 pools dequantize into q's dtype, bf16 pools
    # contract in the cache dtype (decode-kernel discipline).
    q_scaled = (q.astype(jnp.float32) * scale).astype(
        q.dtype if quant else k_cache.dtype)
    q2d = q_scaled.reshape(T, Hq * D)

    kernel = functools.partial(_prefill_kernel, block_size, pair, Hkv, Hq,
                               q_tile, soft_cap, quant)
    in_specs = [
        # Index maps receive (program_id, *scalar_prefetch_refs).
        pl.BlockSpec((T, Hq * D), lambda r, *_: (0, 0)),  # resident queries
        pl.BlockSpec(memory_space=pltpu.ANY),         # K stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),         # V stays in HBM
    ]
    scratch = [
        pltpu.VMEM((2, pair * block_size, Fc), k_cache.dtype),
        pltpu.VMEM((2, pair * block_size, Fc), v_cache.dtype),
    ]
    inputs = [block_tables, seq_lens, q_starts, q_lens, q2d,
              k_cache, v_cache]
    if quant:
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY),
                     pl.BlockSpec(memory_space=pltpu.ANY)]
        scratch += [pltpu.VMEM((2, pair * block_size, Hkv), jnp.float32),
                    pltpu.VMEM((2, pair * block_size, Hkv), jnp.float32)]
        inputs += [k_scale, v_scale]
    scratch.append(pltpu.SemaphoreType.DMA((2, pair, 4 if quant else 2)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(R,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((T, Hq * D), lambda r, *_: (0, 0)),
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((T, Hq * D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(*inputs)
    return out.reshape(T, Hq, D)
