"""Latency-hiding Pallas ring attention for sequence-parallel prefill.

The XLA ring (`ops/ring_attention.py`) rotates K/V blocks with
`lax.ppermute` and HOPES the scheduler overlaps each hop with the local
einsums — nothing guarantees it, and the per-hop `s`/`p` intermediates
round-trip HBM.  This kernel makes the overlap structural (blockwise
ring attention, Liu et al.): each shard keeps its Q block resident in
VMEM with online-softmax (m, l, acc) state, and the NEXT hop's K/V
block — absolute positions and, when quantized, the int8 rows' `[T,
Hkv]` f32 scales riding with them exactly as on the XLA path — is
shipped over ICI via double-buffered `make_async_remote_copy` RDMA
issued BEFORE the local block's compute.  The transfer hides under the
flash fold on every hop instead of being scheduled on faith.

Numerics mirror `ring_causal_attention` operand-for-operand (same
visiting order starting at the shard's own block, same f32 softmax
path, same `NEG` mask fill, same dequant-to-compute-dtype-then-f32
int8 path via `kv_cache.dequantize_rows` semantics), so the XLA ring
stays the oracle: `tests/test_ring_kernel.py` pins kernel == XLA ring
== meshless `causal_attention` for bf16 and int8.

Hardware sync protocol (compiled mode only; interpret executes
sequentially so the races cannot occur and the remote-signal
primitives aren't implemented there):

- an initial neighbor barrier (`get_barrier_semaphore`) so no shard
  RDMAs into a peer that hasn't entered the kernel;
- credit-based ack backpressure: the send at step s writes the
  receiver's slot (s+1) % 2, which the receiver last reads at step
  s-1 — so the sender waits for the receiver's ack before the send at
  every step >= 1, and each shard acks its LEFT neighbor (the device
  writing into its buffers) after folding a slot it will never read
  again.

Eligibility is `ring_geometry_ok` (the mosaic_geometry_ok discipline:
one predicate shared by the model's trace-time dispatch, the engine's
kernel-path counter, profile_decode and the bench so they can never
disagree on which path a geometry runs); ineligible shapes fall back
to the XLA ppermute path loudly at the dispatch site.

Interpret mode: CPU tier-1 exercises the kernel body end to end.
jax's interpret-mode discharge of `dma_start_p` only supports remote
copies under a SINGLE named mesh axis, but every repo mesh binds five
(dp, pp, sp, ep, tp) — `_install_interpret_remote_dma()` re-registers
a narrowly generalized discharge rule (flattened row-major logical id
over the axis env, multi-name all_gathers; single-axis behavior
delegated untouched to the stock rule) so the same kernel body runs
under the real serving meshes on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30

# ---------------------------------------------------------------------------
# Eligibility


def ring_geometry_ok(feat: int, t_local: int) -> bool:
    """THE eligibility rule for the ring kernel: the per-shard K/V
    feature width (F/tp under head-sharded tp) must fill MXU lanes
    (128-aligned) and the per-shard chunk length must be
    sublane-aligned (8), or Mosaic's DMA lowering dies.  Shared by the
    trace-time dispatch in `models/llama._attention_block`, the
    engine's kernel-path counter, profile_decode and bench/ring_plane —
    the same discipline as `mosaic_geometry_ok` — so the served
    engine and every measurement tool agree on which path runs."""
    return feat % 128 == 0 and t_local % 8 == 0 and t_local >= 8


# ---------------------------------------------------------------------------
# Interpret-mode remote-DMA support under multi-axis meshes

_interpret_patch_state: Optional[bool] = None


def _generalized_dma_discharge(stock_rule, prims, in_avals, out_avals,
                               *args, tree, device_id_type):
    """Discharge rule for `dma_start_p` that extends the stock
    interpret-mode rule to remote LOGICAL copies under MULTI-axis
    envs.  Anything the stock rule already handles (local copies,
    single-axis envs, MESH ids) is delegated to it untouched."""
    from jax._src import core as jax_core
    from jax._src import tree_util
    from jax._src.state import discharge as state_discharge

    (src_ref, src_transforms, dst_ref, dst_transforms, dst_sem,
     dst_sem_transforms, src_sem, src_sem_transforms,
     device_id) = tree_util.tree_unflatten(tree, args)
    (_, src_transforms_avals, _, dst_transforms_avals, dst_sem_aval,
     dst_sem_transforms_avals, src_sem_aval, src_sem_transforms_avals,
     _) = tree_util.tree_unflatten(tree, in_avals)

    axis_env = jax_core.get_axis_env()
    nonempty_axes = [n for n in axis_env.axis_sizes if n is not None]
    if (device_id is None or len(nonempty_axes) <= 1
            or device_id_type != prims.DeviceIdType.LOGICAL):
        return stock_rule(in_avals, out_avals, *args, tree=tree,
                          device_id_type=device_id_type)

    pl_core = prims.pl_core
    num_src_sem_transforms = len(
        tree_util.tree_leaves(src_sem_transforms_avals))
    num_dst_sem_transforms = len(
        tree_util.tree_leaves(dst_sem_transforms_avals))
    num_src_transform_vals = len(
        tree_util.tree_leaves(src_transforms_avals))
    num_dst_transform_vals = len(
        tree_util.tree_leaves(dst_transforms_avals))

    updates = state_discharge.transform_array(src_ref, src_transforms)
    local_src = updates

    # The generalization: a LOGICAL id is the flattened row-major index
    # over the mesh axes in binding order (exactly how `make_mesh` lays
    # devices out), so under a multi-axis env we gather over ALL axes
    # and compute our own flattened index the same way.
    shard_axis = tuple(nonempty_axes)
    my_axis = jnp.int32(0)
    for name in nonempty_axes:
        my_axis = (my_axis * axis_env.axis_sizes[name]
                   + jax.lax.axis_index(name))

    who_copy_to_me = jax.lax.all_gather(device_id, shard_axis) == my_axis
    index = jnp.argmax(who_copy_to_me, axis=0)
    global_updates = jax.lax.all_gather(updates, shard_axis)
    updates = jax.lax.dynamic_index_in_dim(global_updates, index, axis=0,
                                           keepdims=False)
    global_dst_transforms = tree_util.tree_map(
        lambda x: jax.lax.all_gather(x, shard_axis), dst_transforms)
    dst_transforms = tree_util.tree_map(
        lambda x: jax.lax.dynamic_index_in_dim(x, index, axis=0,
                                               keepdims=False),
        global_dst_transforms)

    _, new_dst = state_discharge.transform_swap_array(
        dst_ref, dst_transforms, updates)

    recv_size = jnp.minimum(updates.size, pl_core.SEMAPHORE_MAX_VALUE)
    recv_size = jnp.array(recv_size,
                          dtype=pl_core.SEMAPHORE_INTERPRET_DTYPE)
    dst_sem_value = prims._transform_semaphore(
        dst_sem, dst_sem_transforms, dst_sem_aval)
    _, new_dst_sem = state_discharge.transform_swap_array(
        dst_sem, dst_sem_transforms, dst_sem_value + recv_size)

    send_size = jnp.minimum(local_src.size, pl_core.SEMAPHORE_MAX_VALUE)
    send_size = jnp.array(send_size,
                          dtype=pl_core.SEMAPHORE_INTERPRET_DTYPE)
    src_sem_value = prims._transform_semaphore(
        src_sem, src_sem_transforms, src_sem_aval)
    _, new_src_sem = state_discharge.transform_swap_array(
        src_sem, src_sem_transforms, src_sem_value + send_size)

    new_vals = (None,)
    new_vals += (None,) * num_src_transform_vals
    new_vals += (new_dst,)
    new_vals += (None,) * num_dst_transform_vals
    new_vals += (new_dst_sem,)
    new_vals += (None,) * num_dst_sem_transforms
    new_vals += (new_src_sem,)
    new_vals += (None,) * num_src_sem_transforms
    new_vals += (None,)  # device_id
    assert len(new_vals) == len(in_avals)
    return new_vals, []


def _install_interpret_remote_dma() -> bool:
    """Re-register the generalized `dma_start_p` discharge rule
    (idempotent; returns False — making the whole kernel fall back to
    the XLA ring — if the jax internals this leans on ever move)."""
    global _interpret_patch_state
    if _interpret_patch_state is not None:
        return _interpret_patch_state
    try:
        from jax._src.pallas.mosaic import primitives as prims
        from jax._src.state import discharge as state_discharge

        stock = state_discharge._discharge_rules[prims.dma_start_p]
        rule = functools.partial(_generalized_dma_discharge, stock, prims)
        state_discharge.register_discharge_rule(prims.dma_start_p)(rule)
        _interpret_patch_state = True
    except Exception:  # pragma: no cover - future-jax drift guard
        _interpret_patch_state = False
    return _interpret_patch_state


def ring_kernel_supported(feat: int, t_local: int,
                          interpret: bool) -> bool:
    """The ONE kernel-vs-XLA-ring selection predicate (engine counter,
    model dispatch, tools).  Compiled mode needs Mosaic-legal geometry;
    interpret mode runs ANY shape (nothing lowers through Mosaic — this
    is how CPU tier-1 exercises the kernel body at tiny geometry) but
    needs the generalized remote-DMA discharge installed."""
    if interpret:
        return _install_interpret_remote_dma()
    return ring_geometry_ok(feat, t_local)


# ---------------------------------------------------------------------------
# Kernel


def _flash_fold(q_ref, qpos_col_ref, k_buf, v_buf, pos_buf, ks_buf,
                vs_buf, cur, state, *, B, t_loc, Hq, G, D, soft_cap,
                compute_dtype):
    """Fold the visiting K/V block (buffer slot `cur`) into the
    (m, l, acc) state — the same update `ring_causal_attention` applies
    per ppermute step, on 2D tiles: per (batch row, q head) a
    [T_loc, D] x [D, T_loc] MXU matmul in f32."""
    for b in range(B):
        r0 = b * t_loc
        # mask[t, c]: visiting key c attends query t iff its absolute
        # position is <= the query's (causality carried by the rotating
        # positions, correct for any block interleaving).
        mask = (pos_buf[cur, b:b + 1, :]
                <= qpos_col_ref[r0:r0 + t_loc, :])
        for h in range(Hq):
            hk = h // G
            q_h = q_ref[r0:r0 + t_loc, h * D:(h + 1) * D]
            k_h = k_buf[cur, r0:r0 + t_loc, hk * D:(hk + 1) * D]
            v_h = v_buf[cur, r0:r0 + t_loc, hk * D:(hk + 1) * D]
            if ks_buf is not None:
                # Dequant in VMEM to the compute dtype FIRST, then f32 —
                # the exact kv_cache.dequantize_rows operand path every
                # cache read (and the XLA ring) sees.
                k_h = (k_h.astype(jnp.float32)
                       * ks_buf[cur, r0:r0 + t_loc, hk:hk + 1]
                       ).astype(compute_dtype).astype(jnp.float32)
                v_h = (v_h.astype(jnp.float32)
                       * vs_buf[cur, r0:r0 + t_loc, hk:hk + 1]
                       ).astype(compute_dtype).astype(jnp.float32)
            else:
                k_h = k_h.astype(jnp.float32)
                v_h = v_h.astype(jnp.float32)
            s = jax.lax.dot_general(
                q_h, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)
            s = jnp.where(mask, s, _NEG_INF)
            m, l, acc = state[b][h]
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
            pv = jax.lax.dot_general(
                p, v_h, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            state[b][h] = (m_new, l, acc * alpha + pv)


def _ring_kernel(nbr_ref, q_ref, qpos_col_ref, k_ref, v_ref, kpos_ref,
                 *rest, sp, B, t_loc, Hq, Hkv, D, soft_cap, quant,
                 interpret, compute_dtype):
    """One program per shard: flash-fold the resident slot while the
    next hop's K/V (+positions, +scales) RDMAs into the other slot."""
    from jax.experimental.pallas import tpu as pltpu

    if quant:
        ks_ref, vs_ref, o_ref = rest[0], rest[1], rest[2]
        scratch = rest[3:]
    else:
        ks_ref = vs_ref = None
        o_ref = rest[0]
        scratch = rest[1:]
    (k_buf, v_buf, pos_buf, ks_buf, vs_buf, load_sem, send_sem,
     recv_sem, ack_sem) = scratch

    right = nbr_ref[0]
    left = nbr_ref[1]
    G = Hq // Hkv

    streams = [(k_ref, k_buf), (v_ref, v_buf), (kpos_ref, pos_buf)]
    if quant:
        streams += [(ks_ref, ks_buf), (vs_ref, vs_buf)]

    if sp > 1 and not interpret:
        # Neighbor barrier: no shard may RDMA into a peer that hasn't
        # entered the kernel and allocated these buffers.
        bsem = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bsem, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(bsem, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(bsem, 2)

    # Stage the local block into slot 0 (HBM -> VMEM).
    loads = [pltpu.make_async_copy(src, buf.at[0], load_sem.at[i])
             for i, (src, buf) in enumerate(streams)]
    for cp in loads:
        cp.start()
    for cp in loads:
        cp.wait()

    zero = jnp.zeros((t_loc, 1), jnp.float32)
    state = [[(jnp.full((t_loc, 1), _NEG_INF, jnp.float32), zero,
               jnp.zeros((t_loc, D), jnp.float32))
              for _ in range(Hq)] for _ in range(B)]

    for step in range(sp):
        cur, nxt = step % 2, (step + 1) % 2
        rdmas = []
        if step + 1 < sp:
            if step >= 1 and not interpret:
                # Credit: the receiver read slot `nxt` for the last
                # time at step-1; only its ack makes overwriting safe.
                pltpu.semaphore_wait(ack_sem, 1)
            # Ship the NEXT hop before any compute — the whole point.
            for i, (_, buf) in enumerate(streams):
                rdma = pltpu.make_async_remote_copy(
                    src_ref=buf.at[cur], dst_ref=buf.at[nxt],
                    send_sem=send_sem.at[i, cur],
                    recv_sem=recv_sem.at[i, nxt],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                rdma.start()
                rdmas.append(rdma)
        _flash_fold(q_ref, qpos_col_ref, k_buf, v_buf, pos_buf,
                    ks_buf if quant else None,
                    vs_buf if quant else None, cur, state,
                    B=B, t_loc=t_loc, Hq=Hq, G=G, D=D,
                    soft_cap=soft_cap, compute_dtype=compute_dtype)
        if step + 1 < sp:
            if step <= sp - 3 and not interpret:
                # Slot `cur` is dead to us — credit the LEFT neighbor
                # (the device whose sends land in our buffers).
                pltpu.semaphore_signal(
                    ack_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            for rdma in rdmas:
                rdma.wait()

    for b in range(B):
        r0 = b * t_loc
        for h in range(Hq):
            m, l, acc = state[b][h]
            # Fully-masked (padding) rows are junk-but-finite, exactly
            # as on the XLA ring — the divide guard matches it.
            o_ref[r0:r0 + t_loc, h * D:(h + 1) * D] = (
                acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def ring_flash_attention(
    q: jax.Array,            # [B, T_loc, Hq, D]
    k: jax.Array,            # [B, T_loc, Hkv, D] (int8 when k_scale given)
    v: jax.Array,            # [B, T_loc, Hkv, D]
    q_positions: jax.Array,  # [B, T_loc] absolute token positions
    kv_positions: Optional[jax.Array] = None,
    *,
    mesh,                    # the Mesh this shard_map body runs under
    axis_name: str = "sp",
    scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,  # [B, T_loc, Hkv] f32
    v_scale: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash ring attention with RDMA'd K/V rotation; call inside
    `shard_map` with the T axis sharded over `axis_name`.  Drop-in for
    `ring_causal_attention` at eligible geometry (same signature modulo
    the static `mesh`); sp == 1 degenerates to plain flash attention
    with no remote traffic."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, t_loc, Hq, D = q.shape
    Hkv = k.shape[2]
    feat = Hkv * D
    if scale is None:
        scale = D ** -0.5
    if kv_positions is None:
        kv_positions = q_positions
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    sp = mesh.shape[axis_name]
    quant = k_scale is not None
    if not ring_kernel_supported(feat, t_loc, interpret):
        raise ValueError(
            f"ring kernel geometry rejected: per-shard feat={feat} "
            f"(needs % 128 == 0), t_local={t_loc} (needs % 8 == 0, "
            ">= 8) — dispatch the XLA ppermute ring "
            "(ops/ring_attention.ring_causal_attention) instead")

    # Flattened LOGICAL ids of the ring neighbors: row-major over the
    # mesh axes in binding order, the layout make_mesh gives the device
    # array (and the flattening the interpret discharge rule mirrors).
    names = list(mesh.axis_names)
    flat = jnp.int32(0)
    for n in names:
        flat = flat * mesh.shape[n] + jax.lax.axis_index(n)
    stride = 1
    for n in names[names.index(axis_name) + 1:]:
        stride *= mesh.shape[n]
    idx = jax.lax.axis_index(axis_name)
    right = flat + ((idx + 1) % sp - idx) * stride
    left = flat + ((idx + sp - 1) % sp - idx) * stride
    nbr = jnp.stack([right, left]).astype(jnp.int32)

    # 2D operand views; q pre-scaled in f32 exactly like the XLA ring's
    # `qg` (one multiply outside the hop loop).
    q2 = (q.astype(jnp.float32) * scale).reshape(B * t_loc, Hq * D)
    qpos_col = q_positions.reshape(B * t_loc, 1).astype(jnp.int32)
    k2 = k.reshape(B * t_loc, feat)
    v2 = v.reshape(B * t_loc, feat)
    kpos = kv_positions.astype(jnp.int32)
    args = [nbr, q2, qpos_col, k2, v2, kpos]
    if quant:
        args += [k_scale.reshape(B * t_loc, Hkv).astype(jnp.float32),
                 v_scale.reshape(B * t_loc, Hkv).astype(jnp.float32)]

    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM), vmem, vmem,
                any_spec, any_spec, any_spec]
    if quant:
        in_specs += [any_spec, any_spec]
    n_streams = 5 if quant else 3
    scratch = [
        pltpu.VMEM((2, B * t_loc, feat), k.dtype),            # k_buf
        pltpu.VMEM((2, B * t_loc, feat), v.dtype),            # v_buf
        pltpu.VMEM((2, B, t_loc), jnp.int32),                 # pos_buf
        pltpu.VMEM((2, B * t_loc, Hkv), jnp.float32),         # ks_buf
        pltpu.VMEM((2, B * t_loc, Hkv), jnp.float32),         # vs_buf
        pltpu.SemaphoreType.DMA((n_streams,)),                # load
        pltpu.SemaphoreType.DMA((n_streams, 2)),              # send
        pltpu.SemaphoreType.DMA((n_streams, 2)),              # recv
        pltpu.SemaphoreType.REGULAR,                          # ack
    ]

    kernel = functools.partial(
        _ring_kernel, sp=sp, B=B, t_loc=t_loc, Hq=Hq, Hkv=Hkv, D=D,
        soft_cap=soft_cap, quant=quant, interpret=interpret,
        compute_dtype=q.dtype)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * t_loc, Hq * D), q.dtype),
        in_specs=in_specs,
        out_specs=vmem,
        scratch_shapes=scratch,
        interpret=interpret,
        compiler_params=pltpu.TPUCompilerParams(collective_id=1),
    )(*args)
    return out.reshape(B, t_loc, Hq, D)
