"""Ring attention: causal self-attention with sequence-sharded K/V.

The long-context prefill primitive SURVEY §2.5 demands as a TPU-native
addition (the reference core has no CP/ring path — its long-context
levers are conditional disaggregation and engine flags).  The design is
blockwise ring attention (Liu et al.; the public JAX formulation in the
scaling-book's collective-matmul pattern): the sequence axis is sharded
over the `sp` mesh axis, every shard keeps its Q block resident, and
K/V blocks rotate one hop per step around the ICI ring via
`lax.ppermute` while an online-softmax accumulator folds each visiting
block in.  After sp steps every Q block has seen every K/V block; peak
memory per chip is O(T/sp).

Comm/compute overlap on this path is SCHEDULER-DEPENDENT: there is no
data dependence between a step's ppermute and its einsums, so XLA *may*
overlap them, but nothing guarantees it, and the per-hop `s`/`p`
intermediates round-trip HBM either way.  The Pallas flash ring
(`ops/pallas/ring_attention.py`) makes the overlap structural — the
next hop's RDMA is issued before the local block's fold — and eligible
geometry dispatches it instead (llama._sp_ring_attention); THIS module
remains the fallback for ineligible shapes and the parity oracle both
implementations are pinned against.

Causality is enforced with ABSOLUTE positions carried alongside the
rotating K/V — masks stay correct for any block interleaving, and fully
masked (padding) rows are guarded at the final divide.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from dynamo_tpu.runtime import jax_compat

NEG = -1e30


def ring_causal_attention(
    q: jax.Array,            # [B, T_loc, Hq, D]
    k: jax.Array,            # [B, T_loc, Hkv, D] (int8 when k_scale given)
    v: jax.Array,            # [B, T_loc, Hkv, D]
    q_positions: jax.Array,  # [B, T_loc] absolute token positions
    kv_positions: Optional[jax.Array] = None,  # defaults to q_positions
    axis_name: Optional[str] = None,  # None → single shard (degenerates
                                      # to masked causal attention)
    scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,  # [B, T_loc, Hkv] f32 (int8 k/v)
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """Blockwise-causal attention; call inside `shard_map` with the T axis
    sharded over `axis_name` (or standalone with axis_name=None).

    Returns [B, T_loc, Hq, D] in q's dtype.  Numerics match
    ops/attention.py `causal_attention` (same mask, f32 softmax path).

    Quantized exchange (ISSUE 12 leg 1): with `k_scale`/`v_scale`, K/V
    are int8 rows quantized EXACTLY as the paged cache stores them
    (kv_cache.quantize_kv_rows) and the per-token-per-head f32 scales
    rotate around the ring WITH their rows — each hop dequantizes the
    visiting block in-register (kv_cache.dequantize_rows to q's compute
    dtype, f32 inside the softmax math), so ring attention sees the same
    dequantized operands every cache-read path sees, and the per-hop ICI
    payload drops from 2·F·itemsize to F + 4·Hkv bytes per token.
    """
    B, T, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    if scale is None:
        scale = D ** -0.5
    if kv_positions is None:
        kv_positions = q_positions
    sp = 1 if axis_name is None else jax_compat.axis_size(axis_name)

    qg = (q.astype(jnp.float32) * scale).reshape(B, T, Hkv, G, D)

    m = jnp.full((B, Hkv, G, T, 1), NEG, jnp.float32)
    l = jnp.zeros((B, Hkv, G, T, 1), jnp.float32)
    acc = jnp.zeros((B, T, Hkv, G, D), jnp.float32)

    # Visiting order starts with the shard's OWN block (the causal
    # diagonal): every real q row sees at least its own key in step 0, so
    # m leaves the finite NEG floor immediately and later fully-masked
    # blocks contribute exp(NEG - m) == 0 rather than exp(0).  (A ring
    # order that visited a later shard's block first would need the
    # -inf/NaN dance instead.)
    k_cur, v_cur, kv_pos = k, v, kv_positions
    ks_cur, vs_cur = k_scale, v_scale
    for step in range(sp):
        if ks_cur is None:
            kf = k_cur.astype(jnp.float32)
            vf = v_cur.astype(jnp.float32)
        else:
            from dynamo_tpu.engine.kv_cache import dequantize_rows

            # Dequant to q's compute dtype first, THEN f32 — the exact
            # operand path gather_kv_quant feeds the XLA fallback, so
            # ring and gather attention agree bit-for-bit pre-softmax.
            kf = dequantize_rows(k_cur, ks_cur, q.dtype).astype(jnp.float32)
            vf = dequantize_rows(v_cur, vs_cur, q.dtype).astype(jnp.float32)
        # [B, Hkv, G, T, Tk]
        s = jnp.einsum("btkgd,bckd->bkgtc", qg, kf)
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        mask = (kv_pos[:, None, :] <= q_positions[:, :, None]
                )[:, None, None, :, :]
        s = jnp.where(mask, s, NEG)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bkgtc,bckd->btkgd", p, vf)
        acc = acc * alpha.transpose(0, 3, 1, 2, 4) + pv
        m = m_new

        if axis_name is not None and step + 1 < sp:
            perm = [(i, (i + 1) % sp) for i in range(sp)]
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            kv_pos = jax.lax.ppermute(kv_pos, axis_name, perm)
            if ks_cur is not None:
                # Scales ride the ring WITH their int8 rows — a block and
                # its scales can never desynchronize across hops.
                ks_cur = jax.lax.ppermute(ks_cur, axis_name, perm)
                vs_cur = jax.lax.ppermute(vs_cur, axis_name, perm)

    # Fully-masked rows (padding) keep l == 0: guard the divide.
    out = acc / jnp.maximum(l.transpose(0, 3, 1, 2, 4), 1e-30)
    return out.reshape(B, T, Hq, D).astype(q.dtype)
