"""Parallelism over TPU device meshes.

The reference passes parallelism flags through to its engines (SURVEY.md
§2.5 — TP/PP/EP are vLLM's problem); here they are first-class: a named
`jax.sharding.Mesh` with axes

    dp — data (replica) parallel: batch dimension
    sp — sequence/context parallel: ring attention over long prompts
    ep — expert parallel: MoE expert dimension
    tp — tensor parallel: heads / hidden features, over ICI

and GSPMD sharding rules (PartitionSpecs per parameter/cache/activation)
that let XLA insert the collectives (psum over ICI for row-parallel
matmuls, all-to-all for experts, ppermute rings for sequence shards).
"""

from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.parallel.pipeline import make_pp_step
from dynamo_tpu.parallel.sharding import (
    PlaneSpec,
    cache_pspecs,
    check_plane,
    data_pspecs,
    make_sharded_greedy_step,
    make_sharded_step,
    make_sp_prefill_step,
    param_pspecs,
    plane_capability,
    shard_pytree,
)

__all__ = [
    "MeshConfig",
    "make_mesh",
    "PlaneSpec",
    "plane_capability",
    "check_plane",
    "param_pspecs",
    "cache_pspecs",
    "data_pspecs",
    "shard_pytree",
    "make_sharded_step",
    "make_sharded_greedy_step",
    "make_sp_prefill_step",
    "make_pp_step",
]
