"""Device mesh construction.

Axis order is (dp, pp, sp, ep, tp) with tp innermost: on real slices JAX
device order makes the innermost axis span physically-adjacent chips, so
the highest-traffic collectives (tensor-parallel psum every layer) ride
the shortest ICI hops; pp's point-to-point activation hops and dp (lowest
traffic) span the slice/DCN dimension.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "sp", "ep", "tp")


@dataclass(frozen=True)
class MeshConfig:
    """Degrees per axis; product must equal the device count in use."""

    dp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    @property
    def shape(self) -> tuple:
        return (self.dp, self.pp, self.sp, self.ep, self.tp)

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.sp * self.ep * self.tp

    def describe(self) -> str:
        return "x".join(f"{a}{n}" for a, n in zip(AXES, self.shape) if n > 1) or "single"


def make_mesh(
    config: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the named mesh over `devices` (default: all local devices).

    Raises if the axis product doesn't match the device count — a silent
    partial mesh would leave chips idle, which on TPU is a provisioning
    bug, not a fallback.
    """
    devices = list(devices if devices is not None else jax.devices())
    if config.size != len(devices):
        raise ValueError(
            f"mesh {config.describe()} needs {config.size} devices, "
            f"have {len(devices)}"
        )
    arr = np.asarray(devices).reshape(config.shape)
    return Mesh(arr, AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    """1×1×1×1 mesh — lets the same sharded step run on one chip."""
    device = device or jax.devices()[0]
    return make_mesh(MeshConfig(), [device])
