"""Multi-host (multi-process) engine execution.

The reference treats multi-node serving as first-class — TRT-LLM srun
launchers (`components/backends/trtllm/multinode/srun_disaggregated.sh`),
SGLang SLURM jobs (`components/backends/sglang/slurm_jobs/`), and the
operator's LeaderWorkerSet annotations
(`deploy/cloud/operator/internal/dynamo/graph.go:145`).  There, multi-node
means "one engine (vLLM/TRT-LLM) spanning N ranks via NCCL/MPI".  The
TPU-native analog is one `EngineCore` spanning N JAX *processes* over a
global device mesh: `jax.distributed.initialize` joins the processes,
`jax.sharding.Mesh` spans every process's devices, and XLA collectives
ride ICI within a slice / DCN across slices.

Design — SPMD lockstep (the shadow engine):

  Every process builds an IDENTICAL `EngineCore` (same config, same seed,
  same params) over the same global mesh.  The *leader* (process 0) runs
  the real serving stack (control plane, RPC, scheduler); *followers* run
  a tiny command loop.  The leader broadcasts each engine-thread mutation
  — add_request / cancel / step / import_blocks / clear — over a TCP
  lockstep channel BEFORE executing it locally; followers replay the same
  calls in the same order.  Because the scheduler and allocator are
  deterministic pure-Python state machines, every process derives the
  same device program sequence, which is exactly SPMD's requirement.
  Host-visible results (sampled tokens) come off replicated device
  outputs, so followers never need a reverse channel.

  This mirrors how the reference's delegated engines work internally
  (vLLM MP executor broadcasts scheduler output to all ranks each step;
  TRT-LLM's orchestrator does the same over MPI) — but here it is OUR
  engine, so the broadcast seam is ours too.

Data movement rules under a multi-process mesh (enforced by helpers):
  * host → device: numpy inputs must become global arrays via
    `jax.make_array_from_callback` (each process serves its addressable
    shards from the same host bytes) — plain `jnp.asarray` commits to one
    process's devices and cannot enter a global computation.
  * device → host: only fully-replicated arrays can be read locally;
    anything else goes through `multihost_utils.process_allgather`,
    which is itself a collective every process must join (safe here:
    lockstep means every process reaches the same read).

CPU test rig: 2 processes x N virtual CPU devices
(`--xla_force_host_platform_device_count`) with gloo collectives —
the no-TPU fixture SURVEY §4 calls for, validated in
tests/test_multihost.py.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
from typing import Callable, Iterable, Optional

import msgpack
import numpy as np

from dynamo_tpu.runtime import contracts
from dynamo_tpu.runtime.contracts import never_engine_thread

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# process bootstrap


def setup_cpu_rig(devices_per_process: int) -> None:
    """Force this process onto `devices_per_process` virtual CPU devices
    with gloo cross-process collectives.  MUST run before any jax import
    in the process (worker mains call it first thing when
    --multihost-cpu-devices is given)."""
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count="
        f"{devices_per_process}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")


def initialize(coordinator: str, num_processes: int, process_id: int) -> None:
    """Join the jax.distributed cluster (the NCCL/MPI-rendezvous analog).
    After this, `jax.devices()` is the GLOBAL device list and meshes span
    every process."""
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    logger.info("multihost: process %d/%d joined via %s (%d global devices)",
                process_id, num_processes, coordinator,
                len(jax.devices()))


def mesh_spans_processes(mesh) -> bool:
    """True when the mesh's devices live in more than one process —
    the signal for every multihost-aware code path."""
    procs = {d.process_index for d in mesh.devices.flat}
    return len(procs) > 1


# ---------------------------------------------------------------------------
# host <-> device helpers


def to_global(x, sharding):
    """Host bytes (identical on every process) → global jax.Array."""
    import jax

    x = np.asarray(x)
    return jax.make_array_from_callback(
        x.shape, sharding, lambda idx, x=x: np.asarray(x[idx]))


def _needs_convert(x, sharding) -> bool:
    import jax

    if not isinstance(x, jax.Array):
        return True
    try:
        return x.sharding.device_set != sharding.device_set
    except Exception:
        return True


def wrap_global_inputs(fn: Callable, in_shardings) -> Callable:
    """Wrap a jitted fn so numpy / process-local args are converted to
    global arrays per the fn's in_shardings tree.  Arrays already on the
    global device set pass through when their sharding matches (donation
    still applies); a replicated prior output feeding a sharded slot is
    explicitly resharded (multiprocess jit refuses implicit resharding)."""
    import jax

    def leaf(a, s):
        if _needs_convert(a, s):
            return to_global(a, s)
        if a.sharding != s:
            return jax.device_put(a, s)
        return a

    def wrapped(*args):
        conv = tuple(jax.tree.map(leaf, arg, sh)
                     for arg, sh in zip(args, in_shardings))
        return fn(*conv)

    return wrapped


def fetch(arr) -> np.ndarray:
    """Device → host under any topology.  Fully-replicated (or
    single-process) arrays read locally; otherwise every process joins a
    process_allgather (lockstep guarantees they all reach this point)."""
    import jax

    if not isinstance(arr, jax.Array) or arr.is_fully_replicated:
        return np.asarray(arr)
    if len({d.process_index for d in arr.sharding.device_set}) <= 1:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


# ---------------------------------------------------------------------------
# lockstep command channel (leader → followers)

_FRAME = struct.Struct(">I")


class LockstepLeader:
    """TCP fan-out of engine commands to follower processes.  Commands are
    msgpack dicts; ordering per connection is the protocol's only
    guarantee (and the only one SPMD needs).  Sends happen on the engine
    thread — each frame is tiny (ids + token lists), so blocking socket
    writes are fine next to a multi-ms device step."""

    def __init__(self, port: int = 0, num_followers: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self.num_followers = num_followers
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()

    @never_engine_thread
    def wait_for_followers(self, timeout: float = 120.0) -> None:
        # Blocking accept loop — startup/bootstrap thread only; the
        # engine thread must never park here (broadcast() itself runs ON
        # the engine thread by design: tiny frames next to multi-ms
        # device steps).
        self._srv.settimeout(timeout)
        while len(self._conns) < self.num_followers:
            conn, addr = self._srv.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            logger.info("lockstep: follower %d/%d connected from %s",
                        len(self._conns), self.num_followers, addr)

    def broadcast(self, cmd: dict) -> None:
        blob = msgpack.packb(cmd, use_bin_type=True)
        frame = _FRAME.pack(len(blob)) + blob
        with self._lock:
            for c in self._conns:
                c.sendall(frame)

    def close(self) -> None:
        try:
            self.broadcast({"op": "stop"})
        except Exception as e:
            # Followers that never hear "stop" exit on socket close
            # below — but an operator debugging a hung rank needs this.
            logger.warning("lockstep stop broadcast failed (followers "
                           "fall back to socket-close exit): %s", e)
        for c in self._conns:
            try:
                c.close()
            except Exception:
                # dynamo-lint: disable=DL003 teardown: socket already dead
                pass
        self._srv.close()


class LockstepFollower:
    def __init__(self, host: str, port: int, timeout: float = 120.0):
        import time

        deadline = time.monotonic() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=5.0)
                break
            except OSError:
                # Leader may still be compiling/binding; followers retry
                # until the join deadline (srun ranks start unordered).
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._buf = b""

    def recv(self) -> dict:
        while len(self._buf) < _FRAME.size:
            self._more()
        (n,) = _FRAME.unpack(self._buf[:_FRAME.size])
        while len(self._buf) < _FRAME.size + n:
            self._more()
        blob = self._buf[_FRAME.size:_FRAME.size + n]
        self._buf = self._buf[_FRAME.size + n:]
        return msgpack.unpackb(blob, raw=False)

    def _more(self) -> None:
        chunk = self._sock.recv(1 << 16)
        if not chunk:
            raise ConnectionError("lockstep leader closed the channel")
        self._buf += chunk

    def close(self) -> None:
        self._sock.close()


# ---------------------------------------------------------------------------
# follower replay loop


def _decode_sampling(d: dict):
    from dynamo_tpu.engine.sampling import SamplingParams

    return SamplingParams(
        temperature=d["temperature"], top_k=d["top_k"], top_p=d["top_p"],
        max_tokens=d["max_tokens"],
        stop_token_ids=tuple(d["stop_token_ids"]),
        seed=d["seed"], logprobs=d["logprobs"],
        seed_offset=d.get("seed_offset", 0))


def encode_sampling(s) -> dict:
    return {"temperature": s.temperature, "top_k": s.top_k,
            "top_p": s.top_p, "max_tokens": s.max_tokens,
            "stop_token_ids": list(s.stop_token_ids), "seed": s.seed,
            "logprobs": s.logprobs,
            "seed_offset": getattr(s, "seed_offset", 0)}


def run_follower(core, chan: LockstepFollower,
                 stop_event: Optional[threading.Event] = None) -> None:
    """Replay the leader's engine-thread command stream on a shadow
    EngineCore until the leader stops.  Every device computation the
    leader launches, this process launches identically — that IS the
    multihost execution contract.  The replay thread registers as THIS
    process's engine thread (it drives core.step()/add_request — every
    @engine_thread_only pin lands on it, and @never_engine_thread
    functions refuse it, exactly like the leader's step loop)."""
    contracts.register_engine_thread()
    try:
        _follower_loop(core, chan, stop_event)
    finally:
        contracts.unregister_engine_thread()


def _follower_loop(core, chan: LockstepFollower,
                   stop_event: Optional[threading.Event]) -> None:
    while stop_event is None or not stop_event.is_set():
        cmd = chan.recv()
        op = cmd["op"]
        if op == "stop":
            logger.info("lockstep: leader closed; follower exiting")
            return
        elif op == "step":
            core.step()
        elif op == "add":
            try:
                core.add_request(cmd["rid"], cmd["prompt"],
                                 _decode_sampling(cmd["sampling"]),
                                 priority=cmd.get("priority", 1))
            except ValueError:
                logger.warning("follower: rejected add %s (mirrors "
                               "leader rejection)", cmd["rid"])
        elif op == "cancel":
            core.cancel(cmd["rid"])
        elif op == "import":
            blocks = {
                int(h): np.frombuffer(
                    raw, dtype=np.dtype(dt)).reshape(shape)
                for h, (raw, dt, shape) in cmd["blocks"].items()}
            core.import_blocks(blocks)
        elif op == "export":
            # Join the leader's extract computations (collective gathers
            # under a sharded cache); the host copy lands leader-side.
            core.export_blocks([int(h) for h in cmd["hashes"]])
        elif op == "clear":
            core.clear_prefix_cache()
        else:
            raise ValueError(f"unknown lockstep op {op!r}")


def encode_blocks(blocks: dict) -> dict:
    """numpy block dict → msgpack-able {hash: (bytes, dtype, shape)}."""
    return {str(h): (np.ascontiguousarray(a).tobytes(), str(a.dtype),
                     list(a.shape))
            for h, a in blocks.items()}
