"""Pipeline parallelism: stage-partitioned forward with microbatch rotation.

SURVEY §2.5 row "PP" (the reference configures PP in its delegated
engines for multinode runs, `trtllm/multinode/multinode-examples.md`;
here the engine is ours).  TPU-idiomatic design — a GPipe-style schedule
expressed entirely inside one `shard_map` over the `pp` mesh axis:

- layer stacks shard over pp: stage s owns layers [s·L/S, (s+1)·L/S) as
  STACKED arrays, applied with `lax.scan` (one compiled layer body per
  stage, not L/S unrolled copies);
- the KV cache for the pp path is the stacked [L, slots, F] layout
  sharded over pp on the layer axis — each stage holds exactly its
  layers' cache (int8 caches carry stacked [L, slots, Hkv] scale
  buffers sharded the same way — ISSUE 12 leg 2);
- activations + per-microbatch metadata rotate stage→stage+1 via
  `lax.ppermute` each tick; stage 0 injects fresh microbatch embeddings,
  the last stage runs the LM head and banks logits.  S + M − 1 ticks
  drain M microbatches through S stages; every stage executes identical
  code every tick (junk lanes masked at the end) so the schedule is
  branch-free and XLA-friendly.

The tick schedule is ONE shared body (`_pp_schedule`) that three
programs compile (ISSUE 12 leg 3 — the pp half of the r5 single-step
cliff):

- `make_pp_step` — the plain unified step ([B, V] logits out);
- `make_pp_greedy_step` — the ALL-IN-ONE stage program: schedule +
  on-device argmax fused into one donated-cache dispatch returning [B]
  tokens, so steady pp single-step decode costs 1 dispatch + 1 tiny
  host sync instead of 3 dispatches + a [B, V] f32 transfer;
- `make_pp_decode_window` — K schedule passes in one dispatch with
  on-device token feedback (llama.make_decode_window's contract), so pp
  decode rides the same pipelined window path as every other mesh.

v1 restrictions (validated): dense models (no MoE), pp exclusive of
tp/sp in this step (dp rides outside via engine replicas).  The unified
step contract matches `make_forward_step`, so tests compare logits AND
cache against the single-device oracle.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.jax_compat import shard_map


def stack_layer_params(params: Dict) -> Dict:
    """Convert the per-layer list-of-dicts into stacked arrays [L, ...]
    (scan-ready; the pp in_spec shards axis 0)."""
    layers = params["layers"]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    out = dict(params)
    out["layers"] = stacked
    return out


def init_pp_cache(cfg: kvc.KvCacheConfig) -> Dict:
    """Stacked cache for the pp step: {'k': [L, slots, F], 'v': ...} —
    per-layer 2D geometry matching kv_cache.init_cache, stacked on L.
    Quantized configs add stacked [L, slots, Hkv] f32 scale buffers
    (the sibling-buffer discipline of kv_cache.init_cache, stacked)."""
    shape = (cfg.num_layers, cfg.num_slots, cfg.feature_dim)
    cache = {"k": jnp.zeros(shape, cfg.store_dtype),
             "v": jnp.zeros(shape, cfg.store_dtype)}
    if cfg.quantized:
        sshape = (cfg.num_layers, cfg.num_slots, cfg.num_kv_heads)
        cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
        cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return cache


def pp_param_pspecs(cfg: ModelConfig) -> Dict:
    """Stacked-params pspecs: layer leaves shard axis 0 over pp; embed /
    norms / head replicated."""
    layer_leaf = P("pp")
    layers = {
        "attn": {"wq": layer_leaf, "wk": layer_leaf, "wv": layer_leaf,
                 "wo": layer_leaf},
        "attn_norm": layer_leaf,
        "mlp_norm": layer_leaf,
        "mlp": {"w_gate": layer_leaf, "w_up": layer_leaf,
                "w_down": layer_leaf},
    }
    specs = {"embed": P(None, None), "final_norm": P(None),
             "layers": layers}
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, None)
    return specs


def pp_cache_pspecs(kv_quant: bool = False) -> Dict:
    """Stacked-cache pspecs: each stage owns its layers' slice of pages
    AND (int8) of their scale buffers — scales never leave their stage."""
    spec = P("pp", None, None)
    out = {"k": spec, "v": spec}
    if kv_quant:
        out["k_scale"] = spec
        out["v_scale"] = spec
    return out


def make_pp_block_ops(block_size: int, mesh: Mesh, kv_quant: bool = False):
    """Whole-block extract/inject for the STACKED pp cache layout — the
    piece that lets pp serving run the tiered prefix cache (VERDICT r4
    next-10; the reference's block manager is universal,
    `block_manager.rs:90`).

    Same canonical block format as kv_cache.make_block_ops
    ([2, L, block_size, F]), so offload/onboard and the transfer planes
    are layout-agnostic: extract gathers the layer-sharded block off the
    pp axis (replicated out — host reads stay collective-free), inject
    scatters it back.

    Quantized caches (ISSUE 12 leg 2) move the SAME packed wire block as
    kv_cache.make_block_ops: [2, L, bs, F + 4·Hkv] int8 with the page's
    [bs, Hkv] f32 scales bitcast into the trailing bytes — so pp peers
    transfer to/from meshless, tp and dp peers byte-identically, and no
    path can ship pages without their scales.
    """
    cache_sh = jax.tree.map(lambda s: NamedSharding(mesh, s),
                            pp_cache_pspecs(kv_quant))
    rep = NamedSharding(mesh, P())

    def _slice(buf, start):
        return jax.lax.dynamic_slice_in_dim(buf, start, block_size, axis=1)

    def extract(cache: Dict, page) -> jnp.ndarray:
        start = page * block_size
        k = _slice(cache["k"], start)          # [L, bs, F]
        v = _slice(cache["v"], start)
        if not kvc.cache_is_quantized(cache):
            return jnp.stack([k, v])           # [2, L, bs, F]
        ks = _slice(cache["k_scale"], start)   # [L, bs, Hkv] f32
        vs = _slice(cache["v_scale"], start)

        def pack(q, s):
            # f32 [L, bs, Hkv] -> int8 [L, bs, Hkv, 4] -> [L, bs, 4*Hkv]
            sb = jax.lax.bitcast_convert_type(s, jnp.int8)
            sb = sb.reshape(s.shape[0], s.shape[1], -1)
            return jnp.concatenate([q, sb], axis=-1)

        return jnp.stack([pack(k, ks), pack(v, vs)])

    def inject(cache: Dict, page, data) -> Dict:
        start = page * block_size
        upd = jax.lax.dynamic_update_slice_in_dim
        if not kvc.cache_is_quantized(cache):
            data = data.astype(cache["k"].dtype)
            return {
                "k": upd(cache["k"], data[0], start, axis=1),
                "v": upd(cache["v"], data[1], start, axis=1),
            }
        F = cache["k"].shape[-1]
        H = cache["k_scale"].shape[-1]
        data = data.astype(jnp.int8)  # packed wire block (validated host-side)

        def unpack(d):  # [L, bs, F+4H] -> (int8 [L, bs, F], f32 [L, bs, H])
            q = d[..., :F]
            sb = d[..., F:].reshape(d.shape[0], d.shape[1], H, 4)
            return q, jax.lax.bitcast_convert_type(sb, jnp.float32)

        kq, ks = unpack(data[0])
        vq, vs = unpack(data[1])
        return {
            "k": upd(cache["k"], kq, start, axis=1),
            "v": upd(cache["v"], vq, start, axis=1),
            "k_scale": upd(cache["k_scale"], ks, start, axis=1),
            "v_scale": upd(cache["v_scale"], vs, start, axis=1),
        }

    ex = jax.jit(extract, in_shardings=(cache_sh, rep), out_shardings=rep)
    inj = jax.jit(inject, in_shardings=(cache_sh, rep, rep),
                  out_shardings=cache_sh, donate_argnums=(0,))
    return ex, inj


def _validate_pp(cfg: ModelConfig, mesh: Mesh) -> int:
    """Shared pp-plane validation; returns the stage count S."""
    cfg.validate()
    if cfg.is_moe:
        raise ValueError("pp v1 supports dense models only")
    if cfg.post_norms:
        raise ValueError("pp v1 does not wire Gemma-style post-norms")
    S = mesh.shape["pp"]
    if cfg.num_layers % S != 0:
        raise ValueError(f"pp={S} must divide num_layers={cfg.num_layers}")
    for axis in ("dp", "sp", "ep", "tp"):
        if mesh.shape[axis] != 1:
            # The shard_map specs mention only pp: any other populated
            # axis would silently replicate the whole stage compute —
            # wasted chips, which make_mesh treats as a provisioning bug.
            raise ValueError(
                f"pp v1 composes with no other axis in-mesh (got "
                f"{axis}={mesh.shape[axis]}); run dp via engine replicas")
    return S


def _pp_schedule(cfg: ModelConfig, block_size: int, S: int, M: int,
                 quant: bool):
    """ONE tick schedule body, shared by the plain step, the fused
    greedy step and the decode window (the refactor that makes fused pp
    decode a 10-line wrapper instead of a fork).

    Returns `step(params, cache, tokens, positions, seq_lens,
    block_tables, sample_positions) -> (logits, cache)`, traced INSIDE a
    shard_map over the pp axis.  `cache` is the stacked dict (with scale
    buffers when `quant`); one compiled tick body runs inside fori_loop —
    the schedule's length (S + M − 1 ticks) must not scale program
    size/compile time, so all per-tick variation (inject? bank?) is
    traced masking.
    """
    from dynamo_tpu.models.llama import _attention_block, _dense_mlp, rms_norm

    def step(params, cache, tokens, positions, seq_lens, block_tables,
             sample_positions):
        B, T = tokens.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        mb = B // M
        Pw = block_tables.shape[1]
        C = Pw * block_size
        stage = jax.lax.axis_index("pp")
        last_stage = S - 1
        layers = params["layers"]  # stacked, local shard [L/S, ...]
        caches = (cache["k"], cache["v"])  # [L/S, slots, F]
        if quant:
            caches += (cache["k_scale"], cache["v_scale"])

        def stage_compute(x, meta, caches, valid):
            """Run this stage's layers on one microbatch activation.
            `valid` (traced bool): whether this (stage, tick) holds a real
            microbatch — bubble ticks compute uniformly but their cache
            writes are redirected to the null block (slot 0), because the
            rotated-in metadata can point at REAL pages of a previous
            microbatch (the M=2 drain tick corrupted mb1's cache before
            this mask existed)."""
            positions_mb, seq_lens_mb, bt_mb = meta
            write_slots = kvc.slots_for_positions(
                bt_mb, positions_mb, block_size).reshape(mb * T)
            write_slots = jnp.where(valid, write_slots, 0)
            ctx_positions = jnp.broadcast_to(
                jnp.arange(C, dtype=jnp.int32), (mb, C))
            ctx_slots = kvc.slots_for_positions(bt_mb, ctx_positions,
                                                block_size)

            def layer_fn(x, scanned):
                if quant:
                    layer, k_l, v_l, ks_l, vs_l = scanned
                else:
                    layer, k_l, v_l = scanned
                    ks_l = vs_l = None
                attn_out, k_l, v_l, ks_l, vs_l = _attention_block(
                    cfg, layer["attn"],
                    rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps),
                    positions_mb, seq_lens_mb, write_slots, ctx_slots,
                    ctx_positions, bt_mb, block_size, k_l, v_l,
                    k_scale_cache=ks_l, v_scale_cache=vs_l)
                x = x + attn_out
                h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
                x = x + _dense_mlp(layer["mlp"], h)
                return x, ((k_l, v_l, ks_l, vs_l) if quant
                           else (k_l, v_l))

            x, new_caches = jax.lax.scan(layer_fn, x, (layers,) + caches)
            return x, new_caches

        def microbatch(i, arr):
            return jax.lax.dynamic_slice_in_dim(arr, i * mb, mb, axis=0)

        perm = [(i, (i + 1) % S) for i in range(S)]
        H = cfg.hidden_size
        head = params.get("lm_head")
        if head is None:
            head = params["embed"].T

        def tick(t, carry):
            x, meta, sample_mb, out, caches = carry

            # Stage 0 swaps in microbatch t's fresh embedding while any
            # remain; every stage computes the candidate uniformly and
            # `where`-selects — branch-free across stages and ticks.
            t_inj = jnp.minimum(t, M - 1)
            fresh_x = jnp.take(params["embed"], microbatch(t_inj, tokens),
                               axis=0)
            fresh_meta = (microbatch(t_inj, positions),
                          microbatch(t_inj, seq_lens),
                          microbatch(t_inj, block_tables))
            fresh_sample = microbatch(t_inj, sample_positions)
            inject = jnp.logical_and(stage == 0, t < M)
            x = jnp.where(inject, fresh_x, x)
            meta = tuple(jnp.where(inject, f, m)
                         for f, m in zip(fresh_meta, meta))
            sample_mb = jnp.where(inject, fresh_sample, sample_mb)

            valid = jnp.logical_and(t - stage >= 0, t - stage < M)
            x, caches = stage_compute(x, meta, caches, valid)

            # Last stage banks its finished microbatch's logits.
            idx = t - (S - 1)
            bank = jnp.logical_and(stage == last_stage, idx >= 0)
            idx_c = jnp.clip(idx, 0, M - 1)
            hfin = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
            hsel = jnp.take_along_axis(
                hfin, sample_mb[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            logits_mb = (hsel @ head).astype(jnp.float32)
            out = out.at[idx_c].set(
                jnp.where(bank, logits_mb, out[idx_c]))

            x = jax.lax.ppermute(x, "pp", perm)
            meta = tuple(jax.lax.ppermute(m, "pp", perm) for m in meta)
            sample_mb = jax.lax.ppermute(sample_mb, "pp", perm)
            return x, meta, sample_mb, out, caches

        carry = (
            jnp.zeros((mb, T, H), params["embed"].dtype),
            (jnp.zeros((mb, T), jnp.int32), jnp.zeros((mb,), jnp.int32),
             jnp.zeros((mb, Pw), jnp.int32)),
            jnp.zeros((mb,), jnp.int32),
            jnp.zeros((M, mb, cfg.vocab_size), jnp.float32),
            caches,
        )
        _, _, _, out, caches = jax.lax.fori_loop(
            0, S + M - 1, tick, carry)

        # Only the last stage wrote non-zero logits: psum replicates them.
        logits = jax.lax.psum(out, "pp").reshape(M * mb, cfg.vocab_size)
        new_cache = {"k": caches[0], "v": caches[1]}
        if quant:
            new_cache["k_scale"] = caches[2]
            new_cache["v_scale"] = caches[3]
        return logits, new_cache

    return step


def _pp_in_specs(cfg: ModelConfig, kv_quant: bool) -> Tuple:
    """in_specs shared by every pp step variant: stacked params + cache,
    replicated batch inputs."""
    return (pp_param_pspecs(cfg), pp_cache_pspecs(kv_quant),
            P(None, None), P(None, None), P(None), P(None, None),
            P(None))


def make_pp_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                 n_microbatches: int, kv_quant: bool = False):
    """Jit the pipeline-parallel unified step.

    Returns `step(params_stacked, cache, tokens, positions, seq_lens,
    block_tables, sample_positions) -> (logits, cache)` — the regular
    step contract; tokens [B, T] with B divisible by n_microbatches.
    Build inputs with `stack_layer_params` / `init_pp_cache`.
    """
    S = _validate_pp(cfg, mesh)
    body = _pp_schedule(cfg, block_size, S, n_microbatches, kv_quant)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=_pp_in_specs(cfg, kv_quant),
        out_specs=(P(None, None), pp_cache_pspecs(kv_quant)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(1,))


def make_pp_greedy_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                        n_microbatches: int, kv_quant: bool = False):
    """Jit the FUSED greedy pp single step — the all-in-one stage
    program (ISSUE 12 leg 3): schedule + on-device argmax compile into
    ONE donated-cache dispatch returning [B] int32 tokens.  The unfused
    pp decode loop was a schedule dispatch returning [B, V] f32 logits
    plus host-side argmax per token — the pp half of the r5 single-step
    cliff; here steady pp decode costs 1 dispatch + 1 tiny host sync
    (counters pinned in tests/test_compose_matrix.py).

    Same signature as the meshless `EngineCore._greedy_step_fn`:
    `fused(params, cache, tokens[B,1], positions[B,1], seq_lens[B],
    block_tables[B,P], sample_positions[B]) -> (tokens[B], cache)`.
    """
    S = _validate_pp(cfg, mesh)
    body = _pp_schedule(cfg, block_size, S, n_microbatches, kv_quant)

    def fused(params, cache, tokens, positions, seq_lens, block_tables,
              sample_positions):
        logits, cache = body(params, cache, tokens, positions, seq_lens,
                             block_tables, sample_positions)
        return jnp.argmax(logits, -1).astype(jnp.int32), cache

    sharded = shard_map(
        fused,
        mesh=mesh,
        in_specs=_pp_in_specs(cfg, kv_quant),
        out_specs=(P(None), pp_cache_pspecs(kv_quant)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(1,))


def make_pp_decode_window(cfg: ModelConfig, block_size: int, mesh: Mesh,
                          n_microbatches: int, window: int,
                          greedy_only: bool = False,
                          kv_quant: bool = False):
    """Jit the fused K-token decode window OVER the pipeline schedule:
    K schedule passes run inside one `lax.fori_loop` with sampled tokens
    fed back on device — llama.make_decode_window's exact run()
    contract, so the engine's pipelined window path (device-resident row
    state, async token fetch) serves pp meshes unchanged.

    Sampling runs replicated inside the shard_map (logits are psum'd
    across stages), so every stage derives identical tokens — the same
    argument that makes the schedule SPMD-safe makes the window so.
    """
    from dynamo_tpu.engine.sampling import sample

    S = _validate_pp(cfg, mesh)
    body = _pp_schedule(cfg, block_size, S, n_microbatches, kv_quant)

    def run(params, cache, last_tokens, positions0, seq_lens0,
            block_tables, temp, top_k, top_p, base_key_data, key_offsets):
        B = last_tokens.shape[0]
        zero_pos = jnp.zeros((B,), jnp.int32)
        base_keys = (None if greedy_only
                     else jax.random.wrap_key_data(base_key_data))
        # Padding rows (seq_lens0 == 0) stay dead across device-side
        # advances — same discipline as make_decode_window.
        live = seq_lens0 > 0

        def wbody(i, carry):
            cache, toks, out = carry
            adv = jnp.where(live, i, 0)
            logits, cache = body(
                params, cache, toks[:, None],
                (positions0 + adv)[:, None], seq_lens0 + adv,
                block_tables, zero_pos)
            if greedy_only:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                keys = jax.vmap(jax.random.fold_in)(base_keys,
                                                    key_offsets + i)
                nxt = sample(logits, temp, top_k, top_p, keys)
            return cache, nxt, out.at[i].set(nxt)

        out0 = jnp.zeros((window, B), jnp.int32)
        cache, _, out = jax.lax.fori_loop(
            0, window, wbody, (cache, last_tokens, out0))
        adv = jnp.where(live, window, 0)
        return (cache, out, positions0 + adv, seq_lens0 + adv,
                key_offsets + window)

    rep = P(None)
    sharded = shard_map(
        run,
        mesh=mesh,
        in_specs=(pp_param_pspecs(cfg), pp_cache_pspecs(kv_quant),
                  rep, rep, rep, P(None, None), rep, rep, rep,
                  P(None, None), rep),
        out_specs=(pp_cache_pspecs(kv_quant), P(None, None), rep, rep,
                   rep),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(1,))
