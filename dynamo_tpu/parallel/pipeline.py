"""Pipeline parallelism: stage-partitioned forward with microbatch rotation.

SURVEY §2.5 row "PP" (the reference configures PP in its delegated
engines for multinode runs, `trtllm/multinode/multinode-examples.md`;
here the engine is ours).  TPU-idiomatic design — a GPipe-style schedule
expressed entirely inside one `shard_map` over the `pp` mesh axis:

- layer stacks shard over pp: stage s owns layers [s·L/S, (s+1)·L/S) as
  STACKED arrays, applied with `lax.scan` (one compiled layer body per
  stage, not L/S unrolled copies);
- the KV cache for the pp path is the stacked [L, slots, Hkv, D] layout
  sharded over pp on the layer axis — each stage holds exactly its
  layers' cache;
- activations + per-microbatch metadata rotate stage→stage+1 via
  `lax.ppermute` each tick; stage 0 injects fresh microbatch embeddings,
  the last stage runs the LM head and banks logits.  S + M − 1 ticks
  drain M microbatches through S stages; every stage executes identical
  code every tick (junk lanes masked at the end) so the schedule is
  branch-free and XLA-friendly.

v1 restrictions (validated): dense models (no MoE), pp exclusive of
tp/sp in this step (dp rides outside via engine replicas).  The unified
step contract matches `make_forward_step`, so tests compare logits AND
cache against the single-device oracle.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.jax_compat import shard_map


def stack_layer_params(params: Dict) -> Dict:
    """Convert the per-layer list-of-dicts into stacked arrays [L, ...]
    (scan-ready; the pp in_spec shards axis 0)."""
    layers = params["layers"]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    out = dict(params)
    out["layers"] = stacked
    return out


def init_pp_cache(cfg: kvc.KvCacheConfig) -> Dict:
    """Stacked cache for the pp step: {'k': [L, slots, F], 'v': ...} —
    per-layer 2D geometry matching kv_cache.init_cache, stacked on L."""
    shape = (cfg.num_layers, cfg.num_slots, cfg.feature_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def pp_param_pspecs(cfg: ModelConfig) -> Dict:
    """Stacked-params pspecs: layer leaves shard axis 0 over pp; embed /
    norms / head replicated."""
    layer_leaf = P("pp")
    layers = {
        "attn": {"wq": layer_leaf, "wk": layer_leaf, "wv": layer_leaf,
                 "wo": layer_leaf},
        "attn_norm": layer_leaf,
        "mlp_norm": layer_leaf,
        "mlp": {"w_gate": layer_leaf, "w_up": layer_leaf,
                "w_down": layer_leaf},
    }
    specs = {"embed": P(None, None), "final_norm": P(None),
             "layers": layers}
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, None)
    return specs


def pp_cache_pspecs() -> Dict:
    spec = P("pp", None, None)
    return {"k": spec, "v": spec}


def make_pp_block_ops(block_size: int, mesh: Mesh):
    """Whole-block extract/inject for the STACKED pp cache layout — the
    piece that lets pp serving run the tiered prefix cache (VERDICT r4
    next-10: pp v1 was mutually exclusive with the KVBM; the reference's
    block manager is universal, `block_manager.rs:90`).

    Same canonical block format as kv_cache.make_block_ops
    ([2, L, block_size, F]), so offload/onboard and the transfer planes
    are layout-agnostic: extract gathers the layer-sharded block off the
    pp axis (replicated out — host reads stay collective-free), inject
    scatters it back.
    """
    from jax.sharding import NamedSharding

    cache_sh = jax.tree.map(lambda s: NamedSharding(mesh, s),
                            pp_cache_pspecs())
    rep = NamedSharding(mesh, P())

    def extract(cache: Dict, page) -> jnp.ndarray:
        start = page * block_size
        k = jax.lax.dynamic_slice_in_dim(cache["k"], start, block_size,
                                         axis=1)
        v = jax.lax.dynamic_slice_in_dim(cache["v"], start, block_size,
                                         axis=1)
        return jnp.stack([k, v])            # [2, L, block_size, F]

    def inject(cache: Dict, page, data) -> Dict:
        start = page * block_size
        data = data.astype(cache["k"].dtype)
        return {
            "k": jax.lax.dynamic_update_slice_in_dim(
                cache["k"], data[0], start, axis=1),
            "v": jax.lax.dynamic_update_slice_in_dim(
                cache["v"], data[1], start, axis=1),
        }

    ex = jax.jit(extract, in_shardings=(cache_sh, rep), out_shardings=rep)
    inj = jax.jit(inject, in_shardings=(cache_sh, rep, rep),
                  out_shardings=cache_sh, donate_argnums=(0,))
    return ex, inj


def make_pp_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                 n_microbatches: int):
    """Jit the pipeline-parallel unified step.

    Returns `step(params_stacked, cache, tokens, positions, seq_lens,
    block_tables, sample_positions) -> (logits, cache)` — the regular
    step contract; tokens [B, T] with B divisible by n_microbatches.
    Build inputs with `stack_layer_params` / `init_pp_cache`.
    """
    from dynamo_tpu.models.llama import _attention_block, _dense_mlp, rms_norm

    cfg.validate()
    if cfg.is_moe:
        raise ValueError("pp v1 supports dense models only")
    if cfg.post_norms:
        raise ValueError("pp v1 does not wire Gemma-style post-norms")
    S = mesh.shape["pp"]
    if cfg.num_layers % S != 0:
        raise ValueError(f"pp={S} must divide num_layers={cfg.num_layers}")
    for axis in ("dp", "sp", "ep", "tp"):
        if mesh.shape[axis] != 1:
            # The shard_map specs mention only pp: any other populated
            # axis would silently replicate the whole stage compute —
            # wasted chips, which make_mesh treats as a provisioning bug.
            raise ValueError(
                f"pp v1 composes with no other axis in-mesh (got "
                f"{axis}={mesh.shape[axis]}); run dp via engine replicas")
    M = n_microbatches

    def body(params, cache, tokens, positions, seq_lens, block_tables,
             sample_positions):
        B, T = tokens.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        mb = B // M
        Pw = block_tables.shape[1]
        C = Pw * block_size
        stage = jax.lax.axis_index("pp")
        last_stage = S - 1
        layers = params["layers"]  # stacked, local shard [L/S, ...]
        k_cache, v_cache = cache["k"], cache["v"]  # [L/S, slots, F]

        def stage_compute(x, meta, k_cache, v_cache, valid):
            """Run this stage's layers on one microbatch activation.
            `valid` (traced bool): whether this (stage, tick) holds a real
            microbatch — bubble ticks compute uniformly but their cache
            writes are redirected to the null block (slot 0), because the
            rotated-in metadata can point at REAL pages of a previous
            microbatch (the M=2 drain tick corrupted mb1's cache before
            this mask existed)."""
            positions_mb, seq_lens_mb, bt_mb = meta
            write_slots = kvc.slots_for_positions(
                bt_mb, positions_mb, block_size).reshape(mb * T)
            write_slots = jnp.where(valid, write_slots, 0)
            ctx_positions = jnp.broadcast_to(
                jnp.arange(C, dtype=jnp.int32), (mb, C))
            ctx_slots = kvc.slots_for_positions(bt_mb, ctx_positions,
                                                block_size)

            def layer_fn(x, scanned):
                layer, k_l, v_l = scanned
                # (kv_quant is meshless-only; the trailing scale slots
                # are always None on the pp path.)
                attn_out, k_l, v_l, _, _ = _attention_block(
                    cfg, layer["attn"],
                    rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps),
                    positions_mb, seq_lens_mb, write_slots, ctx_slots,
                    ctx_positions, bt_mb, block_size, k_l, v_l)
                x = x + attn_out
                h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
                x = x + _dense_mlp(layer["mlp"], h)
                return x, (k_l, v_l)

            x, (k_new, v_new) = jax.lax.scan(
                layer_fn, x, (layers, k_cache, v_cache))
            return x, k_new, v_new

        def microbatch(i, arr):
            return jax.lax.dynamic_slice_in_dim(arr, i * mb, mb, axis=0)

        perm = [(i, (i + 1) % S) for i in range(S)]
        H = cfg.hidden_size
        head = params.get("lm_head")
        if head is None:
            head = params["embed"].T

        # One compiled tick body inside fori_loop — the schedule's length
        # (S + M − 1 ticks) must not scale program size/compile time.
        # All per-tick variation (inject? bank?) is traced masking.
        def tick(t, carry):
            x, meta, sample_mb, out, k_cache, v_cache = carry

            # Stage 0 swaps in microbatch t's fresh embedding while any
            # remain; every stage computes the candidate uniformly and
            # `where`-selects — branch-free across stages and ticks.
            t_inj = jnp.minimum(t, M - 1)
            fresh_x = jnp.take(params["embed"], microbatch(t_inj, tokens),
                               axis=0)
            fresh_meta = (microbatch(t_inj, positions),
                          microbatch(t_inj, seq_lens),
                          microbatch(t_inj, block_tables))
            fresh_sample = microbatch(t_inj, sample_positions)
            inject = jnp.logical_and(stage == 0, t < M)
            x = jnp.where(inject, fresh_x, x)
            meta = tuple(jnp.where(inject, f, m)
                         for f, m in zip(fresh_meta, meta))
            sample_mb = jnp.where(inject, fresh_sample, sample_mb)

            valid = jnp.logical_and(t - stage >= 0, t - stage < M)
            x, k_cache, v_cache = stage_compute(x, meta, k_cache, v_cache,
                                                valid)

            # Last stage banks its finished microbatch's logits.
            idx = t - (S - 1)
            bank = jnp.logical_and(stage == last_stage, idx >= 0)
            idx_c = jnp.clip(idx, 0, M - 1)
            hfin = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
            hsel = jnp.take_along_axis(
                hfin, sample_mb[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            logits_mb = (hsel @ head).astype(jnp.float32)
            out = out.at[idx_c].set(
                jnp.where(bank, logits_mb, out[idx_c]))

            x = jax.lax.ppermute(x, "pp", perm)
            meta = tuple(jax.lax.ppermute(m, "pp", perm) for m in meta)
            sample_mb = jax.lax.ppermute(sample_mb, "pp", perm)
            return x, meta, sample_mb, out, k_cache, v_cache

        carry = (
            jnp.zeros((mb, T, H), params["embed"].dtype),
            (jnp.zeros((mb, T), jnp.int32), jnp.zeros((mb,), jnp.int32),
             jnp.zeros((mb, Pw), jnp.int32)),
            jnp.zeros((mb,), jnp.int32),
            jnp.zeros((M, mb, cfg.vocab_size), jnp.float32),
            k_cache, v_cache,
        )
        _, _, _, out, k_cache, v_cache = jax.lax.fori_loop(
            0, S + M - 1, tick, carry)

        # Only the last stage wrote non-zero logits: psum replicates them.
        logits = jax.lax.psum(out, "pp").reshape(M * mb, cfg.vocab_size)
        return logits, {"k": k_cache, "v": v_cache}

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(pp_param_pspecs(cfg), pp_cache_pspecs(),
                  P(None, None), P(None, None), P(None), P(None, None),
                  P(None)),
        out_specs=(P(None, None), pp_cache_pspecs()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(1,))
