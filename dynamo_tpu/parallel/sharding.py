"""GSPMD sharding rules for the Llama-family engine.

Megatron-style tensor parallelism expressed as PartitionSpecs; XLA inserts
the collectives (reference counterpart: NCCL inside vLLM — SURVEY.md §2.6
"Collectives (in-engine)"):

- attention: wq/wk/wv column-parallel (heads over tp), wo row-parallel
  (psum on exit); the KV cache shards its head axis over tp so cache
  reads/writes stay device-local.
- MLP: w_gate/w_up column-parallel, w_down row-parallel.
- MoE: expert dimension over ep, each expert's MLP additionally tp-sharded.
- embedding / lm_head: vocab-sharded over tp (logit psum/all-gather at the
  end of the step).
- activations/batch: sharded over dp.

GQA note: `num_kv_heads` (8 for Llama-3) bounds head-sharded tp for the
cache; tp degrees beyond that would need head replication — rejected here
rather than silently replicated.
"""

from __future__ import annotations

from dataclasses import dataclass, replace as _dc_replace
from typing import Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig

Params = Dict


# ---------------------------------------------------------------------------
# Declarative plane spec + capability table (ISSUE 12 tentpole)


@dataclass(frozen=True)
class PlaneSpec:
    """Declarative spec of one compiled serving plane.

    The per-combo `make_sharded_{step,window,greedy,embed,mm}_step`
    family collapsed into ONE `make_sharded_step(cfg, block, mesh,
    plane)` builder parameterized by this spec — the feature-composition
    matrix is now a value, not a code grid:

    - `quant`: int8 KV cache — the cache pytree carries sibling
      `[S, Hkv]` f32 scale buffers that shard with their kv heads
      (or slots, under dp_attention) and every attention body
      dequantizes shard-locally (ring hops included).
    - `spec`: speculative verify chunks (T = K+1 decode with
      all-positions logits) will ride this engine's step.
    - `fused`: on-device argmax fused into the program — [B] int32
      tokens out instead of [B, V] f32 logits (the single-step-cliff
      killer).
    - `window`: fused K-token decode window (0/1 = single-step program).
    - `greedy_only`: argmax-only window variant (no sort, no keys).
    - `use_pallas`: route decode attention through the Pallas paged
      kernel inside shard_map.
    - `dp_attention` / `dp_local`: batch-sharded attention with
      slot-sharded KV, optionally with page locality.
    - `moe`: the model has expert layers.  MoE composes with the decode
      window, the fused greedy step, int8 KV and packed prefill (ISSUE
      17 killed those exclusions); the genuinely-impossible combos
      (moe × pp stacked layout, moe × ring-SP) are declared in
      `plane_capability`, not hand-gated in the engine.
    - `role`: "decode" (the unified step family), "embed"
      (return_hidden), "mm" (input-embeds prefill), "sp_prefill"
      (ring-SP whole-prompt prefill).
    """

    quant: bool = False
    spec: bool = False
    fused: bool = False
    window: int = 0
    greedy_only: bool = False
    use_pallas: bool = False
    dp_attention: bool = False
    dp_local: bool = False
    moe: bool = False
    role: str = "decode"


@dataclass(frozen=True)
class Capability:
    ok: bool
    reason: Optional[str] = None


def plane_capability(mesh: Optional[Mesh], plane: PlaneSpec,
                     multihost: Optional[bool] = None) -> Capability:
    """THE capability table: every genuinely-impossible (feature x mesh)
    combination is declared HERE, with the pointed error serving code
    raises — the engine's gating, the README matrix Notes, and the
    composition grid test all read this one function instead of
    hand-maintained combo lists.  `mesh=None` is the meshless engine;
    `multihost` overrides process-span detection so tests can query
    lockstep combos without building a multi-process mesh."""
    pp = mesh is not None and mesh.shape.get("pp", 1) > 1
    if multihost is None:
        from dynamo_tpu.parallel.multihost import mesh_spans_processes

        multihost = mesh is not None and mesh_spans_processes(mesh)

    def no(reason: str) -> Capability:
        return Capability(False, reason)

    if plane.dp_local and not plane.dp_attention:
        return no("dp_local implies dp_attention")
    if (plane.dp_attention or plane.dp_local) and mesh is None:
        return no("dp_attention needs a mesh")
    if plane.use_pallas and plane.dp_attention and not plane.dp_local:
        return no(
            "pallas decode under dp_attention needs page locality "
            "(dp_attention_local=True): without it a row's pages may "
            "live on any shard and the kernel's slot indexing cannot "
            "cross chips — set dp_attention_local (plain allocator) or "
            "drop use_pallas_decode for the gather path")
    if plane.use_pallas and pp:
        return no(
            "pallas paged decode is not wired into the pp stage scan "
            "(the schedule attends gathered context inside each stage); "
            "drop use_pallas_decode (auto keeps pp on the gather path) "
            "or --pp")
    if plane.use_pallas and multihost:
        return no(
            "pallas paged decode under a multi-process mesh is not "
            "audited for the lockstep stream (shard_map custom calls "
            "across processes are unvalidated); drop use_pallas_decode "
            "— auto keeps multihost on the gather path")
    if pp and multihost:
        return no("pipeline parallelism under a multi-process mesh is "
                  "not wired yet (multihost v2 covers tp/dp/dp-attention "
                  "with int8 and fused steps)")
    if plane.moe:
        if pp:
            return no(
                "MoE on the pp engine is declared impossible: the stage "
                "scan stacks per-stage layer weights into one batched "
                "pytree and its body has no expert branch (router / "
                "grouped / dispatch all need per-layer expert weights); "
                "serve MoE models on a tp/ep/dp mesh or drop --pp")
        if plane.role == "sp_prefill":
            return no(
                "ring-SP prefill is declared impossible for MoE: the sp "
                "step shards the TOKEN axis around the ICI ring while "
                "expert dispatch shards tokens over dp×ep — the two "
                "chunkings conflict; MoE prefill rides the padded or "
                "packed plane")
    if plane.spec:
        if pp:
            return no(
                "speculative decode on the pp engine is declared "
                "impossible: the stage program banks ONE sampled row "
                "per microbatch, and the T=K+1 verify chunk needs "
                "all-positions logits; drop --spec-decode or --pp")
        if multihost:
            return no(
                "speculative decode under a multi-process mesh is "
                "loudly versioned out of the audited lockstep stream "
                "(the host-side verify jit carries no multihost "
                "shardings); drop --spec-decode or run single-process")
    if plane.role == "embed":
        if pp:
            return no("embeddings are not wired for the pp engine "
                      "(pipeline stages have no return_hidden path)")
        if multihost:
            return no("embeddings are not wired for multihost (the "
                      "embed route isn't in the lockstep command "
                      "stream)")
    if plane.role == "mm":
        if pp:
            return no("prompt_embeds (multimodal) on the pp engine is "
                      "not wired (stage step has no input-embeds "
                      "variant)")
        if multihost:
            return no("prompt_embeds (multimodal) under a multi-process "
                      "mesh is not in the lockstep command stream yet")
    if plane.role == "sp_prefill" and plane.dp_attention:
        return no("ring-SP prefill is not wired for dp_attention (the "
                  "sp step's cache specs conflict with slot sharding)")
    return Capability(True)


def check_plane(mesh: Optional[Mesh], plane: PlaneSpec,
                multihost: Optional[bool] = None) -> None:
    """Raise the capability table's pointed error for impossible combos."""
    cap = plane_capability(mesh, plane, multihost)
    if not cap.ok:
        raise ValueError(cap.reason)


def param_pspecs(cfg: ModelConfig, moe_mode: str = "dense",
                 dp_attention: bool = False) -> Params:
    """PartitionSpec pytree matching `llama.init_params` structure.

    MoE weights: dense mode shards each expert's MLP over tp too (the
    dense einsums partition fine under GSPMD); dispatch mode shards the
    expert dim over ep AND each expert's intermediate dim over tp (the
    shard_map body computes a partial down projection per tp member and
    psums — ops/moe.py `_dispatch_one_shard` tp_axis) and replicates the
    router (every shard routes its own tokens).

    `dp_attention` (reference: sglang --enable-dp-attention,
    `disagg_dp_attn.sh:33-37`): attention runs data-parallel over the
    batch with REPLICATED attention weights while MLPs stay
    tensor-parallel — the mode for models whose kv-head count is below
    the tp degree (head-sharded KV would cap tp or duplicate KV)."""
    if dp_attention:
        attn = {
            "wq": P(None, None),
            "wk": P(None, None),
            "wv": P(None, None),
            "wo": P(None, None),
        }
    else:
        attn = {
            "wq": P(None, "tp"),
            "wk": P(None, "tp"),
            "wv": P(None, "tp"),
            "wo": P("tp", None),
        }
    layer = {
        "attn": attn,
        "attn_norm": P(None),
        "mlp_norm": P(None),
    }
    if cfg.post_norms:
        layer["post_attn_norm"] = P(None)
        layer["post_mlp_norm"] = P(None)
    if cfg.is_moe:
        if moe_mode == "dispatch":
            layer["moe"] = {
                "router": P(None, None),
                "w_gate": P("ep", None, "tp"),
                "w_up": P("ep", None, "tp"),
                "w_down": P("ep", "tp", None),
            }
        else:
            layer["moe"] = {
                "router": P(None, "ep"),
                "w_gate": P("ep", None, "tp"),
                "w_up": P("ep", None, "tp"),
                "w_down": P("ep", "tp", None),
            }
    else:
        layer["mlp"] = {
            "w_gate": P(None, "tp"),
            "w_up": P(None, "tp"),
            "w_down": P("tp", None),
        }
    specs: Params = {
        "embed": P("tp", None),
        "final_norm": P(None),
        "layers": [layer] * cfg.num_layers,
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def cache_pspecs(num_layers: int, dp_attention: bool = False,
                 dp_local: bool = False, kv_quant: bool = False) -> Dict:
    """KV cache: per-layer [slots, F = kv_heads * head_dim] buffers; the
    flat feature axis shards over tp, which IS head sharding (F is
    head-major and validate() enforces tp | num_kv_heads).

    The slot axis is deliberately *not* dp-sharded: each dp replica runs its
    own engine process with its own cache (serving-style DP, reference
    PushRouter replicas), so within one process the cache only shards over
    tp.

    `dp_attention`: the SLOT axis shards over tp instead of heads — total
    KV memory still splits tp-ways, but head count no longer caps tp.
    GSPMD resolves page→device movement with collectives.

    `dp_local` (implies dp_attention): slots shard over the FLAT (dp, tp)
    device grid and the engine's locality-aware allocator guarantees a
    row's pages live on that row's device — decode attention then runs
    fully device-local under shard_map (llama._attention_block dp-local
    branch), no cross-chip gathers per step (VERDICT r3 weak #4).

    `kv_quant` (ISSUE 9): the int8 cache's sibling per-layer [S, Hkv] f32
    scale buffers SHARD WITH THEIR KV HEADS — head-sharded tp splits the
    Hkv axis exactly as the F axis splits (F is head-major and tp | Hkv),
    so every shard dequantizes its own heads with locally-resident
    scales; slot-sharded modes (dp_attention / dp_local) shard the scale
    slot axis like the page slot axis.  Scales are never replicated:
    a replicated [S, Hkv] f32 buffer would cost more HBM per chip than
    the int8 quantization saves at small head_dim."""
    if dp_local:
        spec = P(("dp", "tp"), None)
        sspec = P(("dp", "tp"), None)
    elif dp_attention:
        spec = P("tp", None)
        sspec = P("tp", None)
    else:
        spec = P(None, "tp")
        sspec = P(None, "tp")   # Hkv axis: scales ride their heads
    out = {"k": [spec] * num_layers, "v": [spec] * num_layers}
    if kv_quant:
        out["k_scale"] = [sspec] * num_layers
        out["v_scale"] = [sspec] * num_layers
    return out


def data_pspecs() -> Dict:
    """Per-step input batch: batch dim over dp."""
    return {
        "tokens": P("dp", None),
        "positions": P("dp", None),
        "seq_lens": P("dp"),
        "block_tables": P("dp", None),
    }


def validate(cfg: ModelConfig, mesh: Mesh,
             dp_attention: bool = False) -> None:
    tp = mesh.shape["tp"]
    ep = mesh.shape["ep"]
    if not dp_attention and cfg.num_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_kv_heads={cfg.num_kv_heads} "
            "(head-sharded KV cache; use dp_attention for tp beyond the "
            "kv-head count)"
        )
    if cfg.intermediate_size % tp:
        raise ValueError(f"tp={tp} must divide intermediate={cfg.intermediate_size}")
    if cfg.vocab_size % tp:
        raise ValueError(f"tp={tp} must divide vocab={cfg.vocab_size}")
    if cfg.is_moe and cfg.num_experts % ep:
        raise ValueError(f"ep={ep} must divide num_experts={cfg.num_experts}")
    if not cfg.is_moe and ep > 1:
        raise ValueError("ep > 1 on a dense model wastes chips; use tp/dp")


def shard_pytree(tree, pspecs, mesh: Mesh):
    """Place a pytree on the mesh according to a matching pspec pytree.

    Under a multi-process mesh, host leaves become GLOBAL arrays via
    make_array_from_callback (each process serves its addressable shards
    from identical host bytes — plain device_put would commit to one
    process's devices)."""
    from dynamo_tpu.parallel.multihost import mesh_spans_processes, to_global

    if mesh_spans_processes(mesh):
        import numpy as _np

        return jax.tree.map(
            lambda x, s: to_global(_np.asarray(x), NamedSharding(mesh, s)),
            tree, pspecs)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, pspecs
    )


def _finalize(fn, in_shardings, mesh: Mesh):
    """Multihost-aware jit wrapper: when the mesh spans processes, host
    (numpy / process-local) inputs are converted to global arrays per the
    in_shardings tree before the call; single-process meshes return the
    jit untouched (zero overhead on the tuned serving path)."""
    from dynamo_tpu.parallel.multihost import (
        mesh_spans_processes, wrap_global_inputs)

    if mesh_spans_processes(mesh):
        return wrap_global_inputs(fn, in_shardings)
    return fn


def resolve_moe_mode(cfg: ModelConfig, mesh: Optional[Mesh],
                     moe_mode: str = "auto") -> str:
    """The MoE mode ladder: dense | grouped | dispatch.

    - "dense": exact dense compute, every expert over every token with
      zero gates — the oracle, and the GSPMD fallback (tp shards the
      expert einsums fine).  E/k× the minimal FLOPs and weight bytes.
    - "grouped": the MESHLESS fast path — tokens sorted by expert on
      device, one ragged grouped GEMM streams each active expert's
      weights HBM→VMEM once (ops/pallas/moe_grouped.py).
    - "dispatch": all-to-all token dispatch over the mesh's ep axis;
      ep × tp meshes additionally tp-shard each expert's MLP on the
      intermediate dim (psum on exit — ops/moe.py tp_axis), so tp > 1
      no longer blocks dispatch.

    'auto': meshless → "grouped" when the backend is TPU and the expert
    geometry passes `moe_grouped_geometry_ok`, else "dense"; sharded →
    "dispatch" when an ep axis > 1 exists, else "dense"."""
    if not cfg.is_moe:
        return "dense"
    valid = ("auto", "dense", "grouped", "dispatch")
    if moe_mode not in valid:
        raise ValueError(f"moe_mode={moe_mode!r} not in {valid}")
    if mesh is None:
        if moe_mode == "dispatch":
            raise ValueError(
                "moe_mode='dispatch' needs a mesh with an ep axis (the "
                "all-to-all is an ep collective); meshless engines use "
                "'grouped' (TPU fast path) or 'dense'")
        if moe_mode == "auto":
            from dynamo_tpu.ops.pallas import moe_grouped_geometry_ok

            ok = (jax.default_backend() == "tpu"
                  and moe_grouped_geometry_ok(
                      cfg.hidden_size, cfg.intermediate_size,
                      jax.numpy.dtype(cfg.dtype).itemsize))
            return "grouped" if ok else "dense"
        return moe_mode
    if moe_mode == "grouped":
        raise ValueError(
            "moe_mode='grouped' is the meshless fast path (the Pallas "
            "grouped GEMM runs whole experts per chip); sharded meshes "
            "use 'dispatch' (ep all-to-all, tp-sharded expert MLPs) or "
            "'dense' (GSPMD einsums)")
    if moe_mode == "auto":
        return "dispatch" if mesh.shape["ep"] > 1 else "dense"
    return moe_mode


def make_sharded_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                      plane: Optional[PlaneSpec] = None,
                      with_expert_load: bool = False, *,
                      moe_mode: str = "auto",
                      dp_attention: bool = False,
                      use_pallas_decode: bool = False,
                      dp_local: bool = False,
                      kv_quant: bool = False,
                      window: int = 0,
                      greedy_only: bool = False):
    """THE sharded-step builder (ISSUE 12 tentpole): one entry point,
    parameterized by a declarative `PlaneSpec`, for every compiled
    program a sharded engine dispatches — the plain unified step, the
    fused greedy single step, the K-token decode window, the embeddings
    (return_hidden) step, the multimodal (input-embeds) prefill, and the
    ring-SP whole-prompt prefill.  The per-combo
    `make_sharded_{window,greedy,embed,mm,sp_prefill}_step` spellings
    survive as thin wrappers that construct the PlaneSpec.

    Impossible combinations raise the capability table's pointed error
    (`plane_capability`) — ONE place declares them, the engine's gating
    reads the same table, and the composition grid test asserts it.

    Common contract pieces: cache donated (in-place paged update);
    host-read outputs (logits / fused tokens) come back replicated under
    a multi-process mesh so every lockstep process reads locally, and
    host (numpy) inputs are converted to global arrays per in_shardings
    (`_finalize`).  `dp_attention` shards batch over (dp, tp) and the
    cache's slot axis over tp; `quant` carries the int8 cache's sharded
    scale buffers through every plane (ring hops included).

    Pipeline (pp) meshes build their stage programs through
    `parallel.pipeline` (stacked layer/cache layout); this builder
    serves every non-pp mesh.

    Legacy keyword spelling (moe_mode / dp_attention / use_pallas_decode
    / dp_local / kv_quant, and a positional moe_mode string) is still
    accepted and folded into a PlaneSpec.
    """
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import make_decode_window, make_forward_step
    from dynamo_tpu.parallel.multihost import mesh_spans_processes

    if isinstance(plane, str):       # legacy positional moe_mode
        moe_mode, plane = plane, None
    if plane is None:
        plane = PlaneSpec(quant=kv_quant, dp_attention=dp_attention,
                          use_pallas=use_pallas_decode, dp_local=dp_local,
                          window=window, greedy_only=greedy_only)
    # The model decides the moe plane dimension — fold it in here so
    # every caller (engine gates, wrappers, the grid test) queries the
    # capability table with the true spec.
    if plane.moe != cfg.is_moe:
        plane = _dc_replace(plane, moe=cfg.is_moe)
    validate(cfg, mesh, plane.dp_attention)
    check_plane(mesh, plane)
    mh = mesh_spans_processes(mesh)
    # moe × sp_prefill already raised in check_plane, so no dense-forcing
    # special case survives here.
    moe_mode = resolve_moe_mode(cfg, mesh, moe_mode)
    batch_axes = ("dp", "tp") if plane.dp_attention else "dp"

    def nsh(spec):
        return NamedSharding(mesh, spec)

    param_sh = jax.tree.map(
        nsh, param_pspecs(cfg, moe_mode, plane.dp_attention))
    cache_sh = jax.tree.map(
        nsh, cache_pspecs(cfg.num_layers, plane.dp_attention,
                          plane.dp_local, plane.quant))
    b = nsh(P(batch_axes))
    b2 = nsh(P(batch_axes, None))

    def jit_plane(fn, in_shardings, out_shardings):
        return _finalize(jax.jit(fn, in_shardings=in_shardings,
                                 out_shardings=tuple(out_shardings),
                                 donate_argnums=(1,)), in_shardings, mesh)

    if plane.role == "sp_prefill":
        # SEQUENCE-PARALLEL full-prompt prefill: the token axis shards
        # over sp and attention runs on the ICI ring
        # (ops/ring_attention.py).  Contract: the chunk is the WHOLE
        # prompt (positions 0..T-1, no prior cached context); T must
        # divide by sp.  MoE never reaches here (moe × sp_prefill is a
        # capability-table pointed error: token-axis ring sharding
        # conflicts with dp×ep token dispatch).  Quantized caches ride
        # the ring as int8 chunks + scales (llama._attention_block sp
        # branch — ISSUE 12 leg 1).
        # plane.use_pallas routes eligible geometry through the Pallas
        # flash ring kernel (RDMA exchange hidden under the fold); the
        # XLA ppermute ring stays the fallback and the oracle
        # (llama._sp_ring_attention picks per trace).
        step = make_forward_step(cfg, block_size, moe_mode="dense",
                                 mesh=mesh, sp_ring=True,
                                 sp_ring_pallas=plane.use_pallas)
        seq = nsh(P("dp", "sp"))
        in_shardings = (param_sh, cache_sh, seq, seq, nsh(P("dp")),
                        nsh(P("dp", None)), nsh(P("dp")))
        out_shardings = (
            # Logits are host-read (sampling); multihost replicates them
            # so every process can read locally.
            nsh(P(None, None) if mh else P("dp", None)),
            cache_sh,
        )
        return jit_plane(step, in_shardings, out_shardings)

    if plane.role == "embed":
        # return_hidden step (the /v1/embeddings path on a sharded
        # engine — r3 raised NotImplementedError here).
        step = make_forward_step(cfg, block_size, moe_mode=moe_mode,
                                 mesh=mesh, return_hidden=True,
                                 dp_local=plane.dp_local)
        in_shardings = (param_sh, cache_sh, b2, b2, b, b2, b)
        return jit_plane(step, in_shardings, (b2, cache_sh))

    if plane.role == "mm":
        # Multimodal prefill: masked chunk positions take provided
        # [B, T, H] embeddings instead of the token lookup
        # (llm/multimodal.py).  Embeddings shard like activations:
        # batch over the batch axes, H replicated.
        step = make_forward_step(cfg, block_size, moe_mode=moe_mode,
                                 mesh=mesh, with_input_embeds=True,
                                 dp_local=plane.dp_local)
        b3 = nsh(P(batch_axes, None, None))
        in_shardings = (param_sh, cache_sh, b2, b2, b, b2, b, b3, b2)
        out_shardings = (
            nsh(P(None, None) if mh else P(batch_axes, None)), cache_sh)
        return jit_plane(step, in_shardings, out_shardings)

    if plane.window > 0:
        # Fused K-token decode window — the fast decode path for SERVED
        # sharded models (VERDICT r3 weak #3).  Same contract as
        # llama.make_decode_window; MoE models return a sixth output
        # (accumulated expert-load counts through the fori_loop carry).
        # window == 1 still builds the WINDOW program (degenerate
        # single-iteration loop): callers chose the 11-arg run()
        # contract, and silently handing back the 7-arg plain step
        # would TypeError at their first dispatch.
        run = make_decode_window(cfg, block_size, plane.window,
                                 use_pallas_decode=plane.use_pallas,
                                 greedy_only=plane.greedy_only, mesh=mesh,
                                 dp_local=plane.dp_local,
                                 moe_mode=moe_mode,
                                 with_expert_load=cfg.is_moe)
        in_shardings = (param_sh, cache_sh,
                        b,    # last_tokens [B]
                        b,    # positions0 [B]
                        b,    # seq_lens0 [B]
                        b2,   # block_tables [B, P]
                        b,    # temp [B]
                        b,    # top_k [B]
                        b,    # top_p [B]
                        b2,   # base_key_data [B, 2]
                        b)    # key_offsets [B]
        out_shardings = [
            cache_sh,
            # Tokens are the one host-read output: multihost replicates
            # them so the fetch thread can read locally (collectives are
            # illegal off the lockstep thread).
            nsh(P(None, None) if mh else P(None, batch_axes)),
            b,    # positions0 + K
            b,    # seq_lens0 + K
            b,    # key_offsets + K
        ]
        if cfg.is_moe:
            out_shardings.append(nsh(P(None)))  # expert load
        return jit_plane(run, in_shardings, out_shardings)

    # Single-step planes (plain unified step / fused greedy).
    inner = make_forward_step(cfg, block_size, moe_mode=moe_mode, mesh=mesh,
                              with_expert_load=with_expert_load,
                              use_pallas_decode=plane.use_pallas,
                              dp_local=plane.dp_local)
    div = ((mesh.shape["dp"] * mesh.shape["tp"])
           if plane.dp_attention else 1)

    def checked(params, cache, tokens, *rest):
        if tokens.shape[0] % div:
            # Shape check at trace time (batch is static under jit):
            # surfaces a clear error instead of opaque GSPMD padding.
            raise ValueError(
                f"dp_attention: batch {tokens.shape[0]} must be a "
                f"multiple of dp*tp = {div}")
        return inner(params, cache, tokens, *rest)

    step = checked if plane.dp_attention else inner
    in_shardings = (param_sh, cache_sh,
                    b2,   # tokens [B, T]
                    b2,   # positions [B, T]
                    b,    # seq_lens [B]
                    b2,   # block_tables [B, P]
                    b)    # sample_positions [B]

    if plane.fused:
        # FUSED greedy single step: forward + on-device argmax in ONE
        # program with a donated cache, [B] int32 tokens out instead of
        # [B, V] f32 logits (ISSUE 9 leg 3 — the sharded half of the r5
        # single-step cliff; the unfused path was 3 eager dispatches plus
        # a full-vocab output per token).  Multi-process meshes replicate
        # the token output so every lockstep process reads it locally —
        # the fused step IS in the audited command stream (ISSUE 12
        # leg 4).
        def fused(params, cache, tokens, positions, seq_lens,
                  block_tables, sample_positions):
            out = step(params, cache, tokens, positions, seq_lens,
                       block_tables, sample_positions)
            if with_expert_load:
                logits, cache, load = out
                return (jnp.argmax(logits, -1).astype(jnp.int32), cache,
                        load)
            logits, cache = out
            return jnp.argmax(logits, -1).astype(jnp.int32), cache

        out_shardings = [nsh(P(None) if mh else P(batch_axes)), cache_sh]
        if with_expert_load:
            out_shardings.append(nsh(P(None)))
        return jit_plane(fused, in_shardings, out_shardings)

    out_shardings = [
        # Logits are host-read (sampling); multihost replicates them so
        # every process reads locally.
        nsh(P(None, None) if mh else P(batch_axes, None)),
        cache_sh,
    ]
    if with_expert_load:
        out_shardings.append(nsh(P(None)))
    return jit_plane(step, in_shardings, out_shardings)


# -- legacy spellings: thin PlaneSpec wrappers over make_sharded_step ------


def make_sp_prefill_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                         kv_quant: bool = False,
                         use_pallas: bool = False):
    """Ring-SP whole-prompt prefill (`role="sp_prefill"`): tokens and
    positions shard P(dp, sp); same step signature otherwise.
    `use_pallas` selects the flash ring kernel at eligible geometry
    (ops/pallas/ring_attention.py)."""
    return make_sharded_step(cfg, block_size, mesh,
                             PlaneSpec(role="sp_prefill", quant=kv_quant,
                                       use_pallas=use_pallas))


def make_sharded_window(cfg: ModelConfig, block_size: int, mesh: Mesh,
                        window: int,
                        greedy_only: bool = False,
                        use_pallas_decode: bool = False,
                        dp_attention: bool = False,
                        dp_local: bool = False,
                        kv_quant: bool = False,
                        moe_mode: str = "auto"):
    """Fused K-token decode window (`plane.window=K`); see
    llama.make_decode_window for the run() contract."""
    return make_sharded_step(
        cfg, block_size, mesh,
        PlaneSpec(window=window, greedy_only=greedy_only,
                  use_pallas=use_pallas_decode, dp_attention=dp_attention,
                  dp_local=dp_local, quant=kv_quant),
        moe_mode=moe_mode)


def make_sharded_greedy_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                             moe_mode: str = "auto",
                             with_expert_load: bool = False,
                             dp_attention: bool = False,
                             use_pallas_decode: bool = False,
                             dp_local: bool = False,
                             kv_quant: bool = False):
    """Fused greedy single step (`plane.fused=True`): forward + argmax in
    one donated-cache program, [B] tokens out."""
    return make_sharded_step(
        cfg, block_size, mesh,
        PlaneSpec(fused=True, use_pallas=use_pallas_decode,
                  dp_attention=dp_attention, dp_local=dp_local,
                  quant=kv_quant),
        with_expert_load, moe_mode=moe_mode)


def make_sharded_embed_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                            dp_attention: bool = False,
                            dp_local: bool = False,
                            kv_quant: bool = False):
    """return_hidden step (`role="embed"`) — the /v1/embeddings path."""
    return make_sharded_step(
        cfg, block_size, mesh,
        PlaneSpec(role="embed", dp_attention=dp_attention,
                  dp_local=dp_local, quant=kv_quant))


def make_sharded_mm_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                         dp_attention: bool = False,
                         dp_local: bool = False,
                         kv_quant: bool = False):
    """Multimodal input-embeds prefill (`role="mm"`)."""
    return make_sharded_step(
        cfg, block_size, mesh,
        PlaneSpec(role="mm", dp_attention=dp_attention,
                  dp_local=dp_local, quant=kv_quant))
