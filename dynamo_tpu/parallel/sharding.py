"""GSPMD sharding rules for the Llama-family engine.

Megatron-style tensor parallelism expressed as PartitionSpecs; XLA inserts
the collectives (reference counterpart: NCCL inside vLLM — SURVEY.md §2.6
"Collectives (in-engine)"):

- attention: wq/wk/wv column-parallel (heads over tp), wo row-parallel
  (psum on exit); the KV cache shards its head axis over tp so cache
  reads/writes stay device-local.
- MLP: w_gate/w_up column-parallel, w_down row-parallel.
- MoE: expert dimension over ep, each expert's MLP additionally tp-sharded.
- embedding / lm_head: vocab-sharded over tp (logit psum/all-gather at the
  end of the step).
- activations/batch: sharded over dp.

GQA note: `num_kv_heads` (8 for Llama-3) bounds head-sharded tp for the
cache; tp degrees beyond that would need head replication — rejected here
rather than silently replicated.
"""

from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig

Params = Dict


def param_pspecs(cfg: ModelConfig, moe_mode: str = "dense",
                 dp_attention: bool = False) -> Params:
    """PartitionSpec pytree matching `llama.init_params` structure.

    MoE weights: dense mode shards each expert's MLP over tp too (the
    dense einsums partition fine under GSPMD); dispatch mode keeps expert
    shards tp-unsharded (the shard_map body owns them whole) and
    replicates the router (every shard routes its own tokens).

    `dp_attention` (reference: sglang --enable-dp-attention,
    `disagg_dp_attn.sh:33-37`): attention runs data-parallel over the
    batch with REPLICATED attention weights while MLPs stay
    tensor-parallel — the mode for models whose kv-head count is below
    the tp degree (head-sharded KV would cap tp or duplicate KV)."""
    if dp_attention:
        attn = {
            "wq": P(None, None),
            "wk": P(None, None),
            "wv": P(None, None),
            "wo": P(None, None),
        }
    else:
        attn = {
            "wq": P(None, "tp"),
            "wk": P(None, "tp"),
            "wv": P(None, "tp"),
            "wo": P("tp", None),
        }
    layer = {
        "attn": attn,
        "attn_norm": P(None),
        "mlp_norm": P(None),
    }
    if cfg.post_norms:
        layer["post_attn_norm"] = P(None)
        layer["post_mlp_norm"] = P(None)
    if cfg.is_moe:
        if moe_mode == "dispatch":
            layer["moe"] = {
                "router": P(None, None),
                "w_gate": P("ep", None, None),
                "w_up": P("ep", None, None),
                "w_down": P("ep", None, None),
            }
        else:
            layer["moe"] = {
                "router": P(None, "ep"),
                "w_gate": P("ep", None, "tp"),
                "w_up": P("ep", None, "tp"),
                "w_down": P("ep", "tp", None),
            }
    else:
        layer["mlp"] = {
            "w_gate": P(None, "tp"),
            "w_up": P(None, "tp"),
            "w_down": P("tp", None),
        }
    specs: Params = {
        "embed": P("tp", None),
        "final_norm": P(None),
        "layers": [layer] * cfg.num_layers,
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def cache_pspecs(num_layers: int, dp_attention: bool = False,
                 dp_local: bool = False, kv_quant: bool = False) -> Dict:
    """KV cache: per-layer [slots, F = kv_heads * head_dim] buffers; the
    flat feature axis shards over tp, which IS head sharding (F is
    head-major and validate() enforces tp | num_kv_heads).

    The slot axis is deliberately *not* dp-sharded: each dp replica runs its
    own engine process with its own cache (serving-style DP, reference
    PushRouter replicas), so within one process the cache only shards over
    tp.

    `dp_attention`: the SLOT axis shards over tp instead of heads — total
    KV memory still splits tp-ways, but head count no longer caps tp.
    GSPMD resolves page→device movement with collectives.

    `dp_local` (implies dp_attention): slots shard over the FLAT (dp, tp)
    device grid and the engine's locality-aware allocator guarantees a
    row's pages live on that row's device — decode attention then runs
    fully device-local under shard_map (llama._attention_block dp-local
    branch), no cross-chip gathers per step (VERDICT r3 weak #4).

    `kv_quant` (ISSUE 9): the int8 cache's sibling per-layer [S, Hkv] f32
    scale buffers SHARD WITH THEIR KV HEADS — head-sharded tp splits the
    Hkv axis exactly as the F axis splits (F is head-major and tp | Hkv),
    so every shard dequantizes its own heads with locally-resident
    scales; slot-sharded modes (dp_attention / dp_local) shard the scale
    slot axis like the page slot axis.  Scales are never replicated:
    a replicated [S, Hkv] f32 buffer would cost more HBM per chip than
    the int8 quantization saves at small head_dim."""
    if dp_local:
        spec = P(("dp", "tp"), None)
        sspec = P(("dp", "tp"), None)
    elif dp_attention:
        spec = P("tp", None)
        sspec = P("tp", None)
    else:
        spec = P(None, "tp")
        sspec = P(None, "tp")   # Hkv axis: scales ride their heads
    out = {"k": [spec] * num_layers, "v": [spec] * num_layers}
    if kv_quant:
        out["k_scale"] = [sspec] * num_layers
        out["v_scale"] = [sspec] * num_layers
    return out


def data_pspecs() -> Dict:
    """Per-step input batch: batch dim over dp."""
    return {
        "tokens": P("dp", None),
        "positions": P("dp", None),
        "seq_lens": P("dp"),
        "block_tables": P("dp", None),
    }


def validate(cfg: ModelConfig, mesh: Mesh,
             dp_attention: bool = False) -> None:
    tp = mesh.shape["tp"]
    ep = mesh.shape["ep"]
    if not dp_attention and cfg.num_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_kv_heads={cfg.num_kv_heads} "
            "(head-sharded KV cache; use dp_attention for tp beyond the "
            "kv-head count)"
        )
    if cfg.intermediate_size % tp:
        raise ValueError(f"tp={tp} must divide intermediate={cfg.intermediate_size}")
    if cfg.vocab_size % tp:
        raise ValueError(f"tp={tp} must divide vocab={cfg.vocab_size}")
    if cfg.is_moe and cfg.num_experts % ep:
        raise ValueError(f"ep={ep} must divide num_experts={cfg.num_experts}")
    if not cfg.is_moe and ep > 1:
        raise ValueError("ep > 1 on a dense model wastes chips; use tp/dp")


def shard_pytree(tree, pspecs, mesh: Mesh):
    """Place a pytree on the mesh according to a matching pspec pytree.

    Under a multi-process mesh, host leaves become GLOBAL arrays via
    make_array_from_callback (each process serves its addressable shards
    from identical host bytes — plain device_put would commit to one
    process's devices)."""
    from dynamo_tpu.parallel.multihost import mesh_spans_processes, to_global

    if mesh_spans_processes(mesh):
        import numpy as _np

        return jax.tree.map(
            lambda x, s: to_global(_np.asarray(x), NamedSharding(mesh, s)),
            tree, pspecs)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, pspecs
    )


def _finalize(fn, in_shardings, mesh: Mesh):
    """Multihost-aware jit wrapper: when the mesh spans processes, host
    (numpy / process-local) inputs are converted to global arrays per the
    in_shardings tree before the call; single-process meshes return the
    jit untouched (zero overhead on the tuned serving path)."""
    from dynamo_tpu.parallel.multihost import (
        mesh_spans_processes, wrap_global_inputs)

    if mesh_spans_processes(mesh):
        return wrap_global_inputs(fn, in_shardings)
    return fn


def make_sp_prefill_step(cfg: ModelConfig, block_size: int, mesh: Mesh):
    """Jit the SEQUENCE-PARALLEL full-prompt prefill step: the token axis
    shards over the mesh's sp axis and attention runs on the ICI ring
    (ops/ring_attention.py) — the long-context prefill path SURVEY §2.5
    demands.  Contract: the chunk is the WHOLE prompt (positions 0..T-1;
    no prior cached context is read); T must divide by sp.

    Returns `step(params, cache, tokens, positions, seq_lens,
    block_tables, sample_positions)` → (logits, cache), same signature as
    the regular step but with tokens/positions sharded P(dp, sp).
    """
    from dynamo_tpu.models.llama import make_forward_step
    from dynamo_tpu.parallel.multihost import mesh_spans_processes

    validate(cfg, mesh)
    mh = mesh_spans_processes(mesh)
    # MoE under sp: dense compute (the dispatch shard_map shards tokens
    # over dp×ep, which conflicts with the sp sharding of a prefill chunk).
    step = make_forward_step(cfg, block_size, moe_mode="dense", mesh=mesh,
                             sp_ring=True)
    seq = NamedSharding(mesh, P("dp", "sp"))
    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s), param_pspecs(cfg)),
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers)),
        seq,                                       # tokens [B, T]
        seq,                                       # positions [B, T]
        NamedSharding(mesh, P("dp")),              # seq_lens [B]
        NamedSharding(mesh, P("dp", None)),        # block_tables [B, P]
        NamedSharding(mesh, P("dp")),              # sample_positions [B]
    )
    out_shardings = (
        # Logits are host-read (sampling); multihost replicates them so
        # every process can read locally (no off-thread collectives).
        NamedSharding(mesh, P(None, None) if mh else P("dp", None)),
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers)),
    )
    return _finalize(jax.jit(
        step,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        donate_argnums=(1,),
    ), in_shardings, mesh)


def resolve_moe_mode(cfg: ModelConfig, mesh: Mesh,
                     moe_mode: str = "auto") -> str:
    """'auto' → all-to-all dispatch when an ep axis exists and tp == 1
    (the shard_map body owns whole expert MLPs), else dense."""
    if not cfg.is_moe:
        return "dense"
    if moe_mode == "auto":
        return ("dispatch"
                if mesh.shape["ep"] > 1 and mesh.shape["tp"] == 1
                else "dense")
    if moe_mode == "dispatch" and mesh.shape["tp"] != 1:
        raise ValueError("moe_mode='dispatch' requires tp == 1 "
                         "(expert MLPs are whole per ep shard)")
    return moe_mode


def _reject_pallas_dp_attention(use_pallas_decode: bool,
                                dp_attention: bool, dp_local: bool) -> None:
    """Pallas decode composes with head-sharded tp (heads over tp inside
    shard_map) and with dp_attention LOCALITY (slots rebase to the shard's
    local range inside the body — ISSUE 9 leg 2).  Plain dp_attention
    without locality is the one remaining exclusion: pages may live on
    any shard, and the kernel's slot indexing cannot cross chips."""
    if use_pallas_decode and dp_attention and not dp_local:
        raise ValueError(
            "pallas decode under dp_attention needs page locality "
            "(dp_attention_local=True): without it a row's pages may "
            "live on any shard and the kernel's slot indexing cannot "
            "cross chips — set dp_attention_local (plain allocator) or "
            "drop use_pallas_decode for the gather path")


def make_sharded_window(cfg: ModelConfig, block_size: int, mesh: Mesh,
                        window: int,
                        greedy_only: bool = False,
                        use_pallas_decode: bool = False,
                        dp_attention: bool = False,
                        dp_local: bool = False,
                        kv_quant: bool = False):
    """Jit the fused K-token decode window under a mesh — the fast decode
    path for SERVED sharded models (VERDICT r3 weak #3: without this, a
    tp=8 70B decode would fall back to the per-token host loop over a
    ~160 ms-RTT link).  Same contract as llama.make_decode_window; MoE
    models return a sixth output (accumulated expert-load counts — the
    aux threads through the fori_loop carry since r5).

    `use_pallas_decode` routes attention through the Pallas kernel inside
    a shard_map over (dp, tp) — heads over tp, or shard-local slots under
    dp_attention locality (see _reject_pallas_dp_attention).

    `kv_quant`: the cache pytree carries int8 pages + [S, Hkv] f32 scale
    buffers (cache_pspecs kv_quant=True) and the attention bodies
    dequantize shard-locally.
    """
    from dynamo_tpu.models.llama import make_decode_window
    from dynamo_tpu.parallel.multihost import mesh_spans_processes

    validate(cfg, mesh, dp_attention)
    mh = mesh_spans_processes(mesh)
    _reject_pallas_dp_attention(use_pallas_decode, dp_attention, dp_local)
    # MoE windows (r5): the expert-load telemetry threads through the
    # fori_loop carry; the window uses the same resolved moe mode as the
    # engine's single step.
    moe_mode = resolve_moe_mode(cfg, mesh)
    run = make_decode_window(cfg, block_size, window,
                             use_pallas_decode=use_pallas_decode,
                             greedy_only=greedy_only, mesh=mesh,
                             dp_local=dp_local,
                             moe_mode=moe_mode,
                             with_expert_load=cfg.is_moe)
    batch_axes = ("dp", "tp") if dp_attention else "dp"
    b = NamedSharding(mesh, P(batch_axes))
    b2 = NamedSharding(mesh, P(batch_axes, None))
    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     param_pspecs(cfg, moe_mode,
                                  dp_attention=dp_attention)),
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers, dp_attention, dp_local,
                                  kv_quant)),
        b,                                         # last_tokens [B]
        b,                                         # positions0 [B]
        b,                                         # seq_lens0 [B]
        b2,                                        # block_tables [B, P]
        b,                                         # temp [B]
        b,                                         # top_k [B]
        b,                                         # top_p [B]
        b2,                                        # base_key_data [B, 2]
        b,                                         # key_offsets [B]
    )
    out_shardings = [
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers, dp_attention, dp_local,
                                  kv_quant)),
        # Tokens are the one host-read output: multihost replicates them
        # so the fetch thread can read locally (collectives are illegal
        # off the lockstep thread).
        NamedSharding(mesh, P(None, None) if mh else P(None, batch_axes)),
        b,                                         # positions0 + K
        b,                                         # seq_lens0 + K
        b,                                         # key_offsets + K
    ]
    if cfg.is_moe:
        out_shardings.append(NamedSharding(mesh, P(None)))  # expert load
    return _finalize(jax.jit(run, in_shardings=in_shardings,
                             out_shardings=tuple(out_shardings),
                             donate_argnums=(1,)), in_shardings, mesh)


def make_sharded_embed_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                            dp_attention: bool = False,
                            dp_local: bool = False,
                            kv_quant: bool = False):
    """Jit the return_hidden step under a mesh (the /v1/embeddings path on
    a sharded engine — r3 raised NotImplementedError here)."""
    from dynamo_tpu.models.llama import make_forward_step

    validate(cfg, mesh, dp_attention)
    moe_mode = resolve_moe_mode(cfg, mesh)
    step = make_forward_step(cfg, block_size, moe_mode=moe_mode, mesh=mesh,
                             return_hidden=True, dp_local=dp_local)
    batch_axes = ("dp", "tp") if dp_attention else "dp"
    b = NamedSharding(mesh, P(batch_axes))
    b2 = NamedSharding(mesh, P(batch_axes, None))
    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     param_pspecs(cfg, moe_mode, dp_attention)),
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers, dp_attention, dp_local,
                                  kv_quant)),
        b2, b2, b, b2, b,
    )
    out_shardings = (
        b2,                                        # hidden [B, H]
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers, dp_attention, dp_local,
                                  kv_quant)),
    )
    return _finalize(jax.jit(step, in_shardings=in_shardings,
                             out_shardings=out_shardings,
                             donate_argnums=(1,)), in_shardings, mesh)


def make_sharded_mm_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                         dp_attention: bool = False,
                         dp_local: bool = False,
                         kv_quant: bool = False):
    """Jit the multimodal prefill variant under a mesh: positions whose
    mask is set take the provided [B, T, H] embeddings instead of the
    token lookup (llm/multimodal.py; lifts VERDICT r4's sharded-engine
    prompt_embeds rejection, engine.py:380).  Embeddings shard like
    activations: batch over the batch axes, H replicated (the tp-sharded
    projections consume them immediately)."""
    from dynamo_tpu.models.llama import make_forward_step

    validate(cfg, mesh, dp_attention)
    moe_mode = resolve_moe_mode(cfg, mesh)
    step = make_forward_step(cfg, block_size, moe_mode=moe_mode, mesh=mesh,
                             with_input_embeds=True, dp_local=dp_local)
    batch_axes = ("dp", "tp") if dp_attention else "dp"
    from dynamo_tpu.parallel.multihost import mesh_spans_processes

    mh = mesh_spans_processes(mesh)
    b = NamedSharding(mesh, P(batch_axes))
    b2 = NamedSharding(mesh, P(batch_axes, None))
    b3 = NamedSharding(mesh, P(batch_axes, None, None))
    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     param_pspecs(cfg, moe_mode, dp_attention)),
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers, dp_attention, dp_local,
                                  kv_quant)),
        b2,                                        # tokens [B, T]
        b2,                                        # positions [B, T]
        b,                                         # seq_lens [B]
        b2,                                        # block_tables [B, P]
        b,                                         # sample_positions [B]
        b3,                                        # input_embeds [B, T, H]
        b2,                                        # embed_mask [B, T]
    )
    out_shardings = (
        NamedSharding(mesh, P(None, None) if mh else P(batch_axes, None)),
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers, dp_attention, dp_local,
                                  kv_quant)),
    )
    return _finalize(jax.jit(step, in_shardings=in_shardings,
                             out_shardings=out_shardings,
                             donate_argnums=(1,)), in_shardings, mesh)


def make_sharded_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                      moe_mode: str = "auto",
                      with_expert_load: bool = False,
                      dp_attention: bool = False,
                      use_pallas_decode: bool = False,
                      dp_local: bool = False,
                      kv_quant: bool = False):
    """Jit the unified engine step with explicit in/out shardings.

    Returns `step(params, cache, tokens, positions, seq_lens, block_tables)`
    → (logits, cache[, expert_load]).  Cache is donated (in-place paged-
    cache update); logits come back replicated so the sampler/host sees
    full vocab.

    `dp_attention`: batch shards over (dp, tp) and the KV cache's slot
    axis over tp — see param_pspecs/cache_pspecs.  Batch must be a
    multiple of dp×tp.

    `kv_quant`: int8 cache pytree with sharded scale buffers
    (cache_pspecs kv_quant=True; ISSUE 9 leg 1).
    """
    from dynamo_tpu.models.llama import make_forward_step

    validate(cfg, mesh, dp_attention)
    _reject_pallas_dp_attention(use_pallas_decode, dp_attention, dp_local)
    if dp_local and not dp_attention:
        raise ValueError("dp_local implies dp_attention")
    moe_mode = resolve_moe_mode(cfg, mesh, moe_mode)
    inner = make_forward_step(cfg, block_size, moe_mode=moe_mode, mesh=mesh,
                              with_expert_load=with_expert_load,
                              use_pallas_decode=use_pallas_decode,
                              dp_local=dp_local)
    if dp_attention:
        div = mesh.shape["dp"] * mesh.shape["tp"]

        def step(params, cache, tokens, *rest):
            # Shape check at trace time (batch is static under jit):
            # surfaces a clear error instead of opaque GSPMD padding.
            if tokens.shape[0] % div:
                raise ValueError(
                    f"dp_attention: batch {tokens.shape[0]} must be a "
                    f"multiple of dp*tp = {div}")
            return inner(params, cache, tokens, *rest)
    else:
        step = inner
    batch_axes = ("dp", "tp") if dp_attention else "dp"
    from dynamo_tpu.parallel.multihost import mesh_spans_processes

    mh = mesh_spans_processes(mesh)
    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     param_pspecs(cfg, moe_mode, dp_attention)),
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers, dp_attention, dp_local,
                                  kv_quant)),
        NamedSharding(mesh, P(batch_axes, None)),  # tokens
        NamedSharding(mesh, P(batch_axes, None)),  # positions
        NamedSharding(mesh, P(batch_axes)),        # seq_lens
        NamedSharding(mesh, P(batch_axes, None)),  # block_tables
        NamedSharding(mesh, P(batch_axes)),        # sample_positions [B]
    )
    out_shardings = [
        # Logits are host-read (sampling); multihost replicates them so
        # every process reads locally.
        NamedSharding(mesh,
                      P(None, None) if mh else P(batch_axes, None)),
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     cache_pspecs(cfg.num_layers, dp_attention, dp_local,
                                  kv_quant)),
    ]
    if with_expert_load:
        out_shardings.append(NamedSharding(mesh, P(None)))
    return _finalize(jax.jit(
        step,
        in_shardings=in_shardings,
        out_shardings=tuple(out_shardings),
        donate_argnums=(1,),
    ), in_shardings, mesh)


def make_sharded_greedy_step(cfg: ModelConfig, block_size: int, mesh: Mesh,
                             moe_mode: str = "auto",
                             with_expert_load: bool = False,
                             dp_attention: bool = False,
                             use_pallas_decode: bool = False,
                             dp_local: bool = False,
                             kv_quant: bool = False):
    """Jit the FUSED greedy single step under a mesh: forward + on-device
    argmax compile into ONE program with a donated cache, returning [B]
    int32 tokens instead of [B, V] logits (ISSUE 9 leg 3 — the sharded
    half of the r5 single-step cliff).  The unfused sharded path was a
    step dispatch + row gather + argmax, three eager dispatches plus a
    full-vocab f32 logits output per token; on a tunneled chip the extra
    dispatches dominate the step.  Same fusion as the meshless
    `EngineCore._greedy_step_fn`; multihost stays on the plain path (the
    lockstep command stream replays the unfused step).

    Returns `fused(params, cache, tokens, positions, seq_lens,
    block_tables, sample_positions)` → (tokens[B], cache[, expert_load]).
    """
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import make_forward_step

    validate(cfg, mesh, dp_attention)
    _reject_pallas_dp_attention(use_pallas_decode, dp_attention, dp_local)
    if dp_local and not dp_attention:
        raise ValueError("dp_local implies dp_attention")
    moe_mode = resolve_moe_mode(cfg, mesh, moe_mode)
    inner = make_forward_step(cfg, block_size, moe_mode=moe_mode, mesh=mesh,
                              with_expert_load=with_expert_load,
                              use_pallas_decode=use_pallas_decode,
                              dp_local=dp_local)
    div = (mesh.shape["dp"] * mesh.shape["tp"]) if dp_attention else 1

    def fused(params, cache, tokens, positions, seq_lens, block_tables,
              sample_positions):
        if tokens.shape[0] % div:
            # Same trace-time check as make_sharded_step: a clear error
            # instead of opaque GSPMD padding (the fused path must not
            # hide a misconfiguration the unfused path surfaces).
            raise ValueError(
                f"dp_attention: batch {tokens.shape[0]} must be a "
                f"multiple of dp*tp = {div}")
        out = inner(params, cache, tokens, positions, seq_lens,
                    block_tables, sample_positions)
        if with_expert_load:
            logits, cache, load = out
            return (jnp.argmax(logits, -1).astype(jnp.int32), cache, load)
        logits, cache = out
        return jnp.argmax(logits, -1).astype(jnp.int32), cache

    batch_axes = ("dp", "tp") if dp_attention else "dp"
    cache_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        cache_pspecs(cfg.num_layers, dp_attention, dp_local, kv_quant))
    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s),
                     param_pspecs(cfg, moe_mode, dp_attention)),
        cache_sh,
        NamedSharding(mesh, P(batch_axes, None)),  # tokens [B, 1]
        NamedSharding(mesh, P(batch_axes, None)),  # positions [B, 1]
        NamedSharding(mesh, P(batch_axes)),        # seq_lens [B]
        NamedSharding(mesh, P(batch_axes, None)),  # block_tables [B, P]
        NamedSharding(mesh, P(batch_axes)),        # sample_positions [B]
    )
    out_shardings = [NamedSharding(mesh, P(batch_axes)),  # tokens [B]
                     cache_sh]
    if with_expert_load:
        out_shardings.append(NamedSharding(mesh, P(None)))
    return jax.jit(fused, in_shardings=in_shardings,
                   out_shardings=tuple(out_shardings), donate_argnums=(1,))
