"""GSPMD sharding rules for the Llama-family engine.

Megatron-style tensor parallelism expressed as PartitionSpecs; XLA inserts
the collectives (reference counterpart: NCCL inside vLLM — SURVEY.md §2.6
"Collectives (in-engine)"):

- attention: wq/wk/wv column-parallel (heads over tp), wo row-parallel
  (psum on exit); the KV cache shards its head axis over tp so cache
  reads/writes stay device-local.
- MLP: w_gate/w_up column-parallel, w_down row-parallel.
- MoE: expert dimension over ep, each expert's MLP additionally tp-sharded.
- embedding / lm_head: vocab-sharded over tp (logit psum/all-gather at the
  end of the step).
- activations/batch: sharded over dp.

GQA note: `num_kv_heads` (8 for Llama-3) bounds head-sharded tp for the
cache; tp degrees beyond that would need head replication — rejected here
rather than silently replicated.
"""

from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig

Params = Dict


def param_pspecs(cfg: ModelConfig) -> Params:
    """PartitionSpec pytree matching `llama.init_params` structure."""
    attn = {
        "wq": P(None, "tp"),
        "wk": P(None, "tp"),
        "wv": P(None, "tp"),
        "wo": P("tp", None),
    }
    layer = {
        "attn": attn,
        "attn_norm": P(None),
        "mlp_norm": P(None),
    }
    if cfg.is_moe:
        layer["moe"] = {
            "router": P(None, "ep"),
            "w_gate": P("ep", None, "tp"),
            "w_up": P("ep", None, "tp"),
            "w_down": P("ep", "tp", None),
        }
    else:
        layer["mlp"] = {
            "w_gate": P(None, "tp"),
            "w_up": P(None, "tp"),
            "w_down": P("tp", None),
        }
    specs: Params = {
        "embed": P("tp", None),
        "final_norm": P(None),
        "layers": [layer] * cfg.num_layers,
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def cache_pspecs() -> Dict:
    """KV cache [L, slots, kv_heads, head_dim]: heads over tp.

    The slot axis is deliberately *not* dp-sharded: each dp replica runs its
    own engine process with its own cache (serving-style DP, reference
    PushRouter replicas), so within one process the cache only shards over
    tp."""
    spec = P(None, None, "tp", None)
    return {"k": spec, "v": spec}


def data_pspecs() -> Dict:
    """Per-step input batch: batch dim over dp."""
    return {
        "tokens": P("dp", None),
        "positions": P("dp", None),
        "seq_lens": P("dp"),
        "block_tables": P("dp", None),
    }


def validate(cfg: ModelConfig, mesh: Mesh) -> None:
    tp = mesh.shape["tp"]
    ep = mesh.shape["ep"]
    if cfg.num_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_kv_heads={cfg.num_kv_heads} "
            "(head-sharded KV cache; replication not supported)"
        )
    if cfg.intermediate_size % tp:
        raise ValueError(f"tp={tp} must divide intermediate={cfg.intermediate_size}")
    if cfg.vocab_size % tp:
        raise ValueError(f"tp={tp} must divide vocab={cfg.vocab_size}")
    if cfg.is_moe and cfg.num_experts % ep:
        raise ValueError(f"ep={ep} must divide num_experts={cfg.num_experts}")
    if not cfg.is_moe and ep > 1:
        raise ValueError("ep > 1 on a dense model wastes chips; use tp/dp")


def shard_pytree(tree, pspecs, mesh: Mesh):
    """Place a pytree on the mesh according to a matching pspec pytree."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, pspecs
    )


def make_sharded_step(cfg: ModelConfig, block_size: int, mesh: Mesh):
    """Jit the unified engine step with explicit in/out shardings.

    Returns `step(params, cache, tokens, positions, seq_lens, block_tables)`
    → (logits, cache).  Cache is donated (in-place paged-cache update);
    logits come back replicated so the sampler/host sees full vocab.
    """
    from dynamo_tpu.models.llama import make_forward_step

    validate(cfg, mesh)
    step = make_forward_step(cfg, block_size)
    d = data_pspecs()
    in_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s), param_pspecs(cfg)),
        jax.tree.map(lambda s: NamedSharding(mesh, s), cache_pspecs()),
        NamedSharding(mesh, d["tokens"]),
        NamedSharding(mesh, d["positions"]),
        NamedSharding(mesh, d["seq_lens"]),
        NamedSharding(mesh, d["block_tables"]),
        NamedSharding(mesh, P("dp")),              # sample_positions [B]
    )
    out_shardings = (
        NamedSharding(mesh, P("dp", None)),        # logits [B, V]
        jax.tree.map(lambda s: NamedSharding(mesh, s), cache_pspecs()),
    )
    return jax.jit(
        step,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        donate_argnums=(1,),
    )
