"""Load-based planner (autoscaler).

Role of the reference's `components/planner`
(`planner/utils/planner_core.py:241-318`): observe worker load, predict
the near-term value, compute a replica target, and tell a connector to
converge on it.  Round-3 scope is the LOAD-based planner over our
control plane's `load_metrics` stream (the SLA planner's
TTFT/ITL-interpolation layer builds on the same skeleton).

Scaling rules (reference load-planner semantics,
`docs/architecture/load_planner.md`):
- scale UP by one replica when the predicted per-worker KV-cache usage
  exceeds `kv_high` OR any requests are queued (waiting > 0);
- scale DOWN by one when predicted usage across workers would still stay
  under `kv_low` with one fewer replica and nothing is waiting;
- clamp to [min_replicas, max_replicas]; one move per adjustment
  interval (no thrash).

Graceful scale-down mirrors the reference (`load_planner.md:21`): the
connector SIGTERMs the newest worker; the worker's own drain logic
(worker/main.py) leaves routing instantly and finishes in-flight
streams, so no stream is dropped.
"""

from dynamo_tpu.planner.core import LoadPlanner, PlannerConfig
from dynamo_tpu.planner.connector import LocalConnector
from dynamo_tpu.planner.predictor import (
    ConstantPredictor,
    ARPredictor,
    MovingAveragePredictor,
    TrendPredictor,
    make_predictor,
)
from dynamo_tpu.planner.sla import (
    PrometheusScraper,
    SlaObservation,
    SlaPlanner,
    SlaPlannerConfig,
)

__all__ = [
    "LoadPlanner",
    "PlannerConfig",
    "LocalConnector",
    "ConstantPredictor",
    "ARPredictor",
    "MovingAveragePredictor",
    "TrendPredictor",
    "make_predictor",
    "SlaPlanner",
    "SlaPlannerConfig",
    "SlaObservation",
    "PrometheusScraper",
]
