"""CLI entry: `python -m dynamo_tpu.planner`.

    python -m dynamo_tpu.planner --control-plane HOST:PORT \
        --min-replicas 1 --max-replicas 4 -- --mocker --model-name m

Everything after `--` is passed to each spawned worker."""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from dynamo_tpu.planner import LoadPlanner, LocalConnector, PlannerConfig
from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient


def main(argv=None) -> None:
    p = argparse.ArgumentParser("dynamo_tpu.planner")
    p.add_argument("--control-plane", required=True, help="HOST:PORT")
    p.add_argument("--mode", choices=("load", "sla"), default="load")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=8)
    p.add_argument("--kv-high", type=float, default=0.8)
    p.add_argument("--kv-low", type=float, default=0.3)
    p.add_argument("--adjustment-interval", type=float, default=5.0)
    # SLA mode (reference planner_sla.py): profile + targets + the
    # frontend exposition to scrape.
    p.add_argument("--profile", default=None,
                   help="sla: profile JSON from dynamo_tpu.planner.profiler")
    p.add_argument("--ttft", type=float, default=0.5,
                   help="sla: target time-to-first-token (s)")
    p.add_argument("--itl", type=float, default=0.05,
                   help="sla: target inter-token latency (s)")
    p.add_argument("--metrics-url", default=None,
                   help="sla: frontend /metrics URL to observe")
    p.add_argument("--slo-url", default=None,
                   help="load mode: a /debug/slo URL (frontend or "
                        "worker) whose burn rate biases scale-up "
                        "(runtime/slo.py)")
    p.add_argument("--slo-burn-scale-up", type=float, default=2.0,
                   help="fast-window burn rate at or above which the "
                        "load planner scales up regardless of KV usage")
    p.add_argument("--prefill-worker-args", default=None,
                   help="sla: comma-joined args for the prefill pool "
                        "(omit for aggregated deployments)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="status server port for /metrics "
                        "(0 = ephemeral; -1 disables)")
    p.add_argument("--metrics-host", default="127.0.0.1",
                   help="bind + ADVERTISED host for the status server; a "
                        "cross-host aggregator needs a routable address "
                        "(the 127.0.0.1 default only works single-host)")
    p.add_argument("worker_args", nargs="*",
                   help="args after -- go to spawned workers")
    from dynamo_tpu.runtime.tracing import (
        add_trace_args, configure_from_args)

    add_trace_args(p)
    args = p.parse_args(argv)
    if args.mode == "sla" and (not args.profile or not args.metrics_url):
        p.error("--mode sla needs --profile and --metrics-url")
    logging.basicConfig(level=logging.INFO)
    configure_from_args(args, service="planner")

    async def run():
        host, port = args.control_plane.rsplit(":", 1)
        cp = ControlPlaneClient(host, int(port))
        await cp.start()
        connector = LocalConnector(args.control_plane,
                                   worker_args=args.worker_args)
        if args.mode == "sla":
            from dynamo_tpu.planner import (
                PrometheusScraper, SlaPlanner, SlaPlannerConfig)
            from dynamo_tpu.planner.interpolation import load_profile

            prefill_connector = None
            if args.prefill_worker_args is not None:
                prefill_connector = LocalConnector(
                    args.control_plane,
                    worker_args=args.prefill_worker_args.split(","))
            planner = SlaPlanner(
                load_profile(args.profile),
                PrometheusScraper(args.metrics_url).observe,
                decode_connector=connector,
                prefill_connector=prefill_connector,
                config=SlaPlannerConfig(
                    ttft_s=args.ttft, itl_s=args.itl,
                    adjustment_interval_s=args.adjustment_interval,
                    min_replicas=args.min_replicas,
                    max_replicas=args.max_replicas))
        else:
            planner = LoadPlanner(cp, connector, PlannerConfig(
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                kv_high=args.kv_high, kv_low=args.kv_low,
                adjustment_interval=args.adjustment_interval,
                slo_burn_scale_up=args.slo_burn_scale_up),
                slo_url=args.slo_url)
        await planner.start()
        status = None
        if args.metrics_port >= 0:
            from dynamo_tpu.planner.core import planner_metrics_text
            from dynamo_tpu.runtime.status import (
                StatusServer, register_status_endpoint)

            status = StatusServer(
                extra_text_fn=lambda: planner_metrics_text(planner,
                                                           connector))
            bound = await status.start(host=args.metrics_host,
                                       port=args.metrics_port)
            await register_status_endpoint(cp, "planner", bound,
                                           host=args.metrics_host)
            print(f"planner metrics on :{bound}/metrics", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        if status is not None:
            await status.stop()
        await planner.stop()
        await connector.shutdown()
        pc = getattr(planner, "prefill_connector", None)
        if pc is not None:
            await pc.shutdown()
        await cp.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
