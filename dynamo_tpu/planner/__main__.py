"""CLI entry: `python -m dynamo_tpu.planner`.

    python -m dynamo_tpu.planner --control-plane HOST:PORT \
        --min-replicas 1 --max-replicas 4 -- --mocker --model-name m

Everything after `--` is passed to each spawned worker."""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from dynamo_tpu.planner import LoadPlanner, LocalConnector, PlannerConfig
from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient


def main(argv=None) -> None:
    p = argparse.ArgumentParser("dynamo_tpu.planner")
    p.add_argument("--control-plane", required=True, help="HOST:PORT")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=8)
    p.add_argument("--kv-high", type=float, default=0.8)
    p.add_argument("--kv-low", type=float, default=0.3)
    p.add_argument("--adjustment-interval", type=float, default=5.0)
    p.add_argument("worker_args", nargs="*",
                   help="args after -- go to spawned workers")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    async def run():
        host, port = args.control_plane.rsplit(":", 1)
        cp = ControlPlaneClient(host, int(port))
        await cp.start()
        connector = LocalConnector(args.control_plane,
                                   worker_args=args.worker_args)
        planner = LoadPlanner(cp, connector, PlannerConfig(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            kv_high=args.kv_high, kv_low=args.kv_low,
            adjustment_interval=args.adjustment_interval))
        await planner.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await planner.stop()
        await connector.shutdown()
        await cp.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
