"""Connectors: converge the actual worker set to the planner's target.

Reference analogs: `kubernetes_connector.py:172` patches the
DynamoGraphDeployment CRD; `circusd.py:360` manages local processes.
`LocalConnector` is the latter for our runtime: it spawns
`python -m dynamo_tpu.worker` subprocesses and drains them with SIGTERM
(the worker's own handler leaves routing instantly, then finishes
in-flight streams — worker/main.py)."""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import subprocess
import sys
import threading
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class LocalConnector:
    def __init__(self, control_plane_addr: str, *,
                 worker_args: Optional[List[str]] = None,
                 env: Optional[dict] = None,
                 log_dir: str = "/tmp",
                 drain_timeout_s: float = 45.0,
                 role_worker_args: Optional[Dict[str, List[str]]] = None,
                 ) -> None:
        """`worker_args`: extra argv after `--control-plane ADDR`
        (e.g. ["--mocker", "--model-name", "m"]).

        `role_worker_args` (ISSUE 16, heterogeneous cells): role →
        ADDITIONAL argv appended when `add_worker(role=...)` spawns that
        role's slice — typically a `--slice` spec per role, e.g.
        {"prefill": ["--slice", "sp2xtp2,int8,role=prefill"],
         "decode":  ["--slice", "tp2,int8,role=decode"]} — so the
        planner deploys a big-prefill/small-decode cell from ONE
        connector.  Spawned procs remember their role; `replicas(role=)`
        and `remove_worker(role=)` filter on it.

        `drain_timeout_s`: scale-down budget — SIGTERM starts the
        worker's KV-migrating drain (worker/main.py `--drain on`); a
        worker that hasn't exited inside the budget is force-killed,
        counted and logged DISTINCTLY from a clean drain (ISSUE 15: the
        two used to read as one SIGTERM in the logs, hiding every drain
        regression)."""
        self.control_plane_addr = control_plane_addr
        self.worker_args = list(worker_args or [])
        self.role_worker_args = {
            r: list(a) for r, a in (role_worker_args or {}).items()}
        self.env = dict(env if env is not None else os.environ)
        self.log_dir = log_dir
        self.drain_timeout_s = drain_timeout_s
        # Scale-down outcome accounting (planner_metrics_text exports
        # these as dynamo_planner_drains_total{outcome}).
        self.clean_drains = 0
        self.force_kills = 0
        self._procs: List[subprocess.Popen] = []
        # add_worker's spawn thread appends while _reap (event loop,
        # via a concurrent /metrics scrape) rebuilds the list — both
        # sides serialize here or a freshly spawned proc can vanish
        # from the roster and never be SIGTERMed at shutdown.
        self._procs_lock = threading.Lock()
        self._seq = 0

    def replicas(self, role: Optional[str] = None) -> int:
        self._reap()
        if role is None:
            return len(self._procs)
        return sum(1 for p in self._procs
                   if getattr(p, "_role", None) == role)

    @staticmethod
    def _close_log(proc) -> None:
        log = getattr(proc, "_logfile", None)
        if log is not None and not log.closed:
            log.close()

    def _reap(self) -> None:
        with self._procs_lock:
            live = []
            for p in self._procs:
                if p.poll() is None:
                    live.append(p)
                else:
                    self._close_log(p)
            self._procs = live

    async def add_worker(self, role: Optional[str] = None) -> None:
        self._seq += 1
        log_path = os.path.join(
            self.log_dir,
            f"dynamo_planner_worker_{os.getpid()}_{self._seq}.log")
        extra = self.role_worker_args.get(role, []) if role else []

        def spawn():
            # Log-file open AND fork+exec both block (slow/network
            # storage, page-cache-cold python): the planner shares its
            # event loop with the metrics server, and neither may stall
            # scrapes (dynamo-lint DL002).  The proc registers into
            # _procs HERE, on the spawn thread — if the awaiting
            # coroutine is cancelled mid-await (planner stop), the
            # thread still completes and shutdown() can reap the child
            # instead of orphaning it.
            log = open(log_path, "w")
            proc = subprocess.Popen(
                [sys.executable, "-m", "dynamo_tpu.worker",
                 "--control-plane", self.control_plane_addr,
                 *self.worker_args, *extra],
                env=self.env, stdout=log, stderr=subprocess.STDOUT)
            proc._logfile = log  # type: ignore[attr-defined]
            proc._role = role  # type: ignore[attr-defined]
            with self._procs_lock:
                self._procs.append(proc)
            return proc

        proc = await asyncio.to_thread(spawn)
        logger.info("connector: spawned %s worker pid %d",
                    role or "plain", proc.pid)

    async def remove_worker(self, role: Optional[str] = None) -> None:
        """Scale-down = drain, not drop: SIGTERM starts the worker's
        KV-migrating drain (it leaves routing instantly, hands each
        in-flight stream to a peer with its sealed KV, lingers for the
        peers' pulls, then exits).  This call WAITS for drain-complete —
        worker exit — up to `drain_timeout_s`; only then does the reaper
        escalate to SIGKILL, logging and counting the force-kill
        distinctly from a clean drain.

        `role` drains the newest worker of THAT role (heterogeneous
        cells must thin the pool the planner named, not whichever proc
        spawned last); no such worker → no-op."""
        self._reap()
        with self._procs_lock:
            proc = None
            for i in range(len(self._procs) - 1, -1, -1):
                if role is None or getattr(self._procs[i], "_role",
                                           None) == role:
                    proc = self._procs.pop(i)
                    break
            if proc is None:
                return
        logger.info("connector: draining worker pid %d (budget %.1fs)",
                    proc.pid, self.drain_timeout_s)
        proc.send_signal(signal.SIGTERM)
        deadline = (asyncio.get_running_loop().time()
                    + max(0.0, self.drain_timeout_s))
        while proc.poll() is None \
                and asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.1)
        if proc.poll() is None:
            self.force_kills += 1
            logger.error(
                "connector: worker pid %d did NOT drain within %.1fs — "
                "force-killing (SIGKILL); its in-flight KV is lost and "
                "peers fall back to re-prefill", proc.pid,
                self.drain_timeout_s)
            proc.kill()
            await asyncio.to_thread(proc.wait, 10)
        else:
            self.clean_drains += 1
            logger.info("connector: worker pid %d drained cleanly "
                        "(rc=%s)", proc.pid, proc.returncode)
        self._close_log(proc)

    async def shutdown(self) -> None:
        self._reap()
        with self._procs_lock:
            procs, self._procs = self._procs, []
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            # Off-loop: a slow-draining worker may take the full 15 s,
            # and N of them would freeze the shared planner/metrics
            # loop for 15*N s (same DL002 bug class as add_worker's
            # spawn — receiver-method calls like proc.wait() are a
            # documented blind spot of the linter rule, so this is
            # discipline, not gate-enforced).
            try:
                await asyncio.to_thread(p.wait, 15)
            except subprocess.TimeoutExpired:
                p.kill()
            self._close_log(p)
