"""Connectors: converge the actual worker set to the planner's target.

Reference analogs: `kubernetes_connector.py:172` patches the
DynamoGraphDeployment CRD; `circusd.py:360` manages local processes.
`LocalConnector` is the latter for our runtime: it spawns
`python -m dynamo_tpu.worker` subprocesses and drains them with SIGTERM
(the worker's own handler leaves routing instantly, then finishes
in-flight streams — worker/main.py)."""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
from typing import List, Optional

logger = logging.getLogger(__name__)


class LocalConnector:
    def __init__(self, control_plane_addr: str, *,
                 worker_args: Optional[List[str]] = None,
                 env: Optional[dict] = None,
                 log_dir: str = "/tmp") -> None:
        """`worker_args`: extra argv after `--control-plane ADDR`
        (e.g. ["--mocker", "--model-name", "m"])."""
        self.control_plane_addr = control_plane_addr
        self.worker_args = list(worker_args or [])
        self.env = dict(env if env is not None else os.environ)
        self.log_dir = log_dir
        self._procs: List[subprocess.Popen] = []
        self._seq = 0

    def replicas(self) -> int:
        self._reap()
        return len(self._procs)

    @staticmethod
    def _close_log(proc) -> None:
        log = getattr(proc, "_logfile", None)
        if log is not None and not log.closed:
            log.close()

    def _reap(self) -> None:
        live = []
        for p in self._procs:
            if p.poll() is None:
                live.append(p)
            else:
                self._close_log(p)
        self._procs = live

    async def add_worker(self) -> None:
        self._seq += 1
        log = open(os.path.join(
            self.log_dir,
            f"dynamo_planner_worker_{os.getpid()}_{self._seq}.log"), "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--control-plane", self.control_plane_addr,
             *self.worker_args],
            env=self.env, stdout=log, stderr=subprocess.STDOUT)
        proc._logfile = log  # type: ignore[attr-defined]
        self._procs.append(proc)
        logger.info("connector: spawned worker pid %d", proc.pid)

    async def remove_worker(self) -> None:
        """Drain the newest worker: SIGTERM → it leaves routing and
        finishes in-flight streams before exiting."""
        self._reap()
        if not self._procs:
            return
        proc = self._procs.pop()
        logger.info("connector: draining worker pid %d", proc.pid)
        proc.send_signal(signal.SIGTERM)
        # Reap off-loop: the drain can take as long as its longest
        # in-flight stream.
        import asyncio

        async def reap():
            while proc.poll() is None:
                await asyncio.sleep(0.5)
            self._close_log(proc)

        asyncio.get_running_loop().create_task(reap())

    async def shutdown(self) -> None:
        self._reap()
        for p in self._procs:
            p.send_signal(signal.SIGTERM)
        for p in self._procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
            self._close_log(p)
        self._procs.clear()
