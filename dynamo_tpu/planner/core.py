"""LoadPlanner: observe → predict → target → converge.

The decision skeleton of the reference's `planner_core.py:241-318`
specialised to load-based scaling (its SLA variant swaps the target
formula for TTFT/ITL interpolation; same loop)."""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.llm.kv_router.watcher import LoadMetricsWatcher
from dynamo_tpu.planner.predictor import make_predictor

logger = logging.getLogger(__name__)


@dataclass
class PlannerConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    kv_high: float = 0.8        # predicted usage above → scale up
    kv_low: float = 0.3         # redistributable usage below → scale down
    adjustment_interval: float = 5.0
    metrics_stale_secs: float = 10.0
    predictor: str = "moving_average"
    # SLO bias (runtime/slo.py): when a watched /debug/slo reports a
    # fast-window burn rate at or above this, scale up even though KV
    # usage looks fine — latency SLOs burn before memory fills (the
    # AIBrix-style signal the load moving-average can't see).  Scale-
    # DOWN is additionally vetoed while any burn is >= 1.0 (actively
    # consuming budget is the wrong moment to shed capacity).
    slo_burn_scale_up: float = 2.0
    # A /debug/slo payload older than this exerts no pressure: a crashed
    # SLO source must not pin the fleet at max_replicas forever on its
    # last (possibly mid-incident) reading.
    slo_stale_secs: float = 60.0


class LoadPlanner:
    """Watches `load_metrics`, steps a replica target, drives a connector.

    `connector` contract: `replicas() -> int` (current), plus
    `add_worker()` / `remove_worker()` (one step each, async).

    `slo_url`: a /debug/slo endpoint (frontend or worker) polled each
    adjustment interval; its burn rates bias scaling per
    PlannerConfig.slo_burn_scale_up."""

    def __init__(self, cp, connector,
                 config: Optional[PlannerConfig] = None,
                 slo_url: Optional[str] = None) -> None:
        self.cp = cp
        self.connector = connector
        self.config = config or PlannerConfig()
        self.slo_url = slo_url
        self._slo: Optional[dict] = None       # last /debug/slo payload
        self._slo_ts: float = 0.0              # when it was fetched
        self._watcher = LoadMetricsWatcher(
            cp, stale_secs=self.config.metrics_stale_secs, name="planner")
        self._usage_pred = make_predictor(self.config.predictor)
        self._waiting_pred = make_predictor(self.config.predictor)
        self._tasks = []
        # In-flight scale-down: remove_worker waits out the worker's
        # KV-migrating drain (up to the connector's drain_timeout_s), so
        # it runs as a background task — the adjustment loop must stay
        # responsive to scale-UP pressure mid-drain.
        self._drain_task: Optional[asyncio.Task] = None
        self.decisions: list = []              # (ts, kind, reason) log

    async def start(self) -> None:
        await self._watcher.start()
        self._tasks = [asyncio.create_task(self._loop())]

    async def stop(self) -> None:
        await self._watcher.stop()
        if self._drain_task is not None and not self._drain_task.done():
            # Let an in-flight drain finish (bounded by the connector's
            # own timeout) rather than orphan a half-drained worker.
            try:
                await self._drain_task
            except Exception:
                logger.exception("planner: in-flight drain failed at stop")
        for t in self._tasks:
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass

    def _observe(self):
        fresh = list(self._watcher.fresh().values())
        if not fresh:
            return None
        usage = sum(m.kv_stats.gpu_cache_usage_perc
                    for m in fresh) / len(fresh)
        waiting = sum(m.worker_stats.num_requests_waiting for m in fresh)
        return len(fresh), usage, waiting

    def slo_pressure(self) -> float:
        """Worst fast-window burn rate from the last /debug/slo poll
        (0.0 with no SLO source configured, monitor disabled, or a
        payload past slo_stale_secs — dead sources stop steering)."""
        from dynamo_tpu.runtime.slo import max_burn

        if (self._slo is not None
                and time.monotonic() - self._slo_ts
                > self.config.slo_stale_secs):
            return 0.0
        return max_burn(self._slo)

    def plan_step(self) -> Optional[str]:
        """One planning decision from current predictions; returns
        "up" | "down" | None.  Synchronous and side-effect-free on the
        connector (unit-testable; the loop applies it)."""
        draining = (self._drain_task is not None
                    and not self._drain_task.done())
        replicas = self.connector.replicas()
        if replicas < self.config.min_replicas:
            # Floor check needs no observations — it's how the fleet
            # bootstraps (no worker yet → no metrics yet).
            return "up"
        burn = self.slo_pressure()
        if (burn >= self.config.slo_burn_scale_up
                and replicas < self.config.max_replicas):
            # SLO bias: budget is burning NOW; don't wait for the KV
            # moving-average to catch up.
            return "up"
        obs = self._observe()
        if obs is None:
            return None
        n_reporting, usage, waiting = obs
        self._usage_pred.add_data_point(usage)
        self._waiting_pred.add_data_point(waiting)
        p_usage = self._usage_pred.predict_next()
        p_waiting = self._waiting_pred.predict_next()
        if ((p_usage > self.config.kv_high or p_waiting >= 1.0)
                and replicas < self.config.max_replicas):
            return "up"
        # Scale down only if the survivors could absorb the load under
        # kv_low: usage*n / (n-1) stays below the low-water mark — never
        # while an SLO is actively burning budget, and one drain at a
        # time (a scale-down is committed until its background
        # remove_worker lands; stacking removals would over-shed).
        if (not draining
                and replicas > self.config.min_replicas and p_waiting < 1.0
                and n_reporting > 1 and burn < 1.0
                and p_usage * n_reporting / (n_reporting - 1)
                < self.config.kv_low):
            return "down"
        return None

    async def _fetch_slo(self) -> None:
        """Refresh the /debug/slo view; keeps the last payload on
        transient fetch errors (stale pressure beats none mid-incident)."""
        if not self.slo_url:
            return
        import aiohttp

        try:
            timeout = aiohttp.ClientTimeout(total=2.0)
            async with aiohttp.ClientSession(timeout=timeout) as s:
                async with s.get(self.slo_url) as resp:
                    if resp.status == 200:
                        self._slo = await resp.json()
                        self._slo_ts = time.monotonic()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            logger.debug("slo poll of %s failed; keeping last payload",
                         self.slo_url)

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.adjustment_interval)
            try:
                await self._fetch_slo()
                decision = self.plan_step()
                if decision == "up":
                    self.decisions.append((time.monotonic(), "up",
                                           self._reason()))
                    logger.info("planner: scaling UP (%s)", self._reason())
                    await self.connector.add_worker()
                elif decision == "down":
                    self.decisions.append((time.monotonic(), "down",
                                           self._reason()))
                    logger.info("planner: scaling DOWN (%s)", self._reason())
                    # Background: remove_worker waits out the drain
                    # (plan_step holds further decisions off until it
                    # lands; scale-up pressure still gets polled).
                    self._drain_task = asyncio.create_task(
                        self.connector.remove_worker())
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("planner: adjustment failed; continuing")

    def _reason(self) -> str:
        reason = (f"usage~{self._usage_pred.predict_next():.2f} "
                  f"waiting~{self._waiting_pred.predict_next():.1f} "
                  f"replicas={self.connector.replicas()}")
        burn = self.slo_pressure()
        if burn > 0:
            reason += f" slo_burn~{burn:.1f}"
        return reason


def planner_metrics_text(planner, connector) -> str:
    """Prometheus text for the planner's status server (`/metrics` on
    `python -m dynamo_tpu.planner --metrics-port`): replica count,
    scaling-decision tallies, and the predictors' next-step view.  Works
    for both LoadPlanner and SlaPlanner (fields read defensively — the
    SLA variant keeps its own predictor names)."""
    lines = []
    try:
        lines.append(f"dynamo_planner_replicas {connector.replicas()}")
    except Exception:
        # dynamo-lint: disable=DL003 best-effort metrics text
        pass  # connector variant without replicas(): omit the series
    decisions = getattr(planner, "decisions", []) or []
    ups = sum(1 for d in decisions if len(d) > 1 and d[1] == "up")
    downs = sum(1 for d in decisions if len(d) > 1 and d[1] == "down")
    lines.append('dynamo_planner_decisions_total{direction="up"} %d' % ups)
    lines.append('dynamo_planner_decisions_total{direction="down"} %d'
                 % downs)
    # Scale-down outcomes (ISSUE 15): clean KV-migrating drains vs
    # drain-timeout force-kills — a rising force_kill count is the
    # "drains are broken" alarm, previously invisible.
    for attr, outcome in (("clean_drains", "clean"),
                          ("force_kills", "force_kill")):
        n = getattr(connector, attr, None)
        if n is not None:
            lines.append(
                'dynamo_planner_drains_total{outcome="%s"} %d'
                % (outcome, n))
    for attr, name in (("_usage_pred", "kv_usage"),
                       ("_waiting_pred", "requests_waiting")):
        pred = getattr(planner, attr, None)
        if pred is None:
            continue
        try:
            lines.append('dynamo_planner_predicted{metric="%s"} %s'
                         % (name, pred.predict_next()))
        except Exception:
            # dynamo-lint: disable=DL003 best-effort metrics text
            pass  # predictor not warmed up yet: omit the series
    return "\n".join(lines) + "\n"
