"""LoadPlanner: observe → predict → target → converge.

The decision skeleton of the reference's `planner_core.py:241-318`
specialised to load-based scaling (its SLA variant swaps the target
formula for TTFT/ITL interpolation; same loop)."""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from dynamo_tpu.fleet.topology import SliceSpec, validate_placement
from dynamo_tpu.llm.kv_router.watcher import LoadMetricsWatcher
from dynamo_tpu.planner.predictor import make_predictor

logger = logging.getLogger(__name__)


@dataclass
class PlannerConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    kv_high: float = 0.8        # predicted usage above → scale up
    kv_low: float = 0.3         # redistributable usage below → scale down
    adjustment_interval: float = 5.0
    metrics_stale_secs: float = 10.0
    predictor: str = "moving_average"
    # Heterogeneous disagg cell (ISSUE 16): non-empty → every scale
    # decision names one of these roles, each spawned with its own mesh
    # (connector role_worker_args, e.g. a big sp-prefill slice and a
    # small tp+int8-decode slice — the DistServe/Splitwise phase-fitted
    # pool shape).  Empty = aggregated fleet, decisions role-less.
    roles: Tuple[str, ...] = ()
    # SLO bias (runtime/slo.py): when a watched /debug/slo reports a
    # fast-window burn rate at or above this, scale up even though KV
    # usage looks fine — latency SLOs burn before memory fills (the
    # AIBrix-style signal the load moving-average can't see).  Scale-
    # DOWN is additionally vetoed while any burn is >= 1.0 (actively
    # consuming budget is the wrong moment to shed capacity).
    slo_burn_scale_up: float = 2.0
    # A /debug/slo payload older than this exerts no pressure: a crashed
    # SLO source must not pin the fleet at max_replicas forever on its
    # last (possibly mid-incident) reading.
    slo_stale_secs: float = 60.0


class LoadPlanner:
    """Watches `load_metrics`, steps a replica target, drives a connector.

    `connector` contract: `replicas() -> int` (current), plus
    `add_worker()` / `remove_worker()` (one step each, async).

    `slo_url`: a /debug/slo endpoint (frontend or worker) polled each
    adjustment interval; its burn rates bias scaling per
    PlannerConfig.slo_burn_scale_up."""

    def __init__(self, cp, connector,
                 config: Optional[PlannerConfig] = None,
                 slo_url: Optional[str] = None,
                 slices_fn: Optional[Callable[[], Dict]] = None) -> None:
        self.cp = cp
        self.connector = connector
        self.config = config or PlannerConfig()
        self.slo_url = slo_url
        # Topology source: worker id → published SliceSpec (or its wire
        # dict), usually wired to the runtime client's instance records.
        # None = no topology view; role decisions fall back to replica
        # counts alone.
        self._slices_fn = slices_fn
        self._slo: Optional[dict] = None       # last /debug/slo payload
        self._slo_ts: float = 0.0              # when it was fetched
        self._watcher = LoadMetricsWatcher(
            cp, stale_secs=self.config.metrics_stale_secs, name="planner")
        self._usage_pred = make_predictor(self.config.predictor)
        self._waiting_pred = make_predictor(self.config.predictor)
        self._tasks = []
        # In-flight scale-down: remove_worker waits out the worker's
        # KV-migrating drain (up to the connector's drain_timeout_s), so
        # it runs as a background task — the adjustment loop must stay
        # responsive to scale-UP pressure mid-drain.
        self._drain_task: Optional[asyncio.Task] = None
        self.decisions: list = []              # (ts, kind, reason) log

    async def start(self) -> None:
        await self._watcher.start()
        self._tasks = [asyncio.create_task(self._loop())]

    async def stop(self) -> None:
        await self._watcher.stop()
        if self._drain_task is not None and not self._drain_task.done():
            # Let an in-flight drain finish (bounded by the connector's
            # own timeout) rather than orphan a half-drained worker.
            try:
                await self._drain_task
            except Exception:
                logger.exception("planner: in-flight drain failed at stop")
        for t in self._tasks:
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass

    def _observe(self):
        fresh = list(self._watcher.fresh().values())
        if not fresh:
            return None
        usage = sum(m.kv_stats.gpu_cache_usage_perc
                    for m in fresh) / len(fresh)
        waiting = sum(m.worker_stats.num_requests_waiting for m in fresh)
        return len(fresh), usage, waiting

    def slo_pressure(self) -> float:
        """Worst fast-window burn rate from the last /debug/slo poll
        (0.0 with no SLO source configured, monitor disabled, or a
        payload past slo_stale_secs — dead sources stop steering)."""
        from dynamo_tpu.runtime.slo import max_burn

        if (self._slo is not None
                and time.monotonic() - self._slo_ts
                > self.config.slo_stale_secs):
            return 0.0
        return max_burn(self._slo)

    # -- topology reads (ISSUE 16) -----------------------------------------

    def topology(self) -> Dict[object, Optional[SliceSpec]]:
        """Published slice topology: worker id → SliceSpec (None for
        workers that publish nothing).  Tolerant of a failing source —
        the planner must keep scaling a fleet whose discovery hiccups."""
        if self._slices_fn is None:
            return {}
        try:
            raw = self._slices_fn() or {}
        except Exception:
            logger.exception("planner: topology source failed; planning "
                             "topology-blind this step")
            return {}
        return {
            w: (s if isinstance(s, SliceSpec) or s is None
                else SliceSpec.from_dict(s))
            for w, s in raw.items()
        }

    def placement_ok(self, role: str, worker_id=None,
                     spec: Optional[SliceSpec] = None) -> Tuple[bool, str]:
        """Is assigning `role` work to this worker topology-sane?  THE
        planner's SliceSpec consult (fleet.topology.validate_placement):
        a mesh-blind decision — decode role on a dedicated prefill
        slice — is refused here, and the bench gate fabricates exactly
        that decision to prove the consult happens."""
        if spec is None and worker_id is not None:
            spec = self.topology().get(worker_id)
        return validate_placement(role, spec)

    def _role_replicas(self, role: str) -> int:
        try:
            return self.connector.replicas(role=role)
        except TypeError:
            # Role-less connector: every replica counts for every role.
            return self.connector.replicas()

    def plan_role(self, decision: Optional[str]) -> Optional[str]:
        """Which role a scale decision targets in heterogeneous-cell
        mode (config.roles): scale-up fills the thinnest pool first
        (declaration order breaks ties — list prefill first to absorb
        ISL pressure); scale-down thins the fattest pool and NEVER
        drops a role's last replica (a cell without a prefill slice
        serves nothing).  None in aggregated mode."""
        if not self.config.roles or decision is None:
            return None
        counts = {r: self._role_replicas(r) for r in self.config.roles}
        if decision == "up":
            order = {r: i for i, r in enumerate(self.config.roles)}
            return min(self.config.roles,
                       key=lambda r: (counts[r], order[r]))
        victims = [r for r in self.config.roles if counts[r] > 1]
        if not victims:
            return None
        return max(victims, key=lambda r: counts[r])

    def plan_step(self) -> Optional[str]:
        """One planning decision from current predictions; returns
        "up" | "down" | None.  Synchronous and side-effect-free on the
        connector (unit-testable; the loop applies it).

        Heterogeneous-cell mode additionally consults the published
        SliceSpecs: a "down" that would leave some role with no
        placeable slice among the survivors is vetoed (plan_role names
        the victim role; `topology()` + `fleet.topology.place_role`
        check the survivors)."""
        decision = self._plan_step_load()
        if decision == "down" and self.config.roles:
            role = self.plan_role("down")
            if role is None:
                return None  # every role at its floor
            top = self.topology()
            if top:
                from dynamo_tpu.fleet.topology import place_role

                survivors = dict(top)
                # Drop ONE published slice of the victim role (the
                # connector pops newest-first; any same-role member is
                # equivalent for the coverage check).
                for w, s in top.items():
                    if s is not None and s.role == role:
                        survivors.pop(w)
                        break
                for r in self.config.roles:
                    if place_role(r, survivors) is None:
                        logger.info(
                            "planner: scale-down of a %s slice vetoed — "
                            "no surviving slice could serve role %r",
                            role, r)
                        return None
        return decision

    def _plan_step_load(self) -> Optional[str]:
        draining = (self._drain_task is not None
                    and not self._drain_task.done())
        replicas = self.connector.replicas()
        if replicas < self.config.min_replicas:
            # Floor check needs no observations — it's how the fleet
            # bootstraps (no worker yet → no metrics yet).
            return "up"
        burn = self.slo_pressure()
        if (burn >= self.config.slo_burn_scale_up
                and replicas < self.config.max_replicas):
            # SLO bias: budget is burning NOW; don't wait for the KV
            # moving-average to catch up.
            return "up"
        obs = self._observe()
        if obs is None:
            return None
        n_reporting, usage, waiting = obs
        self._usage_pred.add_data_point(usage)
        self._waiting_pred.add_data_point(waiting)
        p_usage = self._usage_pred.predict_next()
        p_waiting = self._waiting_pred.predict_next()
        if ((p_usage > self.config.kv_high or p_waiting >= 1.0)
                and replicas < self.config.max_replicas):
            return "up"
        # Scale down only if the survivors could absorb the load under
        # kv_low: usage*n / (n-1) stays below the low-water mark — never
        # while an SLO is actively burning budget, and one drain at a
        # time (a scale-down is committed until its background
        # remove_worker lands; stacking removals would over-shed).
        if (not draining
                and replicas > self.config.min_replicas and p_waiting < 1.0
                and n_reporting > 1 and burn < 1.0
                and p_usage * n_reporting / (n_reporting - 1)
                < self.config.kv_low):
            return "down"
        return None

    async def _fetch_slo(self) -> None:
        """Refresh the /debug/slo view; keeps the last payload on
        transient fetch errors (stale pressure beats none mid-incident)."""
        if not self.slo_url:
            return
        import aiohttp

        try:
            timeout = aiohttp.ClientTimeout(total=2.0)
            async with aiohttp.ClientSession(timeout=timeout) as s:
                async with s.get(self.slo_url) as resp:
                    if resp.status == 200:
                        self._slo = await resp.json()
                        self._slo_ts = time.monotonic()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            logger.debug("slo poll of %s failed; keeping last payload",
                         self.slo_url)

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.adjustment_interval)
            try:
                await self._fetch_slo()
                decision = self.plan_step()
                role = self.plan_role(decision)
                if decision == "up":
                    self.decisions.append((time.monotonic(), "up",
                                           self._reason(role)))
                    logger.info("planner: scaling UP (%s)",
                                self._reason(role))
                    await self._apply_add(role)
                elif decision == "down":
                    self.decisions.append((time.monotonic(), "down",
                                           self._reason(role)))
                    logger.info("planner: scaling DOWN (%s)",
                                self._reason(role))
                    # Background: remove_worker waits out the drain
                    # (plan_step holds further decisions off until it
                    # lands; scale-up pressure still gets polled).
                    self._drain_task = asyncio.create_task(
                        self._apply_remove(role))
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("planner: adjustment failed; continuing")

    async def _apply_add(self, role: Optional[str]) -> None:
        if role is None:
            await self.connector.add_worker()
            return
        try:
            await self.connector.add_worker(role=role)
        except TypeError:
            # Role-less connector under a roles config: spawn the plain
            # worker rather than stall the fleet.
            await self.connector.add_worker()

    async def _apply_remove(self, role: Optional[str]) -> None:
        if role is None:
            await self.connector.remove_worker()
            return
        try:
            await self.connector.remove_worker(role=role)
        except TypeError:
            await self.connector.remove_worker()

    def _reason(self, role: Optional[str] = None) -> str:
        reason = (f"usage~{self._usage_pred.predict_next():.2f} "
                  f"waiting~{self._waiting_pred.predict_next():.1f} "
                  f"replicas={self.connector.replicas()}")
        if role is not None:
            reason += f" role={role}"
        burn = self.slo_pressure()
        if burn > 0:
            reason += f" slo_burn~{burn:.1f}"
        return reason


def planner_metrics_text(planner, connector) -> str:
    """Prometheus text for the planner's status server (`/metrics` on
    `python -m dynamo_tpu.planner --metrics-port`): replica count,
    scaling-decision tallies, and the predictors' next-step view.  Works
    for both LoadPlanner and SlaPlanner (fields read defensively — the
    SLA variant keeps its own predictor names)."""
    lines = []
    try:
        lines.append(f"dynamo_planner_replicas {connector.replicas()}")
    except Exception:
        # dynamo-lint: disable=DL003 best-effort metrics text
        pass  # connector variant without replicas(): omit the series
    # Heterogeneous-cell mode: per-role pool sizes (ISSUE 16).
    for role in (getattr(getattr(planner, "config", None), "roles", ())
                 or ()):
        try:
            lines.append('dynamo_planner_replicas{role="%s"} %d'
                         % (role, connector.replicas(role=role)))
        except Exception:
            # dynamo-lint: disable=DL003 best-effort metrics text
            pass  # role-less connector: omit the per-role series
    decisions = getattr(planner, "decisions", []) or []
    ups = sum(1 for d in decisions if len(d) > 1 and d[1] == "up")
    downs = sum(1 for d in decisions if len(d) > 1 and d[1] == "down")
    lines.append('dynamo_planner_decisions_total{direction="up"} %d' % ups)
    lines.append('dynamo_planner_decisions_total{direction="down"} %d'
                 % downs)
    # Scale-down outcomes (ISSUE 15): clean KV-migrating drains vs
    # drain-timeout force-kills — a rising force_kill count is the
    # "drains are broken" alarm, previously invisible.
    for attr, outcome in (("clean_drains", "clean"),
                          ("force_kills", "force_kill")):
        n = getattr(connector, attr, None)
        if n is not None:
            lines.append(
                'dynamo_planner_drains_total{outcome="%s"} %d'
                % (outcome, n))
    for attr, name in (("_usage_pred", "kv_usage"),
                       ("_waiting_pred", "requests_waiting")):
        pred = getattr(planner, attr, None)
        if pred is None:
            continue
        try:
            lines.append('dynamo_planner_predicted{metric="%s"} %s'
                         % (name, pred.predict_next()))
        except Exception:
            # dynamo-lint: disable=DL003 best-effort metrics text
            pass  # predictor not warmed up yet: omit the series
    return "\n".join(lines) + "\n"
