"""Perf interpolators for the SLA planner.

Role of the reference's `planner/utils/perf_interpolation.py` (cubic
scipy interpolators over pre-deployment profiling npz): map predicted
load onto the profiled perf surface to get expected TTFT/ITL and
achievable throughput per chip.  Re-designed on plain numpy linear
interpolation — the profile grids are dense enough that cubic buys
nothing, and scipy stays out of the serving image.

Profile format (produced by planner/profiler.py, stored as JSON):

    {"prefill": {"isl": [...], "ttft_s": [...], "tok_s_per_chip": [...]},
     "decode":  {"kv_usage": [...], "context": [...],
                 "itl_s": [[...]], "tok_s_per_chip": [[...]]}}

Decode grids are [len(context), len(kv_usage)] — context (row) by
kv-load (column), mirroring the reference's 2D (kv_usage x context)
surface.
"""

from __future__ import annotations

import json
from typing import Dict

import numpy as np


class PrefillInterpolator:
    """isl → expected TTFT and prefill throughput/chip."""

    def __init__(self, profile: Dict) -> None:
        p = profile["prefill"]
        self.isl = np.asarray(p["isl"], np.float64)
        self.ttft = np.asarray(p["ttft_s"], np.float64)
        self.thpt = np.asarray(p["tok_s_per_chip"], np.float64)
        order = np.argsort(self.isl)
        self.isl, self.ttft, self.thpt = (
            self.isl[order], self.ttft[order], self.thpt[order])

    def interpolate_ttft(self, isl: float) -> float:
        return float(np.interp(isl, self.isl, self.ttft))

    def interpolate_thpt_per_chip(self, isl: float) -> float:
        return float(np.interp(isl, self.isl, self.thpt))


class DecodeInterpolator:
    """(kv_usage, context) → expected ITL and decode throughput/chip."""

    def __init__(self, profile: Dict) -> None:
        d = profile["decode"]
        self.kv = np.asarray(d["kv_usage"], np.float64)
        self.ctx = np.asarray(d["context"], np.float64)
        self.itl = np.asarray(d["itl_s"], np.float64)      # [ctx, kv]
        self.thpt = np.asarray(d["tok_s_per_chip"], np.float64)
        if self.itl.shape != (len(self.ctx), len(self.kv)):
            raise ValueError(f"decode grid shape {self.itl.shape} != "
                             f"({len(self.ctx)}, {len(self.kv)})")

    def _ctx_row(self, context: float) -> int:
        return int(np.argmin(np.abs(self.ctx - context)))

    def interpolate_itl(self, kv_usage: float, context: float) -> float:
        row = self._ctx_row(context)
        return float(np.interp(kv_usage, self.kv, self.itl[row]))

    def interpolate_thpt_per_chip(self, kv_usage: float,
                                  context: float) -> float:
        row = self._ctx_row(context)
        return float(np.interp(kv_usage, self.kv, self.thpt[row]))

    def find_best_throughput_per_chip(self, itl: float,
                                      context: float) -> float:
        """Highest-load throughput whose ITL still meets the target —
        scanned from the loaded end because interpolated ITL need not be
        monotonic (reference `find_best_throughput_per_gpu`)."""
        row = self._ctx_row(context)
        for col in range(len(self.kv) - 1, -1, -1):
            if self.itl[row, col] <= itl:
                return float(self.thpt[row, col])
        return float(self.thpt[row, 0])


def load_profile(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)


def save_profile(profile: Dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(profile, f)
