"""Load predictors (reference `planner/utils/load_predictor.py:159`).

The reference ships constant / ARIMA / Prophet; the constant and
moving-average predictors cover the load-planner's needs without the
heavyweight deps (ARIMA/Prophet are not in this image — the predictor
interface is where they'd slot in)."""

from __future__ import annotations

from collections import deque
from typing import Deque


class ConstantPredictor:
    """Next value = last observation."""

    def __init__(self) -> None:
        self._last = 0.0

    def add_data_point(self, value: float) -> None:
        self._last = float(value)

    def predict_next(self) -> float:
        return self._last


class MovingAveragePredictor:
    """Next value = mean of the last `window` observations."""

    def __init__(self, window: int = 5) -> None:
        self._buf: Deque[float] = deque(maxlen=window)

    def add_data_point(self, value: float) -> None:
        self._buf.append(float(value))

    def predict_next(self) -> float:
        return sum(self._buf) / len(self._buf) if self._buf else 0.0


def make_predictor(kind: str = "moving_average", **kw):
    if kind == "constant":
        return ConstantPredictor()
    if kind == "moving_average":
        return MovingAveragePredictor(**kw)
    raise ValueError(f"unknown predictor {kind!r} "
                     "(have: constant, moving_average)")
