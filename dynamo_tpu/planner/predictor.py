"""Load predictors (reference `planner/utils/load_predictor.py:159`).

The reference ships constant / ARIMA / Prophet; the constant and
moving-average predictors cover the load-planner's needs without the
heavyweight deps (ARIMA/Prophet are not in this image — the predictor
interface is where they'd slot in)."""

from __future__ import annotations

from collections import deque
from typing import Deque


class ConstantPredictor:
    """Next value = last observation."""

    def __init__(self) -> None:
        self._last = 0.0

    def add_data_point(self, value: float) -> None:
        self._last = float(value)

    def predict_next(self) -> float:
        return self._last


class MovingAveragePredictor:
    """Next value = mean of the last `window` observations."""

    def __init__(self, window: int = 5) -> None:
        self._buf: Deque[float] = deque(maxlen=window)

    def add_data_point(self, value: float) -> None:
        self._buf.append(float(value))

    def predict_next(self) -> float:
        return sum(self._buf) / len(self._buf) if self._buf else 0.0


class TrendPredictor:
    """Next value = linear extrapolation over the last `window` points —
    the lightweight stand-in for the reference's ARIMA rung (it catches
    the monotone ramps an autoscaler must lead, without statsmodels)."""

    def __init__(self, window: int = 8) -> None:
        self._buf: Deque[float] = deque(maxlen=window)

    def add_data_point(self, value: float) -> None:
        self._buf.append(float(value))

    def predict_next(self) -> float:
        n = len(self._buf)
        if n == 0:
            return 0.0
        if n == 1:
            return self._buf[0]
        xs = range(n)
        mean_x = (n - 1) / 2.0
        mean_y = sum(self._buf) / n
        cov = sum((x - mean_x) * (y - mean_y)
                  for x, y in zip(xs, self._buf))
        var = sum((x - mean_x) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))


def make_predictor(kind: str = "moving_average", **kw):
    if kind == "constant":
        return ConstantPredictor()
    if kind == "moving_average":
        return MovingAveragePredictor(**kw)
    if kind == "trend":
        return TrendPredictor(**kw)
    raise ValueError(f"unknown predictor {kind!r} "
                     "(have: constant, moving_average, trend)")
