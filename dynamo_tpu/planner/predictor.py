"""Load predictors (reference `planner/utils/load_predictor.py:159`).

The reference ships constant / ARIMA / Prophet; constant, moving-average
and the pure-NumPy AR(p) rung cover the load-planner's needs without the
heavyweight deps (statsmodels/Prophet are not in this image — ARPredictor
is the ARIMA slot: an autoregression fit by least squares catches the
periodic/diurnal structure a moving average always lags)."""

from __future__ import annotations

from collections import deque
from typing import Deque

import numpy as np


class ConstantPredictor:
    """Next value = last observation."""

    def __init__(self) -> None:
        self._last = 0.0

    def add_data_point(self, value: float) -> None:
        self._last = float(value)

    def predict_next(self) -> float:
        return self._last


class MovingAveragePredictor:
    """Next value = mean of the last `window` observations."""

    def __init__(self, window: int = 5) -> None:
        self._buf: Deque[float] = deque(maxlen=window)

    def add_data_point(self, value: float) -> None:
        self._buf.append(float(value))

    def predict_next(self) -> float:
        return sum(self._buf) / len(self._buf) if self._buf else 0.0


class TrendPredictor:
    """Next value = linear extrapolation over the last `window` points —
    the lightweight stand-in for the reference's ARIMA rung (it catches
    the monotone ramps an autoscaler must lead, without statsmodels)."""

    def __init__(self, window: int = 8) -> None:
        self._buf: Deque[float] = deque(maxlen=window)

    def add_data_point(self, value: float) -> None:
        self._buf.append(float(value))

    def predict_next(self) -> float:
        n = len(self._buf)
        if n == 0:
            return 0.0
        if n == 1:
            return self._buf[0]
        xs = range(n)
        mean_x = (n - 1) / 2.0
        mean_y = sum(self._buf) / n
        cov = sum((x - mean_x) * (y - mean_y)
                  for x, y in zip(xs, self._buf))
        var = sum((x - mean_x) ** 2 for x in xs)
        slope = cov / var if var else 0.0
        return max(0.0, mean_y + slope * (n - mean_x))


class ARPredictor:
    """AR(p) one-step predictor, least-squares fit over a sliding window
    (VERDICT r5 #9 — the pure-NumPy stand-in for the reference's ARIMA
    rung).

    Next value = c + sum_i(phi_i * y[t-i]), with (c, phi) refit on every
    prediction from the last `window` observations.  On periodic load
    (the diurnal traffic curve an autoscaler must lead) the lags carry
    the phase information a moving average destroys: MA predicts the
    recent mean and is always half a swing late; AR(p) extrapolates the
    oscillation itself.

    Falls back down the rungs while history is short: constant (1 point),
    linear trend (< 2p+2 points) — so the planner can use it from cold
    start without special-casing.
    """

    def __init__(self, order: int = 8, window: int = 128,
                 ridge: float = 1e-6) -> None:
        if order < 1:
            raise ValueError(f"order must be >= 1, got {order}")
        if window < 2 * order + 2:
            raise ValueError(
                f"window {window} too small for order {order} "
                f"(need >= {2 * order + 2})")
        self.order = order
        self.ridge = ridge
        self._buf: Deque[float] = deque(maxlen=window)
        self._trend = TrendPredictor(window=min(8, window))

    def add_data_point(self, value: float) -> None:
        v = float(value)
        self._buf.append(v)
        self._trend.add_data_point(v)

    def predict_next(self) -> float:
        n = len(self._buf)
        if n == 0:
            return 0.0
        if n < 2 * self.order + 2:
            # Not enough rows for a stable lag regression yet.
            return self._trend.predict_next()
        y = np.asarray(self._buf, dtype=np.float64)
        p = self.order
        # Lag matrix: row t predicts y[t] from [1, y[t-1] ... y[t-p]].
        rows = n - p
        X = np.empty((rows, p + 1))
        X[:, 0] = 1.0
        for i in range(1, p + 1):
            X[:, i] = y[p - i: n - i]
        target = y[p:]
        # Ridge-regularised normal equations: the lstsq of a nearly
        # constant series is rank-deficient and would swing the forecast.
        A = X.T @ X + self.ridge * np.eye(p + 1)
        try:
            coef = np.linalg.solve(A, X.T @ target)
        except np.linalg.LinAlgError:
            return self._trend.predict_next()
        nxt = coef[0] + float(coef[1:] @ y[-1: -p - 1: -1])
        # Load is nonnegative and a one-step forecast should never
        # explode past the observed envelope (an unstable fit on a short
        # noisy window can): clamp to [0, 2 * max seen in window].
        return float(min(max(nxt, 0.0), 2.0 * y.max()))


def make_predictor(kind: str = "moving_average", **kw):
    if kind == "constant":
        return ConstantPredictor()
    if kind == "moving_average":
        return MovingAveragePredictor(**kw)
    if kind == "trend":
        return TrendPredictor(**kw)
    if kind == "ar":
        return ARPredictor(**kw)
    raise ValueError(f"unknown predictor {kind!r} "
                     "(have: constant, moving_average, trend, ar)")
