"""Pre-deployment mini-profiler: sweep OUR engine, emit the SLA profile.

Role of the reference's `benchmarks/profiler/profile_sla.py` (genai-perf
sweeps of TTFT/ITL over TP x load feeding `perf_interpolation.py`): run
the real EngineCore across an ISL grid (prefill) and a context x
kv-load grid (decode), measure TTFT/ITL/throughput per chip, and write
the profile planner/interpolation.py consumes.

Chip-granular and engine-native: no HTTP in the loop, the engine is
driven synchronously the way bench.py drives it, so the profile measures
the serving step itself.  Works against any model preset on TPU or the
CPU test backend (tiny grids for CI).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Sequence

import numpy as np

from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig

logger = logging.getLogger(__name__)


def profile_engine(
    make_core,
    isl_grid: Sequence[int] = (128, 256, 512),
    context_grid: Sequence[int] = (256, 512, 1024),
    kv_grid: Sequence[float] = (0.2, 0.5, 0.8),
    decode_tokens: int = 32,
) -> Dict:
    """Sweep a fresh EngineCore per cell; returns the profile dict.

    `make_core() -> EngineCore` builds one engine per cell (with DISTINCT
    prompts per attempt so measurements never prefix-hit each other).
    Every cell runs its workload twice on the SAME core and keeps the
    SECOND measurement: the first run pays the cell's XLA compiles, and
    a compile-polluted TTFT would poison every interpolation built on it.
    """
    prefill = {"isl": [], "ttft_s": [], "tok_s_per_chip": []}
    for isl in isl_grid:
        core = make_core()
        vocab = core.config.model.vocab_size
        ttft = 0.0
        for attempt in range(2):  # warm, then measure
            rng = np.random.default_rng(isl * 7 + attempt)
            prompt = rng.integers(1, vocab, size=isl).tolist()
            core.add_request(f"p{attempt}", prompt,
                             SamplingParams(max_tokens=1))
            t0 = time.perf_counter()
            done = False
            while not done:
                for d in core.step():
                    if d.token_ids or d.finished:
                        done = True
            ttft = time.perf_counter() - t0
            while core.has_work:
                core.step()  # drain the terminal delta
        prefill["isl"].append(int(isl))
        prefill["ttft_s"].append(ttft)
        prefill["tok_s_per_chip"].append(isl / ttft if ttft > 0 else 0.0)
        logger.info("profile prefill isl=%d ttft=%.3fs", isl, ttft)

    decode = {"kv_usage": list(map(float, kv_grid)),
              "context": [int(c) for c in context_grid],
              "itl_s": [], "tok_s_per_chip": []}
    for ctx in context_grid:
        itl_row, thpt_row = [], []
        for kv in kv_grid:
            core = make_core()
            cfg = core.config
            bs = core.block_size
            vocab = cfg.model.vocab_size
            pages_per_seq = (ctx + bs - 1) // bs + 1
            usable = cfg.num_blocks - 1
            batch = max(1, int(kv * usable / pages_per_seq))
            batch = min(batch, cfg.scheduler.max_seqs)
            itl = wall = produced = 0
            for attempt in range(2):  # warm, then measure
                rng = np.random.default_rng(
                    int(ctx * 1000 + kv * 100 + attempt))
                for i in range(batch):
                    core.add_request(
                        f"d{attempt}-{i}",
                        rng.integers(1, vocab, size=ctx).tolist(),
                        SamplingParams(max_tokens=decode_tokens))
                # Prefill everything first (excluded from the ITL window).
                while core.has_pending_prefill:
                    core.step()
                produced = 0
                t0 = time.perf_counter()
                while core.has_work:
                    produced += sum(len(d.token_ids) for d in core.step())
                wall = time.perf_counter() - t0
                itl = wall / max(produced / batch, 1.0)
            itl_row.append(itl)
            thpt_row.append(produced / wall if wall > 0 else 0.0)
            logger.info("profile decode ctx=%d kv=%.2f itl=%.4fs "
                        "thpt=%.1f", ctx, kv, itl, thpt_row[-1])
        decode["itl_s"].append(itl_row)
        decode["tok_s_per_chip"].append(thpt_row)
    return {"prefill": prefill, "decode": decode}


def default_core_factory(model: str = "llama-3-1b",
                         num_blocks: int = 2048,
                         block_size: int = 64,
                         decode_window: int = 8,
                         max_seqs: int = 64):
    """EngineCore factory matching the serving geometry."""
    return cell_core_factory(model, num_blocks=num_blocks,
                             block_size=block_size,
                             decode_window=decode_window,
                             max_seqs=max_seqs)


def cell_core_factory(model: str = "llama-3-1b", *,
                      num_blocks: int = 2048,
                      block_size: int = 64,
                      decode_window: int = 8,
                      max_seqs: int = 64,
                      tp: int = 1,
                      kv_quant: str = "none",
                      spec_decode: int = 0,
                      packed_prefill: Optional[bool] = None,
                      mixed_prefill_duty: Optional[int] = None):
    """EngineCore factory over the serving feature axes PRs 6-10
    shipped — the real-engine half of one sweep cell
    (benchmarks/sla_profiler.py drives this on TPU; the mocker cells
    cover CPU CI).  `tp > 1` builds a tensor-parallel mesh the same way
    the worker's `--tp` flag does."""

    from dynamo_tpu.models.loader import resolve_model

    cfg, params, _, _ = resolve_model(model)

    def make():
        mesh = None
        if tp > 1:
            import jax

            from dynamo_tpu.parallel import MeshConfig, make_mesh
            cfg_m = MeshConfig(tp=tp)
            mesh = make_mesh(cfg_m, jax.devices()[:cfg_m.size])
        kw = {}
        if mixed_prefill_duty is not None:
            kw["mixed_prefill_duty"] = mixed_prefill_duty
        return EngineCore(EngineConfig(
            model=cfg, num_blocks=num_blocks,
            mesh=mesh,
            enable_prefix_cache=False,
            decode_window=decode_window,
            kv_quant=kv_quant,
            speculative_tokens=spec_decode,
            packed_prefill=packed_prefill,
            scheduler=SchedulerConfig(
                max_seqs=max_seqs, block_size=block_size), **kw),
            params=params)

    return make


def main(argv: Optional[list] = None) -> None:
    import argparse

    from dynamo_tpu.planner.interpolation import save_profile

    p = argparse.ArgumentParser("dynamo_tpu.planner.profiler")
    p.add_argument("--model", default="llama-3-1b")
    p.add_argument("--out", default="sla_profile.json")
    p.add_argument("--isl", type=int, nargs="+", default=[128, 256, 512])
    p.add_argument("--context", type=int, nargs="+",
                   default=[256, 512, 1024])
    p.add_argument("--kv", type=float, nargs="+", default=[0.2, 0.5, 0.8])
    p.add_argument("--num-blocks", type=int, default=2048)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    profile = profile_engine(
        default_core_factory(args.model, num_blocks=args.num_blocks),
        isl_grid=args.isl, context_grid=args.context, kv_grid=args.kv)
    save_profile(profile, args.out)
    print(f"profile written to {args.out}")


if __name__ == "__main__":
    main()
