"""SLA planner: profiled-perf interpolation → P/D replica targets.

The decision loop of the reference's SLA planner
(`planner/utils/planner_core.py:241-276`), re-hosted on our metrics
plane and chip-granular engines:

1. observe the last interval: request count, avg ISL/OSL, measured
   TTFT/ITL (scraped from the frontend's Prometheus exposition —
   `frontend_time_to_first_token_seconds` etc., runtime/metrics.py);
2. correction factors: measured TTFT/ITL over the profile's expected
   values absorb everything the interpolation doesn't model (queueing,
   prefix-cache hits) — `planner_core.py:208-219`;
3. predict next-interval load with pluggable predictors (constant /
   moving-average / trend — the reference's constant/ARIMA/Prophet
   ladder, predictor.py);
4. prefill replicas from interpolated prefill throughput/chip at the
   predicted ISL (queueing-corrected), decode replicas from the highest
   profiled throughput/chip whose ITL meets the corrected SLA at the
   predicted context (`find_best_throughput_per_chip`);
5. clamp to the chip budget proportionally, then converge connectors.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Optional

from dynamo_tpu.planner.interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.planner.predictor import make_predictor

logger = logging.getLogger(__name__)


@dataclass
class SlaObservation:
    """One adjustment-interval's aggregate load + latency."""

    num_requests: float = 0.0
    avg_isl: float = 0.0
    avg_osl: float = 0.0
    ttft_s: float = 0.0      # 0 = no data this interval
    itl_s: float = 0.0


@dataclass
class SlaPlannerConfig:
    ttft_s: float = 0.5                 # the SLA targets
    itl_s: float = 0.05
    adjustment_interval_s: float = 10.0
    chips_per_prefill_engine: int = 1
    chips_per_decode_engine: int = 1
    min_replicas: int = 1
    max_replicas: int = 8
    max_chip_budget: int = 16
    predictor: str = "moving_average"


class PrometheusScraper:
    """Interval observations from the frontend's /metrics exposition.

    Histogram `_sum`/`_count` series are cumulative; the scraper diffs
    successive scrapes to get per-interval averages (the reference's
    Prometheus-range-query analog, `utils/prometheus.py`)."""

    def __init__(self, url: str) -> None:
        self.url = url
        self._prev: dict = {}
        self._primed = False

    def _fetch(self) -> dict:
        out = {}
        with urllib.request.urlopen(self.url, timeout=5.0) as r:
            for line in r.read().decode().splitlines():
                if line.startswith("#") or not line.strip():
                    continue
                name, _, value = line.rpartition(" ")
                base = name.split("{")[0].strip()
                try:
                    out[base] = out.get(base, 0.0) + float(value)
                except ValueError:
                    continue
        return out

    def observe(self) -> SlaObservation:
        cur = self._fetch()
        prev, self._prev = self._prev, cur
        if not self._primed:
            # First scrape sees the frontend's ALL-TIME counters; diffing
            # them against nothing would report the process lifetime as
            # one interval's load and spike the fleet to max_replicas on
            # every planner restart.  Prime and report an idle interval.
            self._primed = True
            return SlaObservation()

        def delta(name):
            return max(0.0, cur.get(name, 0.0) - prev.get(name, 0.0))

        pre = "dynamo_frontend_"
        n_req = delta(pre + "requests_total")
        in_sum = delta(pre + "input_sequence_tokens_sum")
        in_cnt = delta(pre + "input_sequence_tokens_count")
        out_sum = delta(pre + "output_sequence_tokens_sum")
        out_cnt = delta(pre + "output_sequence_tokens_count")
        ttft_sum = delta(pre + "time_to_first_token_seconds_sum")
        ttft_cnt = delta(pre + "time_to_first_token_seconds_count")
        itl_sum = delta(pre + "inter_token_latency_seconds_sum")
        itl_cnt = delta(pre + "inter_token_latency_seconds_count")
        return SlaObservation(
            num_requests=n_req,
            avg_isl=in_sum / in_cnt if in_cnt else 0.0,
            avg_osl=out_sum / out_cnt if out_cnt else 0.0,
            ttft_s=ttft_sum / ttft_cnt if ttft_cnt else 0.0,
            itl_s=itl_sum / itl_cnt if itl_cnt else 0.0,
        )


@dataclass
class SlaDecision:
    num_prefill: int
    num_decode: int
    p_correction: float
    d_correction: float
    predicted: SlaObservation = field(default_factory=SlaObservation)


class SlaPlanner:
    """observe → correct → predict → interpolate → converge.

    `observe`: callable returning an SlaObservation for the last interval
    (PrometheusScraper.observe, or a test stub).  `prefill_connector` /
    `decode_connector`: the LoadPlanner connector contract; either may be
    None (aggregated deployments scale only the decode pool)."""

    def __init__(self, profile: dict, observe: Callable[[], SlaObservation],
                 decode_connector, prefill_connector=None,
                 config: Optional[SlaPlannerConfig] = None) -> None:
        self.config = config or SlaPlannerConfig()
        self.observe = observe
        self.prefill_connector = prefill_connector
        self.decode_connector = decode_connector
        self.prefill_interp = PrefillInterpolator(profile)
        self.decode_interp = DecodeInterpolator(profile)
        self._pred_req = make_predictor(self.config.predictor)
        self._pred_isl = make_predictor(self.config.predictor)
        self._pred_osl = make_predictor(self.config.predictor)
        self.p_correction = 1.0
        self.d_correction = 1.0
        self.decisions: list = []
        self._task: Optional[asyncio.Task] = None

    # -- the decision function (pure; unit-testable) -----------------------

    def decide(self, obs: SlaObservation) -> SlaDecision:
        cfg = self.config
        # Correction factors: how far reality runs from the profile
        # (queueing, prefix hits, interference) — planner_core.py:208-219.
        if obs.ttft_s > 0 and obs.avg_isl > 0:
            expect = self.prefill_interp.interpolate_ttft(obs.avg_isl)
            if expect > 0:
                self.p_correction = obs.ttft_s / expect
        if obs.itl_s > 0 and obs.avg_isl > 0:
            expect = self.decode_interp.interpolate_itl(
                0.5, obs.avg_isl + obs.avg_osl / 2)
            if expect > 0:
                self.d_correction = obs.itl_s / expect

        for pred, val in ((self._pred_req, obs.num_requests),
                          (self._pred_isl, obs.avg_isl),
                          (self._pred_osl, obs.avg_osl)):
            pred.add_data_point(val)
        nxt = SlaObservation(
            num_requests=self._pred_req.predict_next(),
            avg_isl=self._pred_isl.predict_next(),
            avg_osl=self._pred_osl.predict_next(),
        )

        if nxt.num_requests <= 0 or nxt.avg_isl <= 0:
            return SlaDecision(cfg.min_replicas, cfg.min_replicas,
                               self.p_correction, self.d_correction, nxt)

        # Prefill: tokens/s the fleet must prefill; the correction's
        # min(1, ·) treats a better-than-profile TTFT as queueing headroom
        # only, never as licence to under-provision.
        prefill_load = (nxt.num_requests * nxt.avg_isl
                        / cfg.adjustment_interval_s
                        * min(1.0, self.p_correction))
        num_p = math.ceil(
            prefill_load
            / max(self.prefill_interp.interpolate_thpt_per_chip(nxt.avg_isl),
                  1e-9)
            / cfg.chips_per_prefill_engine)

        # Decode: highest profiled per-chip throughput whose ITL meets the
        # corrected SLA at the predicted average context.
        corrected_itl = (cfg.itl_s / self.d_correction
                         if self.d_correction > 0 else cfg.itl_s)
        ctx = nxt.avg_isl + nxt.avg_osl / 2
        thpt = self.decode_interp.find_best_throughput_per_chip(
            corrected_itl, ctx)
        num_d = math.ceil(
            nxt.num_requests * nxt.avg_osl / cfg.adjustment_interval_s
            / max(thpt, 1e-9) / cfg.chips_per_decode_engine)

        num_p = min(max(num_p, cfg.min_replicas), cfg.max_replicas)
        num_d = min(max(num_d, cfg.min_replicas), cfg.max_replicas)
        total = (num_p * cfg.chips_per_prefill_engine
                 + num_d * cfg.chips_per_decode_engine)
        if total > cfg.max_chip_budget:
            scale = cfg.max_chip_budget / total
            num_p = max(cfg.min_replicas, int(num_p * scale))
            num_d = max(cfg.min_replicas, int(num_d * scale))
            floored = (num_p * cfg.chips_per_prefill_engine
                       + num_d * cfg.chips_per_decode_engine)
            if floored > cfg.max_chip_budget:
                # min_replicas floors can make the budget unsatisfiable;
                # deploying over budget silently would hide a config bug.
                logger.warning(
                    "sla: min_replicas floor forces %d chips against a "
                    "budget of %d", floored, cfg.max_chip_budget)
        return SlaDecision(num_p, num_d, self.p_correction,
                           self.d_correction, nxt)

    # -- loop --------------------------------------------------------------

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.adjustment_interval_s)
            try:
                await self.step()
            except Exception:
                logger.exception("sla planner step failed")

    async def step(self) -> SlaDecision:
        # The scraper is synchronous urllib (5 s timeout); off the loop so
        # a slow/dead frontend can't stall connector IO every interval.
        obs = await asyncio.to_thread(self.observe)
        decision = self.decide(obs)
        self.decisions.append((time.monotonic(), decision))
        logger.info(
            "sla decision: P=%d D=%d (corr p=%.2f d=%.2f, pred "
            "req=%.1f isl=%.0f osl=%.0f)", decision.num_prefill,
            decision.num_decode, decision.p_correction,
            decision.d_correction, decision.predicted.num_requests,
            decision.predicted.avg_isl, decision.predicted.avg_osl)
        if self.prefill_connector is not None:
            await self._converge(self.prefill_connector,
                                 decision.num_prefill)
        await self._converge(self.decode_connector, decision.num_decode)
        return decision

    @staticmethod
    async def _converge(connector, target: int, max_moves: int = 4) -> None:
        """Step the fleet toward `target`, at most `max_moves` spawns or
        drains per tick: an instantly-crashing worker otherwise turns
        this into an unbounded spawn loop (replicas() reaps the corpse,
        the loop spawns another, forever)."""
        moves = 0
        while connector.replicas() < target and moves < max_moves:
            await connector.add_worker()
            moves += 1
        while connector.replicas() > target and moves < max_moves:
            await connector.remove_worker()
            moves += 1
        if connector.replicas() != target:
            logger.info("sla: fleet at %d of target %d (max %d moves per "
                        "tick)", connector.replicas(), target, max_moves)
