"""Standalone KV-router service.

Role of the reference's `components/router` binary
(`components/router/src/main.rs:27-44`): host the KV-aware router as its
own `dyn://` endpoint so multiple simple frontends (or non-HTTP clients)
share ONE routing brain instead of each running their own indexer.

Composition: the service discovers a model's workers, builds the same
KvRoutedEngineClient the frontend embeds, then REGISTERS ITSELF as a
worker for that model under its own component.  Any frontend in plain
round-robin mode that discovers the router's entry routes through it and
transparently gets KV-aware placement; the router's replica-sync keeps
multiple router instances consistent (client.py ACTIVE_SEQS).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.llm.discovery import (
    ModelWatcher,
    engine_wire_handler,
    register_llm,
)
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.service import ModelManager
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.metrics import MetricsRegistry

logger = logging.getLogger(__name__)


class RouterService:
    """Discover workers for `model_name`, serve a kv-routed endpoint for
    it, and register that endpoint as a model instance."""

    def __init__(self, runtime: DistributedRuntime, model_name: str,
                 namespace: str = "dynamo",
                 component: str = "router",
                 serve_as: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None) -> None:
        """`serve_as`: public model name of the routed endpoint (default
        `<model>-routed`) — distinct from the raw workers' name so a
        frontend discovering both never mixes routed and unrouted
        replicas of one model, and the router can never discover
        itself."""
        self.runtime = runtime
        self.model_name = model_name
        self.serve_as = serve_as or f"{model_name}-routed"
        self.namespace = namespace
        self.component = component
        self.models = ModelManager()
        self.watcher = ModelWatcher(self.runtime, self.models,
                                    router_mode="kv")
        self.instance = None
        self._endpoint = None
        # Routing-brain observability (`/metrics` via the shared
        # registry on a StatusServer; satellite of the tracing PR —
        # frontend/worker/aggregator already expose one, the router did
        # not).
        self.registry = registry or MetricsRegistry()
        self._requests = self.registry.counter(
            "router_requests_total", "Requests routed through this "
            "router service")
        self._streams = self.registry.gauge(
            "router_inflight_streams", "Streams currently routed")
        self._route_latency = self.registry.histogram(
            "router_request_seconds", "Full routed-stream duration")

    async def start(self, wait_for_model_s: float = 30.0) -> None:
        await self.watcher.start()
        await self.watcher.wait_for_model(self.model_name,
                                          timeout=wait_for_model_s)
        handle = self.models.get(self.model_name)
        self._endpoint = (self.runtime.namespace(self.namespace)
                          .component(self.component).endpoint("generate"))
        self.instance = await self._endpoint.serve(
            engine_wire_handler(self._counted(handle.client)))
        # Reuse the discovered card so tokenizer/template survive the hop,
        # re-advertised under the routed name.
        card_dict = None
        entries = await self.runtime.cp.get_prefix("models/")
        for entry in entries.values():
            if entry.get("card", {}).get("name") == self.model_name:
                card_dict = dict(entry["card"])
                break
        if card_dict is not None:
            card_dict["name"] = self.serve_as
            card = ModelDeploymentCard.from_dict(card_dict)
        else:
            card = ModelDeploymentCard(name=self.serve_as)
        await register_llm(self._endpoint, self.instance, card)
        logger.info("router service for %r at %s", self.model_name,
                    self.instance.address)

    def _counted(self, client):
        """Wrap the routed EngineClient so every stream through the
        router lands in the registry (request count, in-flight gauge,
        stream duration)."""
        svc = self

        class _Counted:
            async def generate(self, request):
                import time

                svc._requests.inc()
                svc._streams.add(1)
                t0 = time.monotonic()
                try:
                    async for delta in client.generate(request):
                        yield delta
                finally:
                    svc._streams.add(-1)
                    svc._route_latency.observe(time.monotonic() - t0)

            def __getattr__(self, name):  # embed / clear_kv passthrough
                return getattr(client, name)

        return _Counted()

    async def stop(self) -> None:
        if self._endpoint is not None:
            await self._endpoint.leave()
        await self.watcher.stop()
