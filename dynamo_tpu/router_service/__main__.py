"""CLI entry: `python -m dynamo_tpu.router_service`.

    python -m dynamo_tpu.router_service --control-plane HOST:PORT \
        --model-name my-model
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from dynamo_tpu.router_service import RouterService
from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient
from dynamo_tpu.runtime.distributed import DistributedRuntime


def main(argv=None) -> None:
    p = argparse.ArgumentParser("dynamo_tpu.router_service")
    p.add_argument("--control-plane", required=True, help="HOST:PORT")
    p.add_argument("--model-name", required=True)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="router")
    p.add_argument("--serve-as", default=None,
                   help="public name of the routed model "
                        "(default: <model-name>-routed)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="status server port for /metrics + /debug/traces "
                        "(0 = ephemeral; -1 disables)")
    p.add_argument("--metrics-host", default="127.0.0.1",
                   help="bind + ADVERTISED host for the status server; a "
                        "cross-host aggregator needs a routable address "
                        "(the 127.0.0.1 default only works single-host)")
    from dynamo_tpu.runtime.tracing import (
        add_trace_args, configure_from_args)

    add_trace_args(p)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    configure_from_args(args, service="router")

    async def run():
        from dynamo_tpu.runtime.status import (
            StatusServer, register_status_endpoint)

        host, port = args.control_plane.rsplit(":", 1)
        cp = ControlPlaneClient(host, int(port))
        await cp.start()
        runtime = DistributedRuntime(cp)
        svc = RouterService(runtime, args.model_name,
                            namespace=args.namespace,
                            component=args.component,
                            serve_as=args.serve_as)
        await svc.start()
        status = None
        if args.metrics_port >= 0:
            status = StatusServer(registry=svc.registry)
            bound = await status.start(host=args.metrics_host,
                                       port=args.metrics_port)
            await register_status_endpoint(cp, args.component, bound,
                                           host=args.metrics_host)
            print(f"router metrics on :{bound}/metrics", flush=True)
        print(f"router service for {args.model_name!r} at "
              f"{svc.instance.address}", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        if status is not None:
            await status.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
