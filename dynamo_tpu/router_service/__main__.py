"""CLI entry: `python -m dynamo_tpu.router_service`.

    python -m dynamo_tpu.router_service --control-plane HOST:PORT \
        --model-name my-model
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from dynamo_tpu.router_service import RouterService
from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient
from dynamo_tpu.runtime.distributed import DistributedRuntime


def main(argv=None) -> None:
    p = argparse.ArgumentParser("dynamo_tpu.router_service")
    p.add_argument("--control-plane", required=True, help="HOST:PORT")
    p.add_argument("--model-name", required=True)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="router")
    p.add_argument("--serve-as", default=None,
                   help="public name of the routed model "
                        "(default: <model-name>-routed)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    async def run():
        host, port = args.control_plane.rsplit(":", 1)
        cp = ControlPlaneClient(host, int(port))
        await cp.start()
        runtime = DistributedRuntime(cp)
        svc = RouterService(runtime, args.model_name,
                            namespace=args.namespace,
                            component=args.component,
                            serve_as=args.serve_as)
        await svc.start()
        print(f"router service for {args.model_name!r} at "
              f"{svc.instance.address}", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
