"""Core distributed runtime.

Role of the reference's Rust `dynamo-runtime` crate (SURVEY.md §2.1):
component/endpoint model with lease-based discovery, transports, the
AsyncEngine streaming contract, cancellation, config, logging, metrics and
the system-status server.  The reference rides etcd + NATS; this runtime
ships its own control plane (in-process broker for single-process, TCP
control-plane server for multi-process) since the capability — discovery,
liveness, pub/sub, work queues — is what matters, not the binaries.
"""
