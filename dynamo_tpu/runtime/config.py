"""Layered configuration: defaults ← TOML file ← DYN_* environment.

Role of the reference's figment-based config (`lib/runtime/src/config.rs:
37,168-181`: defaults ← TOML ← `DYN_RUNTIME_*`/`DYN_SYSTEM_*`).  The
precedence here matches, with CLI flags (handled by each entrypoint's
argparse on top of these) as the final layer:

    defaults  <  TOML file  <  environment  <  CLI flags

- TOML path: `DYN_CONFIG` env var, else `./dynamo.toml` if present.
- Environment: `DYN_<KEY>` (upper-cased, `-`→`_`) overrides key `<key>`;
  values parse as TOML literals when possible (so `DYN_HTTP_PORT=8080`
  is an int and `DYN_MOCKER=true` a bool), falling back to raw strings.

Dynamic (watched) config lives on the control plane instead — see the
disagg threshold key (`llm/disagg.py disagg_config_key`), the analog of
the reference's etcd-watched `DisaggRouterConf`.
"""

from __future__ import annotations

import logging
import os
try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is API-identical
    import tomli as tomllib
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

ENV_PREFIX = "DYN_"
DEFAULT_TOML = "dynamo.toml"


def _parse_env_value(raw: str) -> Any:
    try:
        # TOML value grammar gives ints/floats/bools/strings/lists for free.
        return tomllib.loads(f"v = {raw}")["v"]
    except tomllib.TOMLDecodeError:
        return raw


def load_layered_config(defaults: Dict[str, Any],
                        section: Optional[str] = None,
                        env_prefix: str = ENV_PREFIX,
                        toml_path: Optional[str] = None) -> Dict[str, Any]:
    """Resolve one flat config dict.  `section`: optional TOML table name
    (e.g. "worker" reads `[worker]`); top-level keys apply to every
    section (reference DYN_RUNTIME_* vs per-binary split)."""
    out = dict(defaults)

    path = toml_path or os.environ.get(env_prefix + "CONFIG") or (
        DEFAULT_TOML if os.path.exists(DEFAULT_TOML) else None)
    if path:
        try:
            with open(path, "rb") as f:
                data = tomllib.load(f)
        except (OSError, tomllib.TOMLDecodeError) as e:
            raise ValueError(f"bad config file {path!r}: {e}") from e
        for k, v in data.items():
            if not isinstance(v, dict) and k in out:
                out[k] = v
        if section and isinstance(data.get(section), dict):
            for k, v in data[section].items():
                if k in out:
                    out[k] = v

    for k in out:
        raw = os.environ.get(env_prefix + k.upper().replace("-", "_"))
        if raw is not None:
            out[k] = _parse_env_value(raw)
    return out


def apply_to_parser_defaults(parser, config: Dict[str, Any]) -> None:
    """Push resolved config values under the argparse defaults, so CLI
    flags stay the top layer: flag > env > toml > default."""
    known = {a.dest for a in parser._actions}
    parser.set_defaults(**{k.replace("-", "_"): v for k, v in config.items()
                           if k.replace("-", "_") in known})
