"""Runtime thread-affinity contracts for the serving hot paths.

PRs 2-7 grew ~25 "engine thread only" / "never the engine thread" /
"zero host syncs in the steady window" comments across the engine, the
block manager, the SLO monitor and the worker — enforced only by
convention.  This module turns those comments into machine-checked
contracts, in two modes:

- **Default (production / bench): zero cost.**  When the
  ``DYNAMO_CONTRACTS`` env var is unset (or ``0``), every decorator
  returns the original function object unchanged — no wrapper, no
  attribute lookups, no branch on the call path.  The steady-decode
  pinned counter tests stay byte-identical.
- **Debug (``DYNAMO_CONTRACTS=1`` — the test suite's conftest sets
  it): assert caller-thread identity** on every call and raise
  :class:`ContractViolation` (an ``AssertionError`` subclass) with the
  offending thread's name when a contract is broken.

Three decorators, which ``tools/dynamo_lint.py`` also reads statically
(rules DL001 and DL005), so the static and runtime layers enforce the
same contract:

``@engine_thread_only``
    The function must always run on ONE consistent thread per instance
    (the thread that owns the engine/pool — whichever thread calls
    first pins the identity).  Ownership legitimately transfers when
    ``InferenceEngine`` starts/stops its step loop: :func:`release_owner`
    clears the pin so the new owner re-pins on its first call.

``@never_engine_thread``
    The function must never run on a registered engine thread
    (:func:`register_engine_thread` — ``InferenceEngine._run_loop``
    registers itself).  Calling one of these from the engine thread is
    either a deadlock (awaiting a command the engine thread itself must
    drain) or a latency bug (blocking the step loop on telemetry).

``@hot_path``
    A pure marker: the function body must stay free of host syncs
    (``.item()``, ``jax.device_get``, ``block_until_ready``,
    ``np.asarray`` on device values, blocking future ``.result()``) —
    checked STATICALLY by dynamo-lint rule DL001, never at runtime.

All three handle plain functions, ``async def`` coroutines and async
generators (the check runs on the calling thread before delegation).
"""

from __future__ import annotations

import functools
import inspect
import os
import threading
from typing import Set


def _env_enabled() -> bool:
    return os.environ.get("DYNAMO_CONTRACTS", "0").strip().lower() not in (
        "", "0", "false", "no", "off")


#: Evaluated once at import: decoration happens at module-import time, so
#: flipping the env var mid-process has no effect (by design — the
#: zero-cost guarantee depends on decorators resolving to the bare
#: function object when disabled).
ENABLED = _env_enabled()

_OWNER_ATTR = "_dynamo_contract_owner"

_engine_threads: Set[int] = set()
_engine_threads_lock = threading.Lock()


class ContractViolation(AssertionError):
    """A thread-affinity contract was broken (debug mode only)."""


# -- engine-thread registry ------------------------------------------------


def register_engine_thread() -> None:
    """Mark the CURRENT thread as an engine thread (the step-loop thread
    calls this on entry).  Idempotent; cheap enough to call unconditionally
    (a set add under a lock, once per engine lifetime)."""
    with _engine_threads_lock:
        _engine_threads.add(threading.get_ident())


def unregister_engine_thread() -> None:
    """Remove the CURRENT thread from the engine-thread registry (the
    step loop calls this on exit, so a thread id recycled by the OS
    never haunts ``@never_engine_thread`` checks)."""
    with _engine_threads_lock:
        _engine_threads.discard(threading.get_ident())


def current_is_engine_thread() -> bool:
    return threading.get_ident() in _engine_threads


def release_owner(*objects) -> None:
    """Clear the pinned-thread identity on the given instances so the
    next ``@engine_thread_only`` call re-pins.  Called at ownership
    transfer points: ``InferenceEngine.start()`` (the step-loop thread
    takes over a core built — and possibly warmed — on the main thread)
    and ``stop()`` (tests may drive the core directly afterwards)."""
    for obj in objects:
        if obj is None:
            continue
        try:
            obj.__dict__.pop(_OWNER_ATTR, None)
        except AttributeError:
            pass  # slotted/foreign object: it was never pinned


# -- decorator plumbing ----------------------------------------------------


def _wrap(fn, check):
    """Wrap `fn` so `check(args)` runs on the calling thread first.
    Handles sync functions, coroutine functions and async generators
    (for the async flavors the check still fires on the caller's
    thread, at first iteration/await)."""
    if inspect.isasyncgenfunction(fn):
        @functools.wraps(fn)
        async def agen_wrapper(*args, **kwargs):
            check(args)
            async for item in fn(*args, **kwargs):
                yield item
        return agen_wrapper
    if inspect.iscoroutinefunction(fn):
        @functools.wraps(fn)
        async def coro_wrapper(*args, **kwargs):
            check(args)
            return await fn(*args, **kwargs)
        return coro_wrapper

    @functools.wraps(fn)
    def sync_wrapper(*args, **kwargs):
        check(args)
        return fn(*args, **kwargs)
    return sync_wrapper


def engine_thread_only(fn):
    """All calls (per instance) must come from one consistent thread.

    The pin lives in the instance ``__dict__`` — the first decorated
    call stores ``(ident, name)``; later calls from a different thread
    raise.  Module-level functions pin on the function object itself.
    """
    fn.__dynamo_contract__ = "engine_thread_only"
    if not ENABLED:
        return fn

    def check(args):
        holder = args[0] if args and hasattr(args[0], "__dict__") else fn
        ident = threading.get_ident()
        # setdefault is atomic under the GIL: two threads racing the
        # FIRST call must not both pin (a plain get-then-set window
        # would silently miss exactly the violation this exists for).
        owner = holder.__dict__.setdefault(
            _OWNER_ATTR, (ident, threading.current_thread().name))
        if owner[0] != ident:
            raise ContractViolation(
                f"{fn.__qualname__} is engine-thread-only: instance is "
                f"owned by thread {owner[1]!r} but was called from "
                f"{threading.current_thread().name!r} "
                "(contracts.release_owner transfers ownership)")

    wrapper = _wrap(fn, check)
    wrapper.__dynamo_contract__ = "engine_thread_only"
    return wrapper


def never_engine_thread(fn):
    """The function must not run on a registered engine thread."""
    fn.__dynamo_contract__ = "never_engine_thread"
    if not ENABLED:
        return fn

    def check(args):
        if threading.get_ident() in _engine_threads:
            raise ContractViolation(
                f"{fn.__qualname__} must never run on the engine thread "
                f"(called from {threading.current_thread().name!r}) — it "
                "would block or deadlock the step loop")

    wrapper = _wrap(fn, check)
    wrapper.__dynamo_contract__ = "never_engine_thread"
    return wrapper


def hot_path(fn):
    """Static-only marker: dynamo-lint rule DL001 forbids host-sync
    calls inside the decorated body.  Never wraps — the steady decode
    window pays nothing for the contract existing, in either mode."""
    fn.__dynamo_contract__ = "hot_path"
    return fn
