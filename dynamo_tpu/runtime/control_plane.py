"""Control plane: lease-based KV discovery + pub/sub + work queues.

The reference runs three external services for this (SURVEY.md §2.6):
etcd (leases/watches — `transports/etcd.rs`), NATS pub-sub subjects
(`transports/nats.rs:53`) and NATS JetStream work queues (`NatsQueue`,
`transports/nats.rs:360`).  This module provides the same capability set
as one self-contained service, because the capability — not the binary —
is the contract:

- **KV with leases + watches**: `put(key, value, lease_id)`; keys die with
  their lease (TTL, refreshed by keep-alives); prefix watches push
  PUT/DELETE events to watchers.  Worker instances register under
  `instances/{namespace}/{component}/{endpoint}:{lease}` exactly like the
  reference's path scheme (`component.rs:72-75`).
- **Pub/sub**: fire-and-forget subjects (KV events, metrics).
- **Work queues**: at-least-once delivery with acks (the JetStream
  `NatsQueue` semantics the disagg prefill queue rides on,
  `disagg_serving.md:62-64`): `queue_pop` leases an item to the consumer
  under a visibility timeout; `queue_ack` settles it; an un-acked item
  (consumer died mid-prefill) is redelivered to the next popper.

Two transports share `ControlPlaneState` (the single source of truth):
`InProcessControlPlane` binds it directly (single-process serving, tests);
`ControlPlaneServer`/`ControlPlaneClient` expose it over TCP with
newline-delimited JSON frames for multi-process deployments.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time
from dataclasses import dataclass, field
from typing import AsyncIterator, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

DEFAULT_LEASE_TTL = 10.0  # seconds; reference etcd default lease ~10s
# Reserved backend namespace for durable work-queue items (never visible
# through the kv surface).
_QUEUE_NS = "__queue__/"


@dataclass
class WatchEvent:
    kind: str          # "put" | "delete"
    key: str
    value: Optional[dict] = None


# ---------------------------------------------------------------------------
# State (transport-independent)


class ControlPlaneState:
    """The authoritative store.  All mutation methods are synchronous and
    must run on the owning event loop; notification fan-out is async-safe
    via call_soon."""

    def __init__(self, backend=None) -> None:
        # Pluggable persistence for UNLEASED keys (runtime/kv_store.py —
        # the reference's key_value_store backends); leased keys are
        # liveness records and never persist.
        from dynamo_tpu.runtime.kv_store import MemoryBackend

        self._backend = backend or MemoryBackend()
        raw = self._backend.load()
        self._kv: Dict[str, Tuple[dict, Optional[int]]] = {
            k: (v, None) for k, v in raw.items()
            if not k.startswith(_QUEUE_NS)
        }  # key → (val, lease)
        self._leases: Dict[int, float] = {}                   # lease → deadline
        self._lease_ttl: Dict[int, float] = {}
        self._lease_seq = itertools.count(1)
        self._watchers: List[Tuple[str, asyncio.Queue]] = []  # (prefix, q)
        self._subs: Dict[str, List[asyncio.Queue]] = {}       # subject → qs
        self._queues: Dict[str, asyncio.Queue] = {}           # work queues
        # (queue, msg_id) → (payload, redelivery deadline)
        self._inflight_msgs: Dict[Tuple[str, int], Tuple[dict, float]] = {}
        self._reaper: Optional[asyncio.Task] = None
        # Restore durable queue items (reference NatsQueue = JetStream,
        # which survives broker restarts): anything persisted and never
        # acked — including items popped but unacked at crash time —
        # re-enters its queue as pending (at-least-once).  Queue names
        # may contain '/' (e.g. "{namespace}/prefill_queue"), so the msg
        # id is split from the RIGHT; restore order is numeric msg id
        # (lexicographic key order would put 10 before 2 — FIFO must
        # survive the restart).
        restored = []
        for k, payload in raw.items():
            if not k.startswith(_QUEUE_NS):
                continue
            name, msg_id = k[len(_QUEUE_NS):].rsplit("/", 1)
            restored.append((int(msg_id), name, payload))
        restored.sort()
        for msg_id, name, payload in restored:
            self._queue(name).put_nowait((msg_id, payload))
        self._queue_msg_seq = itertools.count(
            (restored[-1][0] + 1) if restored else 1)

    # -- leases -----------------------------------------------------------

    def lease_grant(self, ttl: float = DEFAULT_LEASE_TTL) -> int:
        lease = next(self._lease_seq)
        self._leases[lease] = time.monotonic() + ttl
        self._lease_ttl[lease] = ttl
        return lease

    def lease_keepalive(self, lease: int) -> bool:
        if lease not in self._leases:
            return False
        self._leases[lease] = time.monotonic() + self._lease_ttl[lease]
        return True

    def lease_revoke(self, lease: int) -> None:
        self._leases.pop(lease, None)
        self._lease_ttl.pop(lease, None)
        dead = [k for k, (_, l) in self._kv.items() if l == lease]
        for k in dead:
            self.delete(k)

    def expire_leases(self) -> int:
        now = time.monotonic()
        expired = [l for l, dl in self._leases.items() if dl < now]
        for l in expired:
            logger.info("lease %d expired", l)
            self.lease_revoke(l)
        return len(expired)

    async def run_reaper(self, interval: float = 1.0) -> None:
        while True:
            await asyncio.sleep(interval)
            self.expire_leases()
            self.redeliver_expired()

    # -- kv ---------------------------------------------------------------

    def put(self, key: str, value: dict, lease: Optional[int] = None) -> None:
        if lease is not None and lease not in self._leases:
            raise KeyError(f"unknown lease {lease}")
        self._kv[key] = (value, lease)
        if lease is None:
            self._backend.put(key, value)
        self._notify(WatchEvent("put", key, value))

    def get(self, key: str) -> Optional[dict]:
        v = self._kv.get(key)
        return v[0] if v else None

    def get_prefix(self, prefix: str) -> Dict[str, dict]:
        return {k: v for k, (v, _) in self._kv.items() if k.startswith(prefix)}

    def delete(self, key: str) -> bool:
        if key in self._kv:
            _, lease = self._kv.pop(key)
            if lease is None:
                self._backend.delete(key)
            self._notify(WatchEvent("delete", key))
            return True
        return False

    # -- watches ----------------------------------------------------------

    def watch_prefix(self, prefix: str) -> asyncio.Queue:
        """Returns a queue of WatchEvents; caller gets current state as
        synthetic puts first (etcd kv_get_and_watch_prefix semantics)."""
        q: asyncio.Queue = asyncio.Queue()
        for k, (v, _) in sorted(self._kv.items()):
            if k.startswith(prefix):
                q.put_nowait(WatchEvent("put", k, v))
        self._watchers.append((prefix, q))
        return q

    def unwatch(self, q: asyncio.Queue) -> None:
        self._watchers = [(p, w) for (p, w) in self._watchers if w is not q]

    def _notify(self, ev: WatchEvent) -> None:
        for prefix, q in self._watchers:
            if ev.key.startswith(prefix):
                q.put_nowait(ev)

    # -- pub/sub ----------------------------------------------------------

    def subscribe(self, subject: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._subs.setdefault(subject, []).append(q)
        return q

    def unsubscribe(self, subject: str, q: asyncio.Queue) -> None:
        subs = self._subs.get(subject, [])
        if q in subs:
            subs.remove(q)

    def publish(self, subject: str, payload: dict) -> int:
        subs = self._subs.get(subject, [])
        for q in subs:
            q.put_nowait(payload)
        return len(subs)

    # -- work queues ------------------------------------------------------

    def _queue(self, name: str) -> asyncio.Queue:
        return self._queues.setdefault(name, asyncio.Queue())

    def queue_push(self, name: str, payload: dict) -> None:
        msg_id = next(self._queue_msg_seq)
        self._backend.put(f"{_QUEUE_NS}{name}/{msg_id}", payload)
        self._queue(name).put_nowait((msg_id, payload))

    async def queue_pop(self, name: str,
                        visibility_timeout: float = 30.0) -> Tuple[int, dict]:
        """Lease the next item: (msg_id, payload).  The caller must
        `queue_ack(name, msg_id)` before the visibility timeout or the
        item is redelivered (at-least-once; reference `NatsQueue` ack
        model, `transports/nats.rs:360`)."""
        msg_id, payload = await self._queue(name).get()
        self._inflight_msgs[(name, msg_id)] = (
            payload, time.monotonic() + visibility_timeout)
        return msg_id, payload

    def queue_ack(self, name: str, msg_id: int) -> bool:
        acked = self._inflight_msgs.pop((name, msg_id), None) is not None
        if acked:
            self._backend.delete(f"{_QUEUE_NS}{name}/{msg_id}")
        return acked

    def redeliver_expired(self) -> int:
        now = time.monotonic()
        expired = [k for k, (_, dl) in self._inflight_msgs.items()
                   if dl < now]
        for name, msg_id in expired:
            payload, _ = self._inflight_msgs.pop((name, msg_id))
            logger.warning("queue %s: redelivering un-acked msg %d",
                           name, msg_id)
            self._queue(name).put_nowait((msg_id, payload))
        return len(expired)

    def queue_len(self, name: str) -> int:
        q = self._queues.get(name)
        return q.qsize() if q else 0


# ---------------------------------------------------------------------------
# Client interface (shared by in-process and TCP implementations)


class InProcessControlPlane:
    """Direct binding to a ControlPlaneState (single-process deployments,
    the analog of running etcd+NATS on localhost for tests)."""

    def __init__(self, state: Optional[ControlPlaneState] = None) -> None:
        self.state = state or ControlPlaneState()
        self._keepalive_tasks: Dict[int, asyncio.Task] = {}

    async def start(self) -> None:
        if self.state._reaper is None:
            self.state._reaper = asyncio.create_task(self.state.run_reaper())

    async def close(self) -> None:
        for t in self._keepalive_tasks.values():
            t.cancel()
        if self.state._reaper:
            self.state._reaper.cancel()
            try:
                await self.state._reaper
            except asyncio.CancelledError:
                pass
            self.state._reaper = None

    # Leases
    async def lease_grant(self, ttl: float = DEFAULT_LEASE_TTL,
                          auto_keepalive: bool = True) -> int:
        lease = self.state.lease_grant(ttl)
        if auto_keepalive:
            self._keepalive_tasks[lease] = asyncio.create_task(
                self._keepalive_loop(lease, ttl))
        return lease

    async def _keepalive_loop(self, lease: int, ttl: float) -> None:
        # Refresh at 1/3 TTL like the reference (`etcd/lease.rs:62`).
        try:
            while True:
                await asyncio.sleep(ttl / 3.0)
                if not self.state.lease_keepalive(lease):
                    return
        except asyncio.CancelledError:
            pass

    async def lease_revoke(self, lease: int) -> None:
        t = self._keepalive_tasks.pop(lease, None)
        if t:
            t.cancel()
        self.state.lease_revoke(lease)

    # KV
    async def put(self, key: str, value: dict,
                  lease: Optional[int] = None) -> None:
        self.state.put(key, value, lease)

    async def get(self, key: str) -> Optional[dict]:
        return self.state.get(key)

    async def get_prefix(self, prefix: str) -> Dict[str, dict]:
        return self.state.get_prefix(prefix)

    async def delete(self, key: str) -> bool:
        return self.state.delete(key)

    async def watch_prefix(self, prefix: str) -> "Watch":
        return Watch(self.state, self.state.watch_prefix(prefix))

    # Pub/sub
    async def publish(self, subject: str, payload: dict) -> None:
        self.state.publish(subject, payload)

    async def subscribe(self, subject: str) -> "Subscription":
        return Subscription(self.state, subject,
                            self.state.subscribe(subject))

    # Queues
    async def queue_push(self, name: str, payload: dict) -> None:
        self.state.queue_push(name, payload)

    async def queue_pop(self, name: str,
                        visibility_timeout: float = 30.0) -> Tuple[int, dict]:
        return await self.state.queue_pop(name, visibility_timeout)

    async def queue_ack(self, name: str, msg_id: int) -> bool:
        return self.state.queue_ack(name, msg_id)

    async def queue_len(self, name: str) -> int:
        return self.state.queue_len(name)


class Watch:
    def __init__(self, state: ControlPlaneState, q: asyncio.Queue) -> None:
        self._state, self._q = state, q

    async def next(self) -> WatchEvent:
        return await self._q.get()

    def cancel(self) -> None:
        self._state.unwatch(self._q)

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        return await self.next()


class Subscription:
    def __init__(self, state, subject: str, q: asyncio.Queue) -> None:
        self._state, self.subject, self._q = state, subject, q

    async def next(self) -> dict:
        return await self._q.get()

    def cancel(self) -> None:
        self._state.unsubscribe(self.subject, self._q)

    def __aiter__(self):
        return self

    async def __anext__(self) -> dict:
        return await self.next()
