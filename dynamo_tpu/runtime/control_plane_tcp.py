"""TCP transport for the control plane (multi-process deployments).

`ControlPlaneServer` hosts a ControlPlaneState over asyncio TCP with
newline-delimited JSON frames; `ControlPlaneClient` implements the same
interface as InProcessControlPlane, so DistributedRuntime doesn't care
which it got.  The wire protocol is deliberately transport-simple
(line-delimited JSON) so alternative broker implementations can speak it
without sharing code.

Wire protocol (one JSON object per line):
  request:  {"op": <name>, "id": N, ...args}
  response: {"id": N, "ok": true, ...result} | {"id": N, "ok": false, "error": ...}
  pushed:   {"push": "watch"|"sub"|"queue", "sid": S, ...payload}

Connection death cleans up that client's watches/subscriptions; leases die
by TTL (a dead worker's instance keys vanish within one lease TTL, the
reference's liveness model — `transports/etcd/lease.rs`).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
from typing import Dict, Optional

from dynamo_tpu.runtime.control_plane import (
    ControlPlaneState,
    WatchEvent,
)

logger = logging.getLogger(__name__)


class ControlPlaneServer:
    def __init__(self, state: Optional[ControlPlaneState] = None) -> None:
        self.state = state or ControlPlaneState()
        self._server: Optional[asyncio.AbstractServer] = None
        self._connections: set = set()
        self._handlers: set = set()   # live per-connection handler tasks
        self.port: Optional[int] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.state._reaper is None:
            self.state._reaper = asyncio.create_task(self.state.run_reaper())
        logger.info("control plane on %s:%d", host, self.port)
        return self.port

    async def stop(self) -> None:
        if self.state._reaper:
            self.state._reaper.cancel()
            try:
                await self.state._reaper
            except asyncio.CancelledError:
                pass
            self.state._reaper = None
        if self._server:
            self._server.close()
            # Sever live client connections before wait_closed(): on
            # Python 3.12+ it blocks until every connection handler
            # returns, and handlers sit in blocking reads.
            for w in list(self._connections):
                w.close()
            await self._server.wait_closed()
        # Await the per-connection handler tasks: a handler still parked
        # in readline() at loop close is a "Task was destroyed but it is
        # pending!" warning in every test teardown that stops a server.
        for t in list(self._handlers):
            t.cancel()
        if self._handlers:
            await asyncio.gather(*self._handlers, return_exceptions=True)
        self._handlers.clear()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._connections.add(writer)
        task = asyncio.current_task()
        if task is not None:
            self._handlers.add(task)
        watches: Dict[int, asyncio.Queue] = {}
        subs: Dict[int, tuple] = {}     # sid → (subject, queue)
        pumps: list = []
        send_lock = asyncio.Lock()

        async def send(obj: dict) -> None:
            async with send_lock:
                writer.write(json.dumps(obj).encode() + b"\n")
                await writer.drain()

        async def pump_watch(sid: int, q: asyncio.Queue) -> None:
            while True:
                ev: WatchEvent = await q.get()
                await send({"push": "watch", "sid": sid, "kind": ev.kind,
                            "key": ev.key, "value": ev.value})

        async def pump_sub(sid: int, q: asyncio.Queue) -> None:
            while True:
                payload = await q.get()
                await send({"push": "sub", "sid": sid, "payload": payload})

        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    await send({"ok": False, "error": "bad json", "id": None})
                    continue
                op, mid = msg.get("op"), msg.get("id")
                st = self.state
                try:
                    if op == "lease_grant":
                        res = {"lease": st.lease_grant(msg.get("ttl", 10.0))}
                    elif op == "lease_keepalive":
                        res = {"alive": st.lease_keepalive(msg["lease"])}
                    elif op == "lease_revoke":
                        st.lease_revoke(msg["lease"])
                        res = {}
                    elif op == "put":
                        st.put(msg["key"], msg["value"], msg.get("lease"))
                        res = {}
                    elif op == "get":
                        res = {"value": st.get(msg["key"])}
                    elif op == "get_prefix":
                        res = {"values": st.get_prefix(msg["prefix"])}
                    elif op == "delete":
                        res = {"deleted": st.delete(msg["key"])}
                    elif op == "watch":
                        sid = msg["sid"]
                        q = st.watch_prefix(msg["prefix"])
                        watches[sid] = q
                        pumps.append(asyncio.create_task(pump_watch(sid, q)))
                        res = {}
                    elif op == "unwatch":
                        q = watches.pop(msg["sid"], None)
                        if q:
                            st.unwatch(q)
                        res = {}
                    elif op == "subscribe":
                        sid = msg["sid"]
                        q = st.subscribe(msg["subject"])
                        subs[sid] = (msg["subject"], q)
                        pumps.append(asyncio.create_task(pump_sub(sid, q)))
                        res = {}
                    elif op == "unsubscribe":
                        subj_q = subs.pop(msg["sid"], None)
                        if subj_q:
                            st.unsubscribe(*subj_q)
                        res = {}
                    elif op == "publish":
                        res = {"n": st.publish(msg["subject"], msg["payload"])}
                    elif op == "queue_push":
                        st.queue_push(msg["queue"], msg["payload"])
                        res = {}
                    elif op == "queue_pop":
                        # Async pop: reply comes whenever an item arrives.
                        async def do_pop(mid=mid, name=msg["queue"],
                                         vt=msg.get("visibility_timeout",
                                                    30.0)):
                            msg_id, item = await st.queue_pop(name, vt)
                            await send({"id": mid, "ok": True,
                                        "msg_id": msg_id, "payload": item})
                        pumps.append(asyncio.create_task(do_pop()))
                        continue
                    elif op == "queue_ack":
                        res = {"acked": st.queue_ack(msg["queue"],
                                                     msg["msg_id"])}
                    elif op == "queue_len":
                        res = {"n": st.queue_len(msg["queue"])}
                    else:
                        raise ValueError(f"unknown op {op!r}")
                    await send({"id": mid, "ok": True, **res})
                except Exception as e:  # per-op failure, connection survives
                    await send({"id": mid, "ok": False, "error": str(e)})
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            for t in pumps:
                t.cancel()
            # Await the cancellations: a cancelled-but-never-awaited pump
            # is destroyed pending at loop close (the asyncio teardown
            # warnings the HTTP-service tests leaked).
            if pumps:
                await asyncio.gather(*pumps, return_exceptions=True)
            for q in watches.values():
                self.state.unwatch(q)
            for subj, q in subs.values():
                self.state.unsubscribe(subj, q)
            self._connections.discard(writer)
            if task is not None:
                self._handlers.discard(task)
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                # dynamo-lint: disable=DL003 teardown: peer already gone
                pass  # nothing to salvage — the connection is history


_POISON = object()  # sentinel pushed into stream queues on connection death


class _RemoteWatch:
    def __init__(self, client: "ControlPlaneClient", sid: int,
                 prefix: str) -> None:
        self._client, self._sid = client, sid
        self.prefix = prefix  # re-established on client reconnect
        self.queue: asyncio.Queue = asyncio.Queue()

    async def next(self) -> WatchEvent:
        item = await self.queue.get()
        if item is _POISON:
            raise ConnectionError("control plane connection lost")
        return item

    def cancel(self) -> None:
        self._client._drop_watch(self._sid)

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        return await self.next()


class _RemoteSubscription:
    def __init__(self, client: "ControlPlaneClient", sid: int,
                 subject: str) -> None:
        self._client, self._sid, self.subject = client, sid, subject
        self.queue: asyncio.Queue = asyncio.Queue()

    async def next(self) -> dict:
        item = await self.queue.get()
        if item is _POISON:
            raise ConnectionError("control plane connection lost")
        return item

    def cancel(self) -> None:
        self._client._drop_sub(self._sid)

    def __aiter__(self):
        return self

    async def __anext__(self) -> dict:
        return await self.next()


class ControlPlaneClient:
    """TCP client with the InProcessControlPlane interface.

    Reconnects automatically: on connection loss the rx loop fails all
    pending calls, poisons stream queues ONCE (consumers see one
    ConnectionError per outage), then dials back with backoff and
    re-establishes every live watch/subscription under its original sid —
    the server replays watch state as synthetic puts
    (ControlPlaneState.watch_prefix), so watchers converge.  Leases are
    NOT restored (they expire server-side by TTL; the keepalive loop logs
    loudly — re-registration is the worker's job, the reference's
    etcd-lease model)."""

    def __init__(self, host: str, port: int) -> None:
        self.host, self.port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._mid = itertools.count(1)
        self._sid = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watches: Dict[int, _RemoteWatch] = {}
        self._subs: Dict[int, _RemoteSubscription] = {}
        self._rx_task: Optional[asyncio.Task] = None
        self._reconnect_task: Optional[asyncio.Task] = None
        self._keepalive_tasks: Dict[int, asyncio.Task] = {}
        self._send_lock = asyncio.Lock()
        self._closed = False
        self._reconnecting = False
        self._conn_gen = 0  # bumps per (re)connect; stale rx loops exit
        # Session-loss callbacks: fired (as tasks) after a successful
        # reconnect, and when a keepalive discovers its lease expired
        # server-side.  Both mean every lease this client held is gone —
        # registrations must be replayed (the reference's etcd-lease
        # model: `transports/etcd/lease.rs` recovery is the worker's
        # job).  Endpoint.serve installs the replay.
        self._session_callbacks: list = []

    def on_session_loss(self, cb) -> None:
        """Register an async callback fired when this client's server-side
        session state (leases + leased keys) is known to be lost."""
        self._session_callbacks.append(cb)

    def remove_session_callback(self, cb) -> None:
        if cb in self._session_callbacks:
            self._session_callbacks.remove(cb)

    def _fire_session_loss(self) -> None:
        for cb in list(self._session_callbacks):
            task = asyncio.create_task(cb())
            task.add_done_callback(
                lambda t: t.exception() and logger.error(
                    "session-loss callback failed: %s", t.exception()))

    async def start(self) -> None:
        self._closed = False
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        self._rx_task = asyncio.create_task(self._rx_loop())

    async def close(self) -> None:
        self._closed = True
        for t in self._keepalive_tasks.values():
            t.cancel()
        for t in (self._rx_task, self._reconnect_task):
            if t:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
        self._fail_all(ConnectionError("control plane client closed"))
        if self._writer:
            self._writer.close()

    def _fail_pending(self, exc: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    def _fail_all(self, exc: Exception) -> None:
        """Connection is gone: fail pending calls AND poison stream queues
        ONCE, so watchers/subscribers surface the outage (one
        ConnectionError per outage, not per reconnect attempt) instead of
        waiting on a frozen queue forever."""
        self._fail_pending(exc)
        for w in self._watches.values():
            w.queue.put_nowait(_POISON)
        for s in self._subs.values():
            s.queue.put_nowait(_POISON)

    async def _rx_loop(self) -> None:
        # Capture this connection's identity: after a reconnect a stale rx
        # loop must neither read the NEW socket nor trigger another
        # reconnect (two live loops would clobber _reader/_writer and
        # double-register every sid).
        reader = self._reader
        gen = self._conn_gen
        assert reader is not None
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                msg = json.loads(line)
                push = msg.get("push")
                if push == "watch":
                    w = self._watches.get(msg["sid"])
                    if w:
                        w.queue.put_nowait(WatchEvent(
                            msg["kind"], msg["key"], msg.get("value")))
                elif push == "sub":
                    s = self._subs.get(msg["sid"])
                    if s:
                        s.queue.put_nowait(msg["payload"])
                else:
                    fut = self._pending.pop(msg.get("id"), None)
                    if fut and not fut.done():
                        fut.set_result(msg)
        except (ConnectionResetError, OSError):
            pass
        if self._closed or gen != self._conn_gen:
            return  # shut down, or a newer connection owns the client
        self._fail_all(ConnectionError("control plane gone"))
        self._writer = None  # _call fails fast until reconnected
        self._schedule_reconnect()

    def _schedule_reconnect(self) -> None:
        if self._closed or self._reconnecting:
            return
        self._reconnecting = True
        self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        backoff = 0.5
        try:
            while not self._closed:
                # Each attempt owns a fresh generation, bumped BEFORE the
                # dial so the rx loop of any prior attempt exits silently
                # (a bump only after success would let a failed attempt's
                # rx re-poison every stream queue on its EOF — the
                # per-retry spam the gen guard exists to prevent).
                # Pending calls of the broken attempt are failed here
                # rather than left hanging.
                self._conn_gen += 1
                self._fail_pending(ConnectionError(
                    "control plane reconnecting"))
                try:
                    reader, writer = \
                        await asyncio.open_connection(self.host, self.port)
                except OSError:
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 15.0)
                    continue
                self._conn_gen += 1
                self._reader, self._writer = reader, writer
                self._rx_task = asyncio.create_task(self._rx_loop())
                try:
                    # Re-establish stream state under the original sids:
                    # the server replays watch state as synthetic puts;
                    # sub streams simply resume from now.
                    for sid, w in list(self._watches.items()):
                        await asyncio.wait_for(
                            self._call("watch", prefix=w.prefix, sid=sid),
                            10.0)
                    for sid, s in list(self._subs.items()):
                        await asyncio.wait_for(
                            self._call("subscribe", subject=s.subject,
                                       sid=sid), 10.0)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    continue  # connection died again: dial once more
                logger.info("control plane reconnected (%d watches, %d "
                            "subs restored)", len(self._watches),
                            len(self._subs))
                # Leases did not survive (server restart or TTL expiry
                # during the outage).  Cancel their keepalive loops FIRST
                # — a stale loop finding alive=False would fire a second
                # session-loss, double-registering every endpoint and
                # leaking the first replacement lease — then let owners
                # re-register (each grant starts a fresh keepalive).
                for t in self._keepalive_tasks.values():
                    t.cancel()
                self._keepalive_tasks.clear()
                self._fire_session_loss()
                return
        finally:
            self._reconnecting = False

    async def _call(self, op: str, **kw) -> dict:
        if self._writer is None or self._writer.is_closing():
            raise ConnectionError("control plane not connected")
        mid = next(self._mid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        async with self._send_lock:
            self._writer.write(
                json.dumps({"op": op, "id": mid, **kw}).encode() + b"\n")
            await self._writer.drain()
        msg = await fut
        if not msg.get("ok"):
            raise RuntimeError(f"control plane {op} failed: {msg.get('error')}")
        return msg

    # -- leases -----------------------------------------------------------

    async def lease_grant(self, ttl: float = 10.0,
                          auto_keepalive: bool = True) -> int:
        lease = (await self._call("lease_grant", ttl=ttl))["lease"]
        if auto_keepalive:
            self._keepalive_tasks[lease] = asyncio.create_task(
                self._keepalive_loop(lease, ttl))
        return lease

    async def _keepalive_loop(self, lease: int, ttl: float) -> None:
        try:
            while True:
                await asyncio.sleep(ttl / 3.0)
                try:
                    msg = await self._call("lease_keepalive", lease=lease)
                except (RuntimeError, ConnectionError):
                    return
                if not msg.get("alive"):
                    # Lease expired server-side (stall > TTL; a restart
                    # drops the connection and goes through reconnect
                    # instead): registrations are gone.  Fire the
                    # session-loss path so owners re-register.
                    logger.error(
                        "lease %d expired server-side; replaying "
                        "registrations", lease)
                    self._keepalive_tasks.pop(lease, None)
                    self._fire_session_loss()
                    return
        except asyncio.CancelledError:
            pass

    async def lease_revoke(self, lease: int) -> None:
        t = self._keepalive_tasks.pop(lease, None)
        if t:
            t.cancel()
        await self._call("lease_revoke", lease=lease)

    # -- kv ---------------------------------------------------------------

    async def put(self, key: str, value: dict,
                  lease: Optional[int] = None) -> None:
        await self._call("put", key=key, value=value, lease=lease)

    async def get(self, key: str) -> Optional[dict]:
        return (await self._call("get", key=key))["value"]

    async def get_prefix(self, prefix: str) -> Dict[str, dict]:
        return (await self._call("get_prefix", prefix=prefix))["values"]

    async def delete(self, key: str) -> bool:
        return (await self._call("delete", key=key))["deleted"]

    async def watch_prefix(self, prefix: str) -> _RemoteWatch:
        sid = next(self._sid)
        w = _RemoteWatch(self, sid, prefix)
        self._watches[sid] = w
        await self._call("watch", prefix=prefix, sid=sid)
        return w

    def _drop_watch(self, sid: int) -> None:
        self._watches.pop(sid, None)
        asyncio.ensure_future(self._call("unwatch", sid=sid))

    # -- pub/sub ----------------------------------------------------------

    async def publish(self, subject: str, payload: dict) -> None:
        await self._call("publish", subject=subject, payload=payload)

    async def subscribe(self, subject: str) -> _RemoteSubscription:
        sid = next(self._sid)
        s = _RemoteSubscription(self, sid, subject)
        self._subs[sid] = s
        await self._call("subscribe", subject=subject, sid=sid)
        return s

    def _drop_sub(self, sid: int) -> None:
        self._subs.pop(sid, None)
        asyncio.ensure_future(self._call("unsubscribe", sid=sid))

    # -- queues -----------------------------------------------------------

    async def queue_push(self, name: str, payload: dict) -> None:
        await self._call("queue_push", queue=name, payload=payload)

    async def queue_pop(self, name: str,
                        visibility_timeout: float = 30.0):
        msg = await self._call("queue_pop", queue=name,
                               visibility_timeout=visibility_timeout)
        return msg["msg_id"], msg["payload"]

    async def queue_ack(self, name: str, msg_id: int) -> bool:
        return (await self._call("queue_ack", queue=name,
                                 msg_id=msg_id))["acked"]

    async def queue_len(self, name: str) -> int:
        return (await self._call("queue_len", queue=name))["n"]
