"""Device-truth profiling plane: what the hardware ACTUALLY did.

Every performance claim this repo gates on — per-chip mbu,
`kv_read_bytes_modeled`, `ring_exchange_bytes_modeled`, transfer GB/s —
is modeled arithmetic compared against datasheets.  This module is the
live path from a serving worker to XLA's own accounting, in three legs:

- **ProgramCostRegistry** (cost-analysis harvest) — the engine's
  dispatch sites already classify every jitted program by the same
  (tag, shape-signature) identity the flight recorder stamps on
  recompiles; on a FIRST-SEEN shape (``EngineStepCounters.note_dispatch``
  returning True) the engine hands the about-to-compile callable + its
  args to :meth:`DeviceProfiler.harvest`, which runs
  ``fn.lower(*args).cost_analysis()`` — XLA's flops / bytes-accessed /
  optimal-seconds estimate, available WITHOUT executing or donating
  anything and without a backend compile.  Harvest cost rides the
  compile event (already tens of ms..s); the steady hot path never sees
  it — steady-window `EngineStepCounters` deltas are byte-identical
  plane-on vs plane-off (pinned in tests + bench_gate --smoke, the same
  discipline as the flight recorder).
- **DriftAuditor** (modeled-vs-measured audit) — folds the registry's
  XLA bytes-accessed per dispatch class against the engine's modeled
  per-chip KV bytes, and XLA's roofline time against the measured
  window-interval EWMA, as `dynamo_modeled_vs_measured_ratio{series=}`.
  The invariant is ONE-SIDED: modeled KV bytes are a *component* of
  what XLA sweeps (weights ride every dispatch too), so ratio =
  modeled/measured must stay ≤ band_hi (default 1.25) — a modeled
  series that CLAIMS more bytes than the hardware touched is lying
  (the PR 16 int8 scale-pack double-count class of bug).  Three
  consecutive out-of-band observations PAGE: a `drift_page` event via
  ``FlightRecorder.record_always`` + an async ring dump, same trigger
  shape as the SLO monitor.
- **On-demand device capture** — a bounded ``jax.profiler``
  start/stop_trace on a LIVE worker (``/debug/deviceprofile?ms=500`` on
  the StatusServer, frontend proxy route, and the control-plane
  ``profile/<pid>`` command key — same shape as ``drain/<pid>``),
  writing xplane + Chrome-trace output under ``--flight-dump-dir`` in a
  ``deviceprofile_<service>_<pid>`` directory that
  ``tools/trace_merge.py --device <dir>`` merges onto the owning
  worker's host-span lanes.

Surfaces: `dynamo_program_flops{program=}` /
`dynamo_program_bytes_accessed{program=}` /
`dynamo_program_registry_size` /
`dynamo_modeled_vs_measured_ratio{series=}` on worker `/metrics`,
`dynamo top`'s DRIFT column, `--once --json` rows (so the
metrics_aggregator pre-sums the fleet ratio), and
`/debug/deviceprofile` on every status surface.

Stdlib-only at import time by design (jax is imported lazily inside
harvest/capture): the engine and worker main import this module
unconditionally, mirroring flight_recorder.
"""

from __future__ import annotations

import glob as _glob
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from dynamo_tpu.runtime import flight_recorder
from dynamo_tpu.runtime.logutil import warn_rate_limited

logger = logging.getLogger(__name__)

# Capture bound: a device trace buffers on-device and in host RAM; an
# unbounded capture on a serving worker is an incident, not a feature.
DEFAULT_MAX_CAPTURE_MS = 2000

# Drift band (modeled / measured).  The invariant is one-sided: modeled
# KV bytes can legitimately be a small fraction of XLA's total
# bytes-accessed (weights dominate tiny models), so the low edge
# defaults to 0 (disabled); the HIGH edge is the honesty gate — modeled
# traffic claiming more than the hardware touched (plus estimator
# headroom) means the accounting double-counts.
DEFAULT_BAND_HI = 1.25
DEFAULT_BAND_LO = 0.0
# Consecutive out-of-band observations before a series PAGEs — one
# scrape-time blip (e.g. a registry mid-warmup) must not dump the ring.
PAGE_STRIKES = 3

# Control-plane capture command prefix: `profile/{pid}` or
# `profile/instance/{instance_id}` (value: optional capture ms).
PROFILE_PREFIX = "profile/"


def profile_key_pid(pid: int) -> str:
    return f"{PROFILE_PREFIX}{pid}"


def profile_key_instance(instance_id: int) -> str:
    return f"{PROFILE_PREFIX}instance/{instance_id}"


def program_label(tag: str, sig: Tuple) -> str:
    """The registry/metrics identity of a compiled program — the same
    (tag, shape-signature) key note_dispatch/flight stamps use."""
    return tag + ":" + ",".join(str(x) for x in sig)


class ProgramCostRegistry:
    """Host-side map of compiled-program label → XLA cost analysis.

    Written only at compile time (first-seen shapes — a handful per
    process lifetime), read at scrape time; plain dict under the GIL,
    iterated via snapshot."""

    def __init__(self) -> None:
        self._programs: Dict[str, Dict[str, Optional[float]]] = {}

    def record(self, label: str, *, flops: float, bytes_accessed: float,
               optimal_s: Optional[float] = None) -> None:
        self._programs[label] = {
            "flops": float(flops),
            "bytes_accessed": float(bytes_accessed),
            "optimal_s": (float(optimal_s)
                          if optimal_s is not None else None),
        }

    def get(self, label: str) -> Optional[Dict[str, Optional[float]]]:
        return self._programs.get(label)

    def size(self) -> int:
        return len(self._programs)

    def items(self) -> List[Tuple[str, Dict[str, Optional[float]]]]:
        return sorted(self._programs.items())

    def tag_values(self, key: str, *tags: str) -> List[float]:
        """All recorded `key` values for programs whose tag is one of
        `tags` (label prefix before the first ':')."""
        out: List[float] = []
        for label, costs in list(self._programs.items()):
            if label.split(":", 1)[0] in tags:
                v = costs.get(key)
                if v is not None:
                    out.append(v)
        return out

    def mean_for_tags(self, key: str, *tags: str) -> Optional[float]:
        vals = self.tag_values(key, *tags)
        return sum(vals) / len(vals) if vals else None

    def top_by(self, key: str, k: int = 10
               ) -> List[Tuple[str, Dict[str, Optional[float]]]]:
        """Top-K programs by a cost column (profile_trace's summary)."""
        rows = [(label, costs) for label, costs in self.items()
                if costs.get(key) is not None]
        rows.sort(key=lambda r: r[1][key], reverse=True)
        return rows[:k]

    def reset(self) -> None:
        self._programs.clear()


class DriftAuditor:
    """Band state machine over modeled/measured ratios, one per series.

    `observe` is called at SCRAPE time (worker_metrics_text →
    audit_engine), never on the engine hot path.  A series that stays
    out of band for PAGE_STRIKES consecutive observations transitions
    to PAGE: one `drift_page` flight event (record_always — drift
    evidence must land even on a recorder that never opted in) plus an
    async ring dump; returning in band resets the episode."""

    def __init__(self, band_hi: float = DEFAULT_BAND_HI,
                 band_lo: float = DEFAULT_BAND_LO) -> None:
        self.band_hi = band_hi
        self.band_lo = band_lo
        self._series: Dict[str, Dict] = {}

    def observe(self, series: str, modeled: float,
                measured: float) -> Optional[float]:
        """Fold one modeled/measured pair; returns the ratio, or None
        when the pair is unobservable (no measured denominator yet)."""
        if measured <= 0 or modeled < 0:
            return None
        ratio = modeled / measured
        st = self._series.setdefault(
            series, {"ratio": None, "state": "ok", "strikes": 0})
        st["ratio"] = ratio
        in_band = self.band_lo <= ratio <= self.band_hi
        if in_band:
            if st["state"] == "page":
                rec = flight_recorder.get_recorder()
                rec.record_always("drift_ok", series=series,
                                  ratio=round(ratio, 4))
            st["state"] = "ok"
            st["strikes"] = 0
            return ratio
        st["strikes"] += 1
        if st["strikes"] >= PAGE_STRIKES and st["state"] != "page":
            st["state"] = "page"
            rec = flight_recorder.get_recorder()
            rec.record_always(
                "drift_page", series=series, ratio=round(ratio, 4),
                band_lo=self.band_lo, band_hi=self.band_hi,
                strikes=st["strikes"])
            logger.error(
                "modeled-vs-measured drift PAGE: series=%s ratio=%.4f "
                "outside [%s, %s] for %d consecutive observations — "
                "modeled accounting is over-claiming; dumping flight "
                "recorder", series, ratio, self.band_lo, self.band_hi,
                st["strikes"])
            rec.dump_async("drift_page")
        return ratio

    def ratios(self) -> Dict[str, float]:
        return {s: st["ratio"] for s, st in self._series.items()
                if st["ratio"] is not None}

    def states(self) -> Dict[str, Dict]:
        return {s: dict(st) for s, st in self._series.items()}

    def paged(self) -> bool:
        return any(st["state"] == "page"
                   for st in self._series.values())

    def reset(self) -> None:
        self._series.clear()


class DeviceProfiler:
    """The per-process device-truth plane: registry + auditor + capture.

    Disabled by default (module singleton — tests and libraries that
    import the engine must not pay for it); the worker flag
    ``--device-profiler on`` enables it at process startup."""

    def __init__(self, service: str = "dynamo", *, enabled: bool = False,
                 max_capture_ms: int = DEFAULT_MAX_CAPTURE_MS,
                 dump_dir: Optional[str] = None,
                 band_hi: float = DEFAULT_BAND_HI,
                 band_lo: float = DEFAULT_BAND_LO) -> None:
        self.service = service
        self.enabled = enabled
        self.max_capture_ms = max_capture_ms
        self.dump_dir = dump_dir
        self.registry = ProgramCostRegistry()
        self.auditor = DriftAuditor(band_hi=band_hi, band_lo=band_lo)
        self.harvests = 0
        self.harvest_failures = 0
        self.captures = 0
        self.last_capture_dir: Optional[str] = None
        # One capture at a time: jax.profiler keeps process-global trace
        # state; a second start_trace mid-capture raises.
        self._capture_lock = threading.Lock()

    # -- configuration -----------------------------------------------------

    def configure(self, *, service: Optional[str] = None,
                  enabled: Optional[bool] = None,
                  max_capture_ms: Optional[int] = None,
                  dump_dir: Optional[str] = None,
                  band_hi: Optional[float] = None,
                  band_lo: Optional[float] = None) -> "DeviceProfiler":
        """In-place reconfiguration — the module singleton is shared by
        reference (the engine captured it at __init__); identity must
        survive, same contract as FlightRecorder.configure."""
        if service is not None:
            self.service = service
        if enabled is not None:
            self.enabled = enabled
        if max_capture_ms is not None:
            self.max_capture_ms = int(max_capture_ms)
        if dump_dir is not None:
            self.dump_dir = dump_dir
        if band_hi is not None:
            self.auditor.band_hi = band_hi
        if band_lo is not None:
            self.auditor.band_lo = band_lo
        return self

    def reset(self) -> None:
        """Drop all state (test isolation)."""
        self.registry.reset()
        self.auditor.reset()
        self.harvests = 0
        self.harvest_failures = 0
        self.captures = 0
        self.last_capture_dir = None

    # -- leg 1: cost-analysis harvest (compile-time only) ------------------

    def harvest(self, tag: str, sig: Tuple, fn, args: Tuple) -> bool:
        """Capture XLA's cost analysis for a program about to compile.

        Called from the engine's dispatch sites ONLY on first-seen
        (tag, sig) shapes — the cost rides the compile event, never the
        steady window.  ``fn.lower(*args)`` traces without executing or
        donating (safe alongside donate_argnums buffers) and
        ``Lowered.cost_analysis()`` answers off the StableHLO without a
        backend compile.  Returns True when a record landed.  MUST
        never break serving: sharded/pp step makers may hand back plain
        callables without ``.lower``, and cost analysis availability
        varies by backend — every failure path degrades to a
        rate-limited warning."""
        if not self.enabled:
            return False
        lower = getattr(fn, "lower", None)
        if lower is None:
            return False
        label = program_label(tag, sig)
        try:
            ca = lower(*args).cost_analysis()
            # Older jax returns a per-partition list; newer a plain dict.
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if not isinstance(ca, dict):
                return False
            self.registry.record(
                label,
                flops=float(ca.get("flops", 0.0)),
                # XLA's key really does contain a space.
                bytes_accessed=float(ca.get("bytes accessed", 0.0)),
                optimal_s=ca.get("optimal_seconds"))
            self.harvests += 1
            return True
        except Exception as e:
            self.harvest_failures += 1
            warn_rate_limited(
                logger, "device_profiler.harvest", 60.0,
                "cost-analysis harvest failed for %s: %s: %s",
                label, type(e).__name__, e)
            return False

    # -- leg 2: drift audit (scrape-time only) -----------------------------

    def audit_engine(self, core) -> Dict[str, float]:
        """Fold the engine's modeled counters against the registry's
        XLA-measured costs; returns the current ratios.  Scrape-time
        only (worker_metrics_text / dynamo top) — reads counters the
        engine thread increments, never blocks it.

        Series:
        - ``kv_decode`` — modeled per-chip KV bytes swept
          (kv_read_bytes_modeled) vs XLA bytes-accessed summed over the
          decode dispatch classes (window × window_dispatches, decode1
          mean × single_step_dispatches, spec mean × spec_dispatches).
          One-sided: modeled is a component of measured, so the ratio
          must stay ≤ band_hi.
        - ``window_time`` — XLA's roofline optimal-seconds per window
          (TPU backends only) vs the measured window-interval EWMA;
          absent where the backend reports no optimal_seconds (CPU).
        """
        if not self.enabled:
            return {}
        c = getattr(core, "counters", None)
        if c is None:
            return {}
        reg = self.registry
        measured = 0.0
        win_bytes = reg.mean_for_tags("bytes_accessed", "window")
        if win_bytes is not None:
            measured += win_bytes * c.window_dispatches
        d1_bytes = reg.mean_for_tags("bytes_accessed",
                                     "decode1", "decode1g")
        if d1_bytes is not None:
            measured += d1_bytes * c.single_step_dispatches
        spec_bytes = reg.mean_for_tags("bytes_accessed", "spec")
        if spec_bytes is not None:
            measured += spec_bytes * c.spec_dispatches
        if measured > 0:
            self.auditor.observe("kv_decode",
                                 float(c.kv_read_bytes_modeled), measured)
        opt_s = reg.mean_for_tags("optimal_s", "window")
        ewma = c.decode_token_cost_ewma
        if (opt_s is not None and ewma is not None
                and c.window_dispatches > 0 and c.decode_tokens_emitted):
            wall_per_window = ewma * (c.decode_tokens_emitted
                                      / c.window_dispatches)
            self.auditor.observe("window_time", opt_s, wall_per_window)
        return self.auditor.ratios()

    # -- leg 3: on-demand bounded device capture ---------------------------

    def capture_dir(self) -> str:
        import tempfile

        d = self.dump_dir or tempfile.gettempdir()
        return os.path.join(
            d, "deviceprofile_"
               f"{self.service.replace('/', '_')}_{os.getpid()}")

    def capture(self, ms: int) -> dict:
        """Bounded jax.profiler capture on the live process: start the
        trace, sleep `ms` (clamped to max_capture_ms) while the serving
        threads keep dispatching, stop, and report what landed.  Runs
        OFF the engine thread (status-server executor / control-plane
        watcher); serialized — jax's profiler state is process-global."""
        ms = max(1, min(int(ms), self.max_capture_ms))
        if not self.enabled:
            return {"ok": False, "error": "device profiler disabled "
                                          "(--device-profiler off)"}
        if not self._capture_lock.acquire(blocking=False):
            return {"ok": False, "error": "capture already in progress"}
        try:
            import jax

            out_dir = self.capture_dir()
            os.makedirs(out_dir, exist_ok=True)
            wall_start = time.time()
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(ms / 1000.0)
            finally:
                jax.profiler.stop_trace()
            # Sidecar for tools/trace_merge.py --device: the profiler's
            # Chrome-trace timestamps are RELATIVE to trace start; the
            # wall anchor here is what lets device activity land
            # time-aligned under this worker's host spans.
            import json as _json

            with open(os.path.join(out_dir, "capture_meta.json"),
                      "w") as f:
                _json.dump({"service": self.service, "pid": os.getpid(),
                            "ms": ms, "wall_start": wall_start,
                            "wall_end": time.time()}, f)
            files = sorted(
                os.path.relpath(p, out_dir)
                for pat in ("**/*.xplane.pb", "**/*.trace.json.gz")
                for p in _glob.glob(os.path.join(out_dir, pat),
                                    recursive=True))
            self.captures += 1
            self.last_capture_dir = out_dir
            logger.warning("device capture: %d ms → %s (%d file(s))",
                           ms, out_dir, len(files))
            return {"ok": bool(files), "ms": ms, "dir": out_dir,
                    "files": files, "pid": os.getpid(),
                    "service": self.service,
                    **({} if files else
                       {"error": "capture produced no trace output"})}
        except Exception as e:
            logger.warning("device capture failed: %s: %s",
                           type(e).__name__, e)
            return {"ok": False, "ms": ms,
                    "error": f"{type(e).__name__}: {e}"}
        finally:
            self._capture_lock.release()

    # -- surfaces ----------------------------------------------------------

    def metrics_lines(self) -> List[str]:
        """Prometheus text lines for worker /metrics (scrape-time)."""
        out = [
            "# HELP dynamo_program_registry_size compiled programs with "
            "harvested XLA cost analysis",
            "# TYPE dynamo_program_registry_size gauge",
            f"dynamo_program_registry_size {self.registry.size()}",
        ]
        items = self.registry.items()
        if items:
            out.append("# HELP dynamo_program_flops XLA-estimated flops "
                       "per compiled program dispatch")
            out.append("# TYPE dynamo_program_flops gauge")
            for label, costs in items:
                out.append(f'dynamo_program_flops{{program="{label}"}} '
                           f'{costs["flops"]}')
            out.append("# HELP dynamo_program_bytes_accessed "
                       "XLA-estimated bytes accessed per compiled "
                       "program dispatch")
            out.append("# TYPE dynamo_program_bytes_accessed gauge")
            for label, costs in items:
                out.append(
                    f'dynamo_program_bytes_accessed{{program="{label}"}} '
                    f'{costs["bytes_accessed"]}')
        ratios = self.auditor.ratios()
        if ratios:
            out.append("# HELP dynamo_modeled_vs_measured_ratio modeled "
                       "accounting vs XLA-measured truth per series "
                       "(honest: <= band_hi)")
            out.append("# TYPE dynamo_modeled_vs_measured_ratio gauge")
            for series in sorted(ratios):
                out.append(
                    "dynamo_modeled_vs_measured_ratio"
                    f'{{series="{series}"}} {round(ratios[series], 6)}')
        return out

    def debug_payload(self) -> dict:
        """The `/debug/deviceprofile` GET (no ms param) / status body."""
        return {
            "service": self.service,
            "enabled": self.enabled,
            "pid": os.getpid(),
            "max_capture_ms": self.max_capture_ms,
            "registry_size": self.registry.size(),
            "programs": dict(self.registry.items()),
            "drift": self.auditor.states(),
            "harvests": self.harvests,
            "harvest_failures": self.harvest_failures,
            "captures": self.captures,
            "last_capture_dir": self.last_capture_dir,
        }


# ---------------------------------------------------------------------------
# Process singleton (same pattern as flight_recorder.get_recorder)

_profiler = DeviceProfiler()


def get_profiler() -> DeviceProfiler:
    return _profiler


def configure(**kwargs) -> DeviceProfiler:
    return _profiler.configure(**kwargs)


def add_device_profiler_args(parser) -> None:
    """The shared --device-profiler CLI surface (worker)."""
    parser.add_argument("--device-profiler", choices=("on", "off"),
                        default="on",
                        help="device-truth plane: XLA cost-analysis "
                             "harvest at compile time "
                             "(dynamo_program_* metrics), "
                             "modeled-vs-measured drift audit, and "
                             "on-demand bounded jax.profiler capture "
                             "(/debug/deviceprofile?ms=N, control-plane "
                             "profile/<pid>)")
    parser.add_argument("--device-profile-max-ms", type=int,
                        default=DEFAULT_MAX_CAPTURE_MS,
                        help="upper bound on one on-demand device "
                             "capture (requests above it are clamped)")
    parser.add_argument("--drift-band-hi", type=float,
                        default=DEFAULT_BAND_HI,
                        help="modeled/measured ratio above which the "
                             "drift auditor strikes (3 consecutive "
                             "out-of-band scrapes PAGE + dump the "
                             "flight recorder)")
    parser.add_argument("--drift-band-lo", type=float,
                        default=DEFAULT_BAND_LO,
                        help="modeled/measured ratio below which the "
                             "drift auditor strikes (default 0: "
                             "under-claiming is not an error — modeled "
                             "series are components of XLA totals)")


def configure_from_args(args, service: str) -> DeviceProfiler:
    """Apply the add_device_profiler_args flags (plus the shared
    --flight-dump-dir capture destination) to the process profiler."""
    return configure(
        service=service,
        enabled=getattr(args, "device_profiler", "on") != "off",
        max_capture_ms=getattr(args, "device_profile_max_ms",
                               DEFAULT_MAX_CAPTURE_MS),
        dump_dir=getattr(args, "flight_dump_dir", None),
        band_hi=getattr(args, "drift_band_hi", DEFAULT_BAND_HI),
        band_lo=getattr(args, "drift_band_lo", DEFAULT_BAND_LO))
