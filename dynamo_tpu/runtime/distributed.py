"""DistributedRuntime: Namespace → Component → Endpoint → Client.

Role of the reference's `lib/runtime/src/{distributed,component}.rs`
(SURVEY.md §2.1): a cluster handle owning the control-plane connection and
one RpcServer; components register endpoint instances under

    instances/{namespace}/{component}/{endpoint}:{lease_id}

with lease-backed liveness (value carries the worker's RPC address +
metadata); clients watch that prefix, keep a live instance set, and route
with the PushRouter modes (random / round-robin / direct / KV —
`pipeline/network/egress/push_router.rs:31-62`).
"""

from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from typing import AsyncIterator, Dict, List, Optional

from dynamo_tpu.runtime.rpc import Handler, RpcClient, RpcServer

logger = logging.getLogger(__name__)

INSTANCE_ROOT = "instances"
MODEL_ROOT = "models"  # reference MODEL_ROOT_PATH (`discovery.rs:14`)


@dataclass(frozen=True)
class Instance:
    """One live endpoint instance (reference `component.rs` Instance)."""

    instance_id: int           # lease id doubles as instance id
    namespace: str
    component: str
    endpoint: str
    address: str               # host:port of the worker's RpcServer
    metadata: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return (f"{INSTANCE_ROOT}/{self.namespace}/{self.component}/"
                f"{self.endpoint}:{self.instance_id}")

    def to_dict(self) -> dict:
        return {"instance_id": self.instance_id, "namespace": self.namespace,
                "component": self.component, "endpoint": self.endpoint,
                "address": self.address, "metadata": self.metadata}

    @staticmethod
    def from_dict(d: dict) -> "Instance":
        return Instance(
            instance_id=d["instance_id"], namespace=d["namespace"],
            component=d["component"], endpoint=d["endpoint"],
            address=d["address"], metadata=d.get("metadata", {}))


class DistributedRuntime:
    """Per-process cluster handle (reference `DistributedRuntime`,
    `lib/runtime/src/lib.rs:153`)."""

    def __init__(self, control_plane, rpc_host: str = "127.0.0.1") -> None:
        self.cp = control_plane
        self.rpc = RpcServer()
        self._rpc_host = rpc_host
        self._started = False
        self._clients: Dict[str, RpcClient] = {}

    async def start(self) -> None:
        if not self._started:
            await self.rpc.start(self._rpc_host)
            self._started = True

    async def shutdown(self) -> None:
        for c in self._clients.values():
            await c.close()
        await self.rpc.stop()

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    def client_for(self, address: str) -> RpcClient:
        c = self._clients.get(address)
        if c is None:
            c = RpcClient(address)
            self._clients[address] = c
        return c

    async def evict_client(self, address: str) -> None:
        """Drop the cached client for a dead address (workers use ephemeral
        ports, so churn would otherwise grow the cache unboundedly)."""
        c = self._clients.pop(address, None)
        if c is not None:
            await c.close()


@dataclass
class Namespace:
    runtime: DistributedRuntime
    name: str

    def component(self, name: str) -> "Component":
        return Component(self.runtime, self.name, name)


@dataclass
class Component:
    runtime: DistributedRuntime
    namespace: str
    name: str

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.runtime, self.namespace, self.name, name)


class Endpoint:
    def __init__(self, runtime: DistributedRuntime, namespace: str,
                 component: str, name: str) -> None:
        self.runtime = runtime
        self.namespace = namespace
        self.component = component
        self.name = name
        self._lease: Optional[int] = None
        self._instance: Optional[Instance] = None
        self._session_cb = None
        # Extra leased puts replayed on re-registration (register_llm's
        # model entry rides here).
        self._extra_puts: List = []

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    @property
    def rpc_name(self) -> str:
        return self.path

    # -- serving ----------------------------------------------------------

    async def serve(self, handler: Handler,
                    metadata: Optional[dict] = None,
                    lease_ttl: float = 10.0) -> Instance:
        """Register the handler and announce the instance (reference
        `endpoint.serve_endpoint`)."""
        await self.runtime.start()
        self.runtime.rpc.register(self.rpc_name, handler)
        lease = await self.runtime.cp.lease_grant(lease_ttl)
        inst = Instance(
            instance_id=lease, namespace=self.namespace,
            component=self.component, endpoint=self.name,
            address=self.runtime.rpc.address, metadata=metadata or {})
        await self.runtime.cp.put(inst.key, inst.to_dict(), lease=lease)
        self._lease, self._instance = lease, inst
        # Survive a control-plane restart: when the client reports the
        # server-side session lost (reconnect done, or keepalive found
        # the lease dead), grant a fresh lease and replay every
        # registration under the SAME instance id — router state, KV
        # events and in-flight streams all key on it (VERDICT r4 next-6;
        # reference `transports/etcd.rs` lease recovery).
        on_loss = getattr(self.runtime.cp, "on_session_loss", None)
        if on_loss is not None:
            async def _reregister():
                if self._instance is None or self._lease is None:
                    return  # left gracefully; do not resurrect
                new_lease = await self.runtime.cp.lease_grant(lease_ttl)
                self._lease = new_lease
                await self.runtime.cp.put(self._instance.key,
                                          self._instance.to_dict(),
                                          lease=new_lease)
                for put in list(self._extra_puts):
                    await put()
                logger.warning(
                    "re-registered %s (instance %d) under lease %d after "
                    "control-plane session loss", self.path,
                    self._instance.instance_id, new_lease)

            self._session_cb = _reregister
            on_loss(_reregister)
        logger.info("serving %s as instance %d at %s",
                    self.path, lease, inst.address)
        return inst

    def add_registration_put(self, put) -> None:
        """Register an async callable replayed (bound to the current
        lease) whenever the endpoint re-registers after a control-plane
        session loss."""
        self._extra_puts.append(put)

    async def leave(self) -> None:
        """Graceful deregistration: revoke lease (instant removal from
        routing — reference decode-worker scale-down semantics,
        `load_planner.md:21`), keep serving in-flight streams."""
        if self._session_cb is not None:
            remove = getattr(self.runtime.cp, "remove_session_callback",
                             None)
            if remove is not None:
                remove(self._session_cb)
            self._session_cb = None
        self._instance = None  # a later session loss must not resurrect
        if self._lease is not None:
            await self.runtime.cp.lease_revoke(self._lease)
            self._lease = None

    # -- client side ------------------------------------------------------

    async def client(self, router_mode: str = "round_robin") -> "Client":
        c = Client(self, router_mode)
        await c.start()
        return c


class Client:
    """Instance-set watcher + push router (reference `component/client.rs`
    InstanceSource::Dynamic + `push_router.rs` modes)."""

    def __init__(self, endpoint: Endpoint, router_mode: str = "round_robin"):
        self.endpoint = endpoint
        self.router_mode = router_mode
        self._instances: Dict[int, Instance] = {}
        self._watch = None
        self._watch_task: Optional[asyncio.Task] = None
        self._rr = 0
        self._ready = asyncio.Event()

    @property
    def prefix(self) -> str:
        e = self.endpoint
        return f"{INSTANCE_ROOT}/{e.namespace}/{e.component}/{e.name}:"

    async def start(self) -> None:
        # watch_prefix delivers current state as synthetic put events
        # before live updates, so the watch loop alone maintains the set.
        self._watch = await self.endpoint.runtime.cp.watch_prefix(self.prefix)
        self._watch_task = asyncio.create_task(self._watch_loop())

    async def stop(self) -> None:
        if self._watch:
            self._watch.cancel()
        if self._watch_task:
            self._watch_task.cancel()
            try:
                await self._watch_task
            except asyncio.CancelledError:
                pass

    async def _watch_loop(self) -> None:
        while True:
            try:
                await self._watch_events()
                return
            except ConnectionError:
                # One poison per control-plane outage.  The client's
                # reconnect path re-registers the watch and replays
                # current state as synthetic puts into this SAME queue,
                # so the consumer must RESUME iterating, not exit —
                # exiting froze discovery for the process lifetime.
                # (At shutdown stop() cancels this task, which breaks
                # the loop via CancelledError.)  Unhandled, the error
                # also surfaced as "Task exception was never retrieved"
                # noise at loop close in every distributed test.
                continue

    async def _watch_events(self) -> None:
        async for ev in self._watch:
            if ev.kind == "put" and ev.value:
                inst = Instance.from_dict(ev.value)
                self._instances[inst.instance_id] = inst
                self._ready.set()
            elif ev.kind == "delete":
                iid = int(ev.key.rsplit(":", 1)[1])
                self._instances.pop(iid, None)
                if not self._instances:
                    self._ready.clear()

    # -- instance views ---------------------------------------------------

    def instance_ids(self) -> List[int]:
        return sorted(self._instances)

    def instances(self) -> List[Instance]:
        return [self._instances[i] for i in sorted(self._instances)]

    async def wait_for_instances(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self._ready.wait(), timeout)

    # -- routing ----------------------------------------------------------

    def _pick(self, instance_id: Optional[int] = None) -> Instance:
        if not self._instances:
            raise NoInstancesError(f"no instances for {self.endpoint.path}")
        if instance_id is not None:  # direct
            inst = self._instances.get(instance_id)
            if inst is None:
                raise NoInstancesError(
                    f"instance {instance_id} gone from {self.endpoint.path}")
            return inst
        ids = sorted(self._instances)
        if self.router_mode == "random":
            return self._instances[random.choice(ids)]
        # round_robin default
        inst = self._instances[ids[self._rr % len(ids)]]
        self._rr += 1
        return inst

    async def generate(
        self, payload: dict, instance_id: Optional[int] = None
    ) -> AsyncIterator[dict]:
        """Route one streaming request (push router).  Raises
        ConnectionError mid-stream if the instance dies — the migration
        operator's retry signal."""
        inst = self._pick(instance_id)
        client = self.endpoint.runtime.client_for(inst.address)
        try:
            async for delta in client.call(self.endpoint.rpc_name, payload):
                yield delta
        except ConnectionError:
            # Dead address: evict the cached client so churned workers
            # don't accumulate, then let migration handle the retry.
            await self.endpoint.runtime.evict_client(inst.address)
            raise

    async def round_robin(self, payload: dict) -> AsyncIterator[dict]:
        async for d in self.generate(payload):
            yield d

    async def direct(self, payload: dict,
                     instance_id: int) -> AsyncIterator[dict]:
        async for d in self.generate(payload, instance_id=instance_id):
            yield d


class NoInstancesError(RuntimeError):
    """No live instances (reference NATS NoResponders analog)."""
