"""Per-process flight recorder + engine-thread stall watchdog.

The postmortem plane for chaos-era serving: PR 3's traces and PR 5's
gauges describe the HEALTHY steady state and evaporate exactly when a
process wedges or dies — a scrape of a hung worker times out, a crashed
one takes its span ring with it.  This module is the black box that
survives those moments:

- **FlightRecorder** — a fixed-size ring of structured host-side events
  (per-step counter deltas, dispatch shapes, scheduler admissions and
  preemptions, KV plane choices, tier demotions, HBM samples, SLO state
  transitions).  Recording is lock-light (one atomic `itertools.count`
  next + one list-slot store under the GIL) and allocation-thin: hot
  paths pass PRE-COMPUTED scalars only — dynamo_lint rule DL006 rejects
  f-strings, container displays, and call expressions in
  `record(...)` arguments inside `@hot_path` bodies, so the formatting
  cost is paid at dump time, never per step.
- **Dump triggers** — the ring serializes to JSONL when something goes
  wrong: SLO PAGE transition (runtime/slo.py), slow-request
  force-sample (runtime/tracing.py), `SIGUSR2` (operator-initiated live
  snapshot), atexit (+ `faulthandler` armed for hard crashes, whose C
  traceback lands in the same dump file), and the stall watchdog below.
  Dumps are rate-limited per reason so a flapping trigger cannot grind
  the disk.
- **StallWatchdog** — the step loop stamps a heartbeat
  (`FlightRecorder.beat`, one `time.monotonic` store) every iteration;
  a daemon thread checks it against pending work.  No progress for
  `stall_s` seconds while `pending_fn()` reports queued prefill or
  in-flight decode ⇒ one stall event, `stalls` increments (surfaced as
  `dynamo_engine_stalls_total`), and an automatic dump.  Re-arms when
  the heartbeat resumes, so one wedge produces one dump, not a storm.

Surfaces: `/debug/flightrecorder?n=K` on every StatusServer and the
frontend HttpService (`debug_payload`), the
`dynamo_engine_last_step_age_seconds` / `dynamo_engine_stalls_total`
series feeding `dynamo top`'s AGE/STL column, and
`tools/trace_merge.py --flight dump.jsonl` which time-aligns recorder
events as instant markers on the owning process track of the merged
Perfetto view.

Stdlib-only by design: every subsystem (engine, scheduler, slo,
metrics, tracing, block managers) may import this module without cycles.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# Event payloads must stay scalar-cheap at hot record sites (DL006);
# anything structured belongs in the dump header, computed once.
DEFAULT_RING = 4096
# Per-reason dump throttle: a trigger that keeps firing (slow requests
# under sustained overload, SLO flapping at the PAGE threshold) re-dumps
# at most this often; the ring still holds the latest events when the
# next dump lands.
DEFAULT_DUMP_INTERVAL_S = 30.0


class FlightRecorder:
    """Bounded in-memory ring of structured events + JSONL dumping.

    Writer cost budget (the whole point): `record` is one enabled check,
    one atomic counter next, one tuple build, one list store.  No locks
    on the write path — `itertools.count` is atomic under the GIL and a
    torn read in `events()` can at worst show a slot mid-overwrite,
    which the sequence numbers make detectable and the dump path
    tolerates.  `beat()` is a single float store, cheap enough to run
    unconditionally every engine step even with recording disabled (the
    watchdog needs it regardless)."""

    def __init__(self, service: str = "dynamo", *, enabled: bool = False,
                 ring_size: int = DEFAULT_RING,
                 dump_dir: Optional[str] = None,
                 dump_interval_s: float = DEFAULT_DUMP_INTERVAL_S) -> None:
        self.service = service
        self.enabled = enabled
        self.dump_dir = dump_dir
        self.dump_interval_s = dump_interval_s
        self._buf: List[Optional[tuple]] = [None] * max(2, int(ring_size))
        self._seq = itertools.count()
        self.events_written = 0
        # Engine heartbeat (monotonic) — stamped by the step loop; None
        # until the first step (a never-stepped engine is "starting",
        # not "stalled").
        self.last_beat: Optional[float] = None
        # Last first-seen-shape compile start (monotonic), stamped by
        # the engine's recompile hook.  A compile that began after the
        # last heartbeat means the current step is probably inside a
        # long XLA compile, not wedged — the watchdog widens its
        # threshold to compile_grace_s for that episode instead of
        # false-paging every cold start.
        self.last_compile: Optional[float] = None
        # Stall accounting (incremented by the watchdog; exported as
        # dynamo_engine_stalls_total).
        self.stalls = 0
        self._dump_lock = threading.Lock()
        self._last_dump: Dict[str, float] = {}   # reason -> monotonic ts
        self.dumps_written = 0
        self.last_dump_path: Optional[str] = None
        self._signal_installed = False
        self._atexit_installed = False
        self._crash_file = None

    # -- configuration -----------------------------------------------------

    def configure(self, *, service: Optional[str] = None,
                  enabled: Optional[bool] = None,
                  ring_size: Optional[int] = None,
                  dump_dir: Optional[str] = None,
                  dump_interval_s: Optional[float] = None
                  ) -> "FlightRecorder":
        """In-place reconfiguration (the module singleton is shared by
        reference; identity must survive — same contract as
        tracing.Tracer.configure)."""
        if service is not None:
            self.service = service
        if enabled is not None:
            self.enabled = enabled
        if ring_size is not None and ring_size != len(self._buf):
            # Resize drops history: acceptable at configure time (process
            # startup / test setup), never done on the record path.
            self._buf = [None] * max(2, int(ring_size))
            self._seq = itertools.count()
        if dump_dir is not None:
            self.dump_dir = dump_dir
        if dump_interval_s is not None:
            self.dump_interval_s = dump_interval_s
        return self

    def reset(self) -> None:
        """Drop all state (test isolation)."""
        self._buf = [None] * len(self._buf)
        self._seq = itertools.count()
        self.events_written = 0
        self.last_beat = None
        self.last_compile = None
        self.stalls = 0
        self._last_dump.clear()
        self.dumps_written = 0
        self.last_dump_path = None

    # -- hot-path writers --------------------------------------------------

    def beat(self) -> None:
        """Engine-thread heartbeat: one float store per step.  Runs even
        with recording disabled — the stall watchdog reads it."""
        self.last_beat = time.monotonic()

    def note_compile(self) -> None:
        """Stamp a compile start (one float store; called from the
        engine's first-seen-shape hook regardless of `enabled` — the
        watchdog's compile grace needs it even with recording off)."""
        self.last_compile = time.monotonic()

    def record(self, kind: str, **fields) -> None:
        """Append one event.  Callers on `@hot_path` code must pass only
        pre-computed scalars (names/constants/plain attributes — DL006);
        this body itself does no formatting and takes no lock."""
        if not self.enabled:
            return
        i = next(self._seq)
        self._buf[i % len(self._buf)] = (i, time.time(), kind, fields)
        self.events_written += 1

    def record_always(self, kind: str, **fields) -> None:
        """Force an event past the enabled gate — for the watchdog's
        stall marker and crash-adjacent triggers, which must leave
        evidence even on a process that never opted into recording."""
        i = next(self._seq)
        self._buf[i % len(self._buf)] = (i, time.time(), kind, fields)
        self.events_written += 1

    # -- reads -------------------------------------------------------------

    def last_step_age_s(self) -> Optional[float]:
        """Seconds since the step loop last stamped a heartbeat; None
        before the first step.  The `dynamo_engine_last_step_age_seconds`
        gauge and `dynamo top`'s AGE column read this."""
        if self.last_beat is None:
            return None
        return max(0.0, time.monotonic() - self.last_beat)

    def events(self, n: Optional[int] = None) -> List[dict]:
        """Oldest→newest snapshot of the ring as dicts (`n` newest when
        given).  Slots being overwritten concurrently are skipped via the
        sequence-number sanity check."""
        buf = list(self._buf)      # one GIL-atomic copy of the slot list
        rows = [e for e in buf if e is not None]
        rows.sort(key=lambda e: e[0])
        if n is not None:
            # n <= 0 means "no events, just the envelope" — a plain
            # negative slice would degenerate to the WHOLE ring.
            rows = rows[-n:] if n > 0 else []
        return [dict({"seq": seq, "ts": ts, "kind": kind}, **fields)
                for seq, ts, kind, fields in rows]

    def debug_payload(self, n: int = 256) -> dict:
        """The `/debug/flightrecorder` response body — one shape for
        every process (frontend HttpService, worker/router/planner
        StatusServer)."""
        return {
            "service": self.service,
            "enabled": self.enabled,
            "pid": os.getpid(),
            "ring_size": len(self._buf),
            "events_written": self.events_written,
            "stalls": self.stalls,
            "last_step_age_s": self.last_step_age_s(),
            "dumps_written": self.dumps_written,
            "last_dump_path": self.last_dump_path,
            "events": self.events(n),
        }

    # -- dumping -----------------------------------------------------------

    def default_dump_path(self) -> str:
        import tempfile

        d = self.dump_dir or tempfile.gettempdir()
        return os.path.join(
            d, f"flight_{self.service.replace('/', '_')}_{os.getpid()}"
               ".jsonl")

    def dump(self, reason: str, path: Optional[str] = None,
             min_interval_s: Optional[float] = None) -> Optional[str]:
        """Serialize the ring to JSONL; returns the path written, or
        None when the per-reason throttle suppressed it.  First line is
        a header (reason, pid, service, stall count, wall/mono clocks
        for offline time alignment); one line per event follows.  Dumps
        APPEND — a stall dump followed by the atexit dump of the same
        death lands in one file, in order."""
        interval = (self.dump_interval_s if min_interval_s is None
                    else min_interval_s)
        now = time.monotonic()
        with self._dump_lock:
            last = self._last_dump.get(reason)
            if last is not None and interval > 0 \
                    and now - last < interval:
                return None
            self._last_dump[reason] = now
            target = path or self.default_dump_path()
            try:
                events = self.events()
                header = {
                    "flight_dump": True,
                    "reason": reason,
                    "service": self.service,
                    "pid": os.getpid(),
                    "ts": time.time(),
                    "mono": now,
                    "stalls": self.stalls,
                    "events": len(events),
                    "events_written": self.events_written,
                    "last_step_age_s": self.last_step_age_s(),
                }
                with open(target, "a") as f:
                    f.write(json.dumps(header) + "\n")
                    for ev in events:
                        f.write(json.dumps(ev, default=str) + "\n")
            except OSError as e:
                logger.warning("flight-recorder dump to %s failed: %s",
                               target, e)
                return None
            self.dumps_written += 1
            self.last_dump_path = target
        logger.warning("flight recorder dumped %d event(s) to %s "
                       "(reason=%s)", len(events), target, reason)
        return target

    def dump_async(self, reason: str,
                   min_interval_s: Optional[float] = None
                   ) -> Optional[threading.Thread]:
        """`dump` on a short-lived daemon thread — for triggers that
        fire on latency-sensitive threads: the asyncio event loop (SLO
        PAGE in SloMonitor.tick, slow-request force-sample) must not
        stall behind ring serialization + file I/O, and the SIGUSR2
        handler must not re-enter `_dump_lock` a suspended main-thread
        frame may already hold (a non-reentrant lock there would
        deadlock the process).  Returns the started thread, or None
        when the per-reason throttle will suppress the dump anyway
        (lock-free pre-check: under sustained overload the slow-request
        trigger fires per request, and spawning a thread just to hit
        the throttle would be pure churn; dump() re-checks under the
        lock, so a racy pre-read only ever skips work, never doubles
        it)."""
        interval = (self.dump_interval_s if min_interval_s is None
                    else min_interval_s)
        last = self._last_dump.get(reason)
        if last is not None and interval > 0 \
                and time.monotonic() - last < interval:
            return None
        t = threading.Thread(
            target=self.dump, args=(reason,),
            kwargs={"min_interval_s": min_interval_s},
            name="flight-dump", daemon=True)
        t.start()
        return t

    # -- crash / signal triggers ------------------------------------------

    def install_crash_dump(self, signal_dump: bool = True) -> None:
        """Arm the involuntary triggers: `faulthandler` (hard-crash C
        traceback appended to the dump file), an atexit ring dump, and —
        when `signal_dump` and we are on the main thread — SIGUSR2 as
        the operator's live-snapshot hook (`kill -USR2 <pid>`)."""
        import atexit
        import faulthandler

        if self._crash_file is None:
            try:
                # The crash traceback lands IN the flight dump file, so
                # one artifact carries both the ring and the fatal stack.
                self._crash_file = open(self.default_dump_path(), "a")
                faulthandler.enable(file=self._crash_file)
            except (OSError, ValueError):
                faulthandler.enable()
        if not self._atexit_installed:
            self._atexit_installed = True
            atexit.register(self._atexit_dump)
        if signal_dump and not self._signal_installed:
            import signal as _signal

            try:
                # dump_async, not dump: the handler interrupts an
                # arbitrary main-thread frame — possibly one already
                # inside dump() holding _dump_lock.
                _signal.signal(_signal.SIGUSR2,
                               lambda *_: self.dump_async(
                                   "sigusr2", min_interval_s=0.0))
                self._signal_installed = True
            except (ValueError, OSError, AttributeError):
                # Non-main thread or platform without SIGUSR2: the other
                # triggers still work.
                logger.debug("SIGUSR2 dump handler not installed")

    def _atexit_dump(self) -> None:
        # Only leave an artifact when there is evidence to leave: an
        # idle process exiting cleanly should not litter dump files.
        if self.events_written or self.stalls:
            self.dump("atexit", min_interval_s=0.0)
            return
        # Nothing to dump: the file faulthandler pre-opened (so a hard
        # crash has somewhere to write its C traceback) is still empty
        # — remove it rather than leave one stray zero-byte
        # flight_*.jsonl per process start.
        f = self._crash_file
        if f is None:
            return
        try:
            import faulthandler

            faulthandler.disable()
            f.flush()
            empty = os.path.getsize(f.name) == 0
            f.close()
            self._crash_file = None
            if empty:
                os.unlink(f.name)
        except (OSError, ValueError):
            pass  # best-effort tidy at exit


class StallWatchdog:
    """Detects a wedged engine thread: heartbeat stamped by the step
    loop, checked off-thread against pending work.

    `pending_fn` must be cheap and thread-safe-ish (it runs off the
    engine thread against live engine state); any exception it raises
    reads as "no pending work" — the watchdog must never take a worker
    down, only report on one.  One stall EPISODE produces one counter
    increment, one `stall` ring event and one dump; the episode re-arms
    when the heartbeat advances again.

    Compile grace: a first-seen-shape XLA compile legitimately holds
    one step() open for tens of seconds (cold-start warmup, a new
    bucket under churn).  The engine stamps `note_compile` right before
    such a dispatch, so a compile that began at/after the last
    heartbeat widens this episode's threshold to `compile_grace_s` —
    a genuine wedge without a preceding compile still pages at
    `stall_s`, and a wedge DURING a compile pages at the grace."""

    def __init__(self, recorder: FlightRecorder,
                 pending_fn: Callable[[], bool],
                 stall_s: float = 10.0,
                 interval_s: Optional[float] = None,
                 compile_grace_s: float = 120.0,
                 on_stall: Optional[Callable[[], None]] = None) -> None:
        self.recorder = recorder
        self.pending_fn = pending_fn
        self.stall_s = stall_s
        self.interval_s = (interval_s if interval_s is not None
                           else max(0.05, stall_s / 4.0))
        self.compile_grace_s = max(compile_grace_s, stall_s)
        self.on_stall = on_stall
        self.stalled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check_once(self, now: Optional[float] = None) -> bool:
        """One watchdog evaluation (importable for deterministic tests);
        returns True when a NEW stall episode was just declared."""
        rec = self.recorder
        beat = rec.last_beat
        if beat is None:
            return False          # engine never stepped: starting, not stuck
        now = time.monotonic() if now is None else now
        age = now - beat
        threshold = self.stall_s
        compile_ts = rec.last_compile
        if compile_ts is not None and compile_ts >= beat:
            # The step that owns the stale heartbeat dispatched a
            # first-seen shape: probably compiling, not wedged.
            threshold = self.compile_grace_s
        if age < threshold:
            if self.stalled:
                logger.warning(
                    "engine thread recovered after stall (heartbeat "
                    "age now %.2fs)", age)
            self.stalled = False
            return False
        try:
            pending = bool(self.pending_fn())
        except Exception:
            pending = False       # racing teardown: do not page on it
        if not pending:
            # Idle engines stop stepping by design — old heartbeat with
            # no pending work is rest, not a wedge.
            self.stalled = False
            return False
        if self.stalled:
            return False          # same episode: already counted + dumped
        self.stalled = True
        rec.stalls += 1
        rec.record_always("stall", age_s=round(age, 3),
                          threshold_s=threshold, stalls=rec.stalls)
        logger.error(
            "engine-thread stall: no step heartbeat for %.2fs with "
            "pending work (threshold %.2fs) — dumping flight recorder",
            age, threshold)
        if self.on_stall is not None:
            try:
                self.on_stall()
            except Exception:
                logger.exception("on_stall callback failed")
        rec.dump("stall", min_interval_s=0.0)
        return True

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.check_once()
            except Exception:  # the watchdog must outlive its own bugs
                logger.exception("stall watchdog check failed")
            self._stop.wait(self.interval_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="engine-stall-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# ---------------------------------------------------------------------------
# Process singleton (same pattern as tracing.get_tracer)

_recorder = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _recorder


def configure(**kwargs) -> FlightRecorder:
    return _recorder.configure(**kwargs)


def add_flight_args(parser) -> None:
    """The shared --flight* / --watchdog* CLI surface (frontend,
    worker)."""
    parser.add_argument("--flight-recorder", choices=("on", "off"),
                        default="on",
                        help="per-process flight recorder: bounded ring "
                             "of structured engine/scheduler/KV/SLO "
                             "events, dumped as JSONL on SLO PAGE, slow "
                             "requests, SIGUSR2, exit/crash, and "
                             "engine-thread stalls "
                             "(/debug/flightrecorder)")
    parser.add_argument("--flight-ring", type=int, default=DEFAULT_RING,
                        help="flight-recorder ring size (events kept)")
    parser.add_argument("--flight-dump-dir", default=None,
                        help="directory for flight-recorder JSONL dumps "
                             "(default: the system temp dir; file name "
                             "flight_<service>_<pid>.jsonl)")
    parser.add_argument("--watchdog-stall-s", type=float, default=10.0,
                        help="engine-thread stall watchdog: no step "
                             "heartbeat for this many seconds while "
                             "prefill/decode work is pending counts as "
                             "a stall (event + dynamo_engine_stalls_total "
                             "+ automatic dump); 0 disables")


def configure_from_args(args, service: str) -> FlightRecorder:
    """Apply the add_flight_args flags to the process recorder."""
    return configure(
        service=service,
        enabled=getattr(args, "flight_recorder", "on") != "off",
        ring_size=getattr(args, "flight_ring", DEFAULT_RING),
        dump_dir=getattr(args, "flight_dump_dir", None))
