"""Version shims for jax APIs used at their modern names.

The codebase targets current jax (`jax.shard_map`, `check_vma=`); CI
images sometimes pin an older release where the same machinery lives at
`jax.experimental.shard_map.shard_map` with the `check_rep=` spelling.
Import `shard_map` from here instead of `jax` so both work.
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:  # jax < 0.6: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma, **kw)

try:
    axis_size = jax.lax.axis_size
except AttributeError:  # jax < 0.5: psum of a literal is the axis size
    def axis_size(axis_name):
        return jax.lax.psum(1, axis_name)
