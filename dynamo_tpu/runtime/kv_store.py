"""Pluggable KV persistence backends for the control plane.

Role of the reference's `lib/runtime/src/storage/key_value_store/
{etcd,mem,nats}.rs` — one KeyValueStore interface, several stores.  Here
the control plane IS the store (ControlPlaneState); the pluggable part
is its persistence:

- **MemoryBackend** — nothing survives the process (the default; the
  mem.rs analog).
- **FileBackend** — UNLEASED keys (operator config: disagg thresholds,
  model metadata) survive control-plane restarts via an atomic JSON
  snapshot.  LEASED keys are deliberately NOT persisted: they are
  liveness records whose leases died with the process — reloading them
  would resurrect ghost workers (etcd's lease semantics).

Backends only see unleased traffic; ControlPlaneState filters.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Dict, Optional, Protocol

logger = logging.getLogger(__name__)


class KeyValueBackend(Protocol):
    def load(self) -> Dict[str, dict]:
        """Initial (unleased) contents."""
        ...

    def put(self, key: str, value: dict) -> None: ...

    def delete(self, key: str) -> None: ...


class MemoryBackend:
    def load(self) -> Dict[str, dict]:
        return {}

    def put(self, key: str, value: dict) -> None:
        pass

    def delete(self, key: str) -> None:
        pass


class FileBackend:
    """Atomic-snapshot JSON file; every mutation rewrites the snapshot
    (control-plane config churn is low-rate — correctness over IO)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._data: Dict[str, dict] = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    self._data = json.load(f)
            except (OSError, json.JSONDecodeError):
                logger.exception("kv snapshot %s unreadable; starting "
                                 "empty", path)
                self._data = {}

    def load(self) -> Dict[str, dict]:
        return dict(self._data)

    def _flush(self) -> None:
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".kv_snapshot_")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._data, f)
            os.replace(tmp, self.path)
        except OSError:
            logger.exception("kv snapshot flush failed")
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def put(self, key: str, value: dict) -> None:
        self._data[key] = value
        self._flush()

    def delete(self, key: str) -> None:
        if self._data.pop(key, None) is not None:
            self._flush()


def make_backend(spec: Optional[str]) -> KeyValueBackend:
    """'file:/path.json' → FileBackend; None/'' / 'memory' → memory."""
    if not spec or spec == "memory":
        return MemoryBackend()
    if spec.startswith("file:"):
        return FileBackend(spec[len("file:"):])
    raise ValueError(f"unknown kv store spec {spec!r} "
                     "(have: memory, file:PATH)")
