"""Per-request latency ledger + fleet goodput attribution (ISSUE 18).

Disaggregated serving assembles ONE request's latency out of many
processes — frontend, router, prefill slice, KV transfer plane, decode
slice — and the process-centric planes (traces, gauges, flight recorder)
answer "is this process healthy", never "which hop ate THIS request's
TTFT".  The ledger is the request-centric complement: a compact,
wire-carried list of `(phase, t_mono_delta, dur, scalar_attrs)` stamps
accumulated as the request crosses the fleet, merged back at the
frontend when the stream finishes.

Topology
--------
- The frontend `begin()`s a live `RequestLedger` on the preprocessed
  request (a plain attribute — never serialized as-is) and marks the
  request's `annotations[LEDGER_ANNOTATION]` so remote hops opt in.
- Every component on the path stamps phases onto `ledger_of(request)`:
  receive/tokenize (frontend), route (+donor hint), queue/prefill/
  first_token (engine timings, recorded at first-token time), kv_transfer
  rounds (plane device|host, blocks, tokens), remote-prefill waits,
  migration stalls, drain handoffs, and a per-token decode interval
  summary.
- A worker hop builds its OWN ledger (`begin_hop`, its own monotonic
  anchor) and returns it on the final — or migrate — `TokenDelta` via
  the delta codec's optional `ledger` key; the frontend-side wire
  clients `absorb_delta()` it into the live ledger.  Old peers ignore
  the key; garbage is tolerated (see below).
- The frontend folds completed ledgers into `LedgerSink`:
  `dynamo_request_phase_seconds{phase=}` histograms, the goodput counter
  pair (SLO-good vs total tokens), a slowest-N ring behind
  `/debug/requests?n=K`, and a recent-window dominant-phase attribution
  consumed by `SloMonitor` PAGEs and `dynamo top`'s WHY column.

Overhead contract (flight-recorder discipline)
----------------------------------------------
Stamp sites are scalar-cheap behind the module `enabled()` guard: one
monotonic read + one tuple append, no containers built in hot paths
(lint rule DL006 covers `.stamp(...)` receivers), zero added host syncs
— steady-decode `EngineStepCounters` deltas are byte-identical ledger-on
vs ledger-off (pinned by tests and `bench_gate --smoke`).

Tolerance contract
------------------
A bad peer must never break the request path for the sake of telemetry
(same rule as `TraceContext.from_wire`): any truncated/garbage ledger
payload at any hop is dropped with a rate-limited warn
(`runtime.logutil.warn_rate_limited`) and the request proceeds
ledger-less.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from dynamo_tpu.runtime.logutil import warn_rate_limited

logger = logging.getLogger(__name__)

LEDGER_VERSION = 1
# Annotation key marking "this request wants a ledger" on the request
# leg of the wire (annotations are Dict[str, str]; any truthy value
# opts the hop in — tolerant by construction).
LEDGER_ANNOTATION = "x-dynamo-ledger"
# Per-hop stamp bound: a runaway stamper degrades to a drop counter,
# never an unbounded wire payload.
MAX_STAMPS = 64
# Attr values must be scalars on the wire; anything else is dropped at
# decode (never the request).
_SCALAR_TYPES = (str, int, float, bool)

_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def add_ledger_args(p) -> None:
    p.add_argument(
        "--request-ledger", choices=("on", "off"), default="on",
        help="per-request latency ledger (ISSUE 18): wire-carried phase "
             "stamps folded into dynamo_request_phase_seconds{phase=}, "
             "the goodput counter pair, /debug/requests?n=K and SLO burn "
             "attribution.  Scalar-cheap stamps; steady-decode engine "
             "counters are byte-identical on vs off")


def configure_from_args(args) -> None:
    set_enabled(getattr(args, "request_ledger", "on") != "off")


# ---------------------------------------------------------------------------
# The ledger itself


class RequestLedger:
    """Phase stamps for one request on one hop (or the frontend's merged
    view).  Stamps are `(phase, t_rel, dur, attrs)` where `t_rel` is the
    monotonic offset of the stamp (phase END) from this ledger's anchor.
    NOT thread-safe by design: each hop's ledger is owned by that hop's
    event loop; the engine thread never touches one (engine timings are
    popped onto the loop by LocalEngineClient)."""

    __slots__ = ("request_id", "anchor", "stamps", "dropped")

    def __init__(self, request_id: str,
                 anchor: Optional[float] = None) -> None:
        self.request_id = request_id
        self.anchor = time.monotonic() if anchor is None else anchor
        self.stamps: List[Tuple[str, float, float, Optional[dict]]] = []
        self.dropped = 0

    def stamp(self, phase: str, dur: float = 0.0,
              t: Optional[float] = None, **attrs) -> None:
        """Record one phase: `dur` seconds ending at `t` (now when
        omitted).  Scalar-cheap: one monotonic read + one append; attrs
        must be scalars (DL006 enforces this inside @hot_path bodies)."""
        if len(self.stamps) >= MAX_STAMPS:
            self.dropped += 1
            return
        now = time.monotonic() if t is None else t
        self.stamps.append((phase, now - self.anchor, float(dur),
                            attrs or None))

    # -- aggregation -------------------------------------------------------

    def phase_totals(self) -> Dict[str, float]:
        """Summed duration per phase (merged hops included)."""
        totals: Dict[str, float] = {}
        for phase, _t, dur, _a in self.stamps:
            totals[phase] = totals.get(phase, 0.0) + dur
        return totals

    def total(self, exclude: Tuple[str, ...] = ()) -> float:
        return sum(d for p, _t, d, _a in self.stamps if p not in exclude)

    # -- wire --------------------------------------------------------------

    def to_wire(self) -> dict:
        """Compact wire form: rides the delta codec's optional `ledger`
        key (worker → frontend) — old peers never read it."""
        return {
            "v": LEDGER_VERSION,
            "rid": self.request_id,
            "anchor": self.anchor,
            "stamps": [[p, round(t, 6), round(d, 6), a]
                       for p, t, d, a in self.stamps],
            "dropped": self.dropped,
        }

    def merge_wire(self, obj, where: str = "wire") -> bool:
        """Fold a peer hop's wire ledger into this one, re-basing stamp
        times onto this ledger's anchor (same-host monotonic clocks
        line up exactly; cross-host offsets only skew rendering, never
        the durations the fold consumes).  Malformed payloads are
        dropped with a rate-limited warn; returns False then."""
        decoded = decode_wire(obj, where=where)
        if decoded is None:
            return False
        peer_anchor, stamps, dropped = decoded
        shift = peer_anchor - self.anchor
        for phase, t, dur, attrs in stamps:
            if len(self.stamps) >= MAX_STAMPS:
                self.dropped += 1
                continue
            self.stamps.append((phase, t + shift, dur, attrs))
        self.dropped += dropped
        return True

    def to_payload(self) -> dict:
        """JSON payload form (`/debug/requests`, trace_merge --ledger):
        absolute monotonic times so spans time-align with the tracer's."""
        return {
            "request_id": self.request_id,
            "anchor": self.anchor,
            "stamps": [
                {"phase": p, "t": self.anchor + t, "dur": d,
                 "attrs": a or {}}
                for p, t, d, a in self.stamps],
            "phase_totals": {k: round(v, 6)
                             for k, v in self.phase_totals().items()},
            "dropped": self.dropped,
        }


def decode_wire(obj, where: str = "wire"):
    """Tolerant wire decode → (anchor, stamps, dropped) or None.

    EVERY structural failure — wrong container, non-scalar attrs,
    unparsable numbers, absurd sizes — drops the ledger with ONE
    rate-limited warn per site and never raises: telemetry must never
    fail a request (ISSUE 18 bugfix satellite)."""
    try:
        if not isinstance(obj, dict):
            raise TypeError(f"ledger payload is {type(obj).__name__}")
        raw = obj.get("stamps")
        if not isinstance(raw, (list, tuple)):
            raise TypeError("stamps is not a list")
        anchor = float(obj.get("anchor", 0.0))
        stamps = []
        for row in raw[:MAX_STAMPS]:
            phase, t, dur = row[0], float(row[1]), float(row[2])
            if not isinstance(phase, str):
                raise TypeError("phase is not a string")
            attrs = row[3] if len(row) > 3 else None
            if attrs is not None:
                if not isinstance(attrs, dict):
                    raise TypeError("attrs is not a dict")
                attrs = {str(k): v for k, v in attrs.items()
                         if isinstance(v, _SCALAR_TYPES)} or None
            stamps.append((phase, t, dur, attrs))
        dropped = int(obj.get("dropped", 0)) \
            + max(0, len(raw) - MAX_STAMPS)
        return anchor, stamps, dropped
    except Exception as e:
        warn_rate_limited(
            logger, f"ledger_decode:{where}", 10.0,
            "dropping malformed request ledger at %s (%s) — request "
            "unaffected", where, e)
        return None


# ---------------------------------------------------------------------------
# Request attachment helpers (the seam every stamp site goes through)


def ledger_of(request) -> Optional[RequestLedger]:
    """The live ledger riding `request` (None when disabled/absent) —
    the getattr every stamp site uses so requests from old peers or
    ledger-off frontends cost one attribute read."""
    return getattr(request, "ledger", None)


def begin(request) -> Optional[RequestLedger]:
    """Frontend entry: attach a live ledger to the preprocessed request
    and mark the wire annotation so remote hops stamp too."""
    if not _enabled:
        return None
    led = RequestLedger(request.request_id)
    request.ledger = led
    try:
        request.annotations[LEDGER_ANNOTATION] = f"v{LEDGER_VERSION}"
    except Exception:
        # dynamo-lint: disable=DL003 annotations missing/frozen on odd
        # request types: local stamps still work, remote hops just
        # don't opt in
        pass
    return led


def begin_hop(request) -> Optional[RequestLedger]:
    """Worker-side entry (engine_wire_handler): a fresh per-hop ledger,
    created only when this hop has the plane enabled AND the request
    opted in via the annotation marker."""
    if not _enabled:
        return None
    ann = getattr(request, "annotations", None) or {}
    if not ann.get(LEDGER_ANNOTATION):
        return None
    led = RequestLedger(request.request_id)
    request.ledger = led
    return led


def absorb_delta(request, delta, where: str = "wire") -> None:
    """Merge a wire delta's returned hop ledger (final or migrate delta)
    into the request's live ledger; consumed ledgers are cleared so
    upper layers never double-merge.  No-ops cheaply when either side
    is absent."""
    wire = getattr(delta, "ledger", None)
    if wire is None:
        return
    led = ledger_of(request)
    if led is not None:
        led.merge_wire(wire, where=where)
    delta.ledger = None


# ---------------------------------------------------------------------------
# Coverage (bench_gate --smoke honesty checks)

COVERAGE_FLOOR = 0.9     # assembled phases must explain >= 90% of TTFT
COVERAGE_CEIL = 1.10     # claiming more time than wall-clock = fabricated

# Phases on the TTFT critical path (everything stamped before the first
# token); the decode interval summary and terminal bookkeeping phases
# land after TTFT and must not count toward its coverage.
TTFT_PHASES = ("receive", "route", "queue", "prefill", "first_token",
               "kv_transfer", "prefill_remote", "migration")


def ttft_coverage(led: "RequestLedger", ttft_s: float) -> float:
    """Fraction of a measured TTFT the ledger's TTFT-path phase
    durations account for (0.0 on a degenerate TTFT)."""
    if ttft_s <= 0:
        return 0.0
    covered = sum(d for p, _t, d, _a in led.stamps if p in TTFT_PHASES)
    return covered / ttft_s


def coverage_ok(led: "RequestLedger", ttft_s: float,
                floor: float = COVERAGE_FLOOR,
                ceil: float = COVERAGE_CEIL) -> bool:
    """True iff the ledger honestly explains the measured TTFT: no dark
    time (>= floor) and no fabricated over-claim (<= ceil — a ledger
    claiming more time than the wall-clock envelope FAILS)."""
    ratio = ttft_coverage(led, ttft_s)
    return floor <= ratio <= ceil


# ---------------------------------------------------------------------------
# Frontend fold


class LedgerSink:
    """Where completed ledgers land on the frontend.

    Folds each finished request into (a) per-phase latency histograms
    `dynamo_request_phase_seconds{phase=}` — fleet-wide merge semantics:
    `sum(_sum)/sum(_count)` per phase across instances (the aggregator
    carries pre-summed `dynamo_aggregate_request_phase_seconds_*`); (b)
    the goodput counter pair `dynamo_goodput_good_tokens_total` /
    `dynamo_goodput_tokens_total` (good = the request met its TTFT/TPOT
    SLO thresholds and finished ok); (c) a slowest-N ring served by
    `/debug/requests?n=K`; (d) a recent-window per-phase duration
    aggregate answering `dominant_phase()` for SLO burn attribution and
    `dynamo top`'s WHY column.  Thread-safe (HTTP handlers + SLO tick
    thread)."""

    def __init__(self, registry, slo_ttft: Optional[float] = None,
                 slo_tpot: Optional[float] = None, keep_slowest: int = 64,
                 window_s: float = 300.0) -> None:
        self.phase_seconds = registry.histogram(
            "request_phase_seconds",
            "Per-request ledger phase durations (label phase=; "
            "fleet merge: sum sums and counts across instances)")
        self.goodput_good = registry.counter(
            "goodput_good_tokens_total",
            "Output tokens of requests that met their TTFT/TPOT SLO "
            "thresholds and finished ok (sum across instances)")
        self.goodput_total = registry.counter(
            "goodput_tokens_total",
            "Output tokens of all finished requests "
            "(sum across instances)")
        self.slo_ttft = slo_ttft
        self.slo_tpot = slo_tpot
        self.keep_slowest = keep_slowest
        self.window_s = window_s
        self.folded = 0
        self.decode_failures = 0
        self._slowest: List[dict] = []          # desc by total_s
        self._window = deque()                  # (wall_ts, {phase: dur})
        self._lock = threading.Lock()

    def fold(self, led: Optional[RequestLedger], ttft: Optional[float],
             tpot: Optional[float], output_tokens: int,
             ok: bool = True) -> None:
        if led is None:
            return
        totals = led.phase_totals()
        for phase, dur in totals.items():
            self.phase_seconds.observe(dur, labels={"phase": phase})
        good = ok
        if good and self.slo_ttft is not None and ttft is not None \
                and ttft > self.slo_ttft:
            good = False
        if good and self.slo_tpot is not None and tpot is not None \
                and tpot > self.slo_tpot:
            good = False
        if output_tokens > 0:
            self.goodput_total.inc(output_tokens)
            if good:
                self.goodput_good.inc(output_tokens)
        entry = led.to_payload()
        entry["ttft_s"] = ttft
        entry["tpot_s"] = tpot
        entry["output_tokens"] = output_tokens
        entry["ok"] = bool(ok)
        entry["slo_good"] = bool(good)
        entry["total_s"] = round(sum(totals.values()), 6)
        now = time.monotonic()
        with self._lock:
            self.folded += 1
            self._slowest.append(entry)
            self._slowest.sort(key=lambda e: e["total_s"], reverse=True)
            del self._slowest[self.keep_slowest:]
            self._window.append((now, totals))
            self._prune(now)

    def _prune(self, now: float) -> None:
        # Callers hold self._lock (fold / dominant_phase).
        cutoff = now - self.window_s
        while self._window and self._window[0][0] < cutoff:
            # dynamo-lint: disable=DL004 called only under self._lock
            self._window.popleft()

    def dominant_phase(
            self, exclude: Tuple[str, ...] = ("decode",)
    ) -> Optional[str]:
        """The phase with the largest summed duration over the recent
        window — the burn-attribution answer.  The steady `decode`
        interval summary is excluded by default: long generations make
        it dominate by construction, while stalls on the decode path
        surface as their own phases (migration, kv_transfer)."""
        sums: Dict[str, float] = {}
        with self._lock:
            self._prune(time.monotonic())
            for _ts, totals in self._window:
                for phase, dur in totals.items():
                    if phase in exclude:
                        continue
                    sums[phase] = sums.get(phase, 0.0) + dur
        if not sums:
            return None
        return max(sums.items(), key=lambda kv: kv[1])[0]

    def goodput_ratio(self) -> Optional[float]:
        total = self.goodput_total.value()
        if total <= 0:
            return None
        return self.goodput_good.value() / total

    def debug_payload(self, n: int = 10) -> dict:
        """`/debug/requests?n=K`: the K slowest completed ledgers with
        full stamp detail, plus the window attribution summary."""
        with self._lock:
            slowest = [dict(e) for e in self._slowest[:max(0, n)]]
        return {
            "slowest": slowest,
            "folded": self.folded,
            "dominant_phase": self.dominant_phase(),
            "goodput": self.goodput_ratio(),
            "window_s": self.window_s,
            "ledger_enabled": enabled(),
        }
