"""Rate-limited logging for serving-path failure swallows.

dynamo-lint rule DL003 forbids silent `except Exception: pass` in
serving-path modules: donor/transfer/control-plane failures used to
vanish entirely.  Most of those sites sit on per-request or per-poll
paths where UNBOUNDED logging would flood under a persistent failure
(a dead donor hit by every request, a backend whose memory_stats always
raises) — this helper logs the first occurrence per key immediately and
then at most once per `interval` seconds.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

_last_emit: Dict[str, float] = {}
_lock = threading.Lock()


def warn_rate_limited(logger, key: str, interval: float,
                      msg: str, *args) -> bool:
    """`logger.warning(msg, *args)` at most once per `interval` seconds
    per `key`; returns True when the record was actually emitted.
    Thread-safe (telemetry threads and event loops share keys)."""
    now = time.monotonic()
    with _lock:
        last = _last_emit.get(key)
        if last is not None and now - last < interval:
            return False
        _last_emit[key] = now
    logger.warning(msg, *args)
    return True


def reset() -> None:
    """Forget emission history (tests)."""
    with _lock:
        _last_emit.clear()
