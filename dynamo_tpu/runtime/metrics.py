"""Prometheus-format metrics registry.

Role of the reference's `lib/runtime/src/metrics.rs` (hierarchical names
drt→namespace→component→endpoint) and `lib/llm/src/http/service/metrics.rs`
(the TTFT/ITL histograms the SLA planner scrapes —
`*_time_to_first_token_seconds`, `*_inter_token_latency_seconds`).  Those
exact series names are load-bearing: the planner's Prometheus queries key
on them (reference `planner/utils/prometheus.py`), so our planner does too.

Self-contained text-format exposition (no prometheus_client dependency);
thread-safe; histograms use fixed buckets chosen for LLM latencies.
"""

from __future__ import annotations

import logging
import math
import threading
from bisect import bisect_right
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from dynamo_tpu.runtime import flight_recorder
from dynamo_tpu.runtime.contracts import never_engine_thread
from dynamo_tpu.runtime.logutil import warn_rate_limited

_logger = logging.getLogger(__name__)

# Buckets tuned for token-level latencies (seconds): sub-ms resolution at
# the bottom (a routing decision or in-process TPOT at speedup is ~100 µs)
# through 60 s at the top (a cold-compile TTFT).
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    return tuple(sorted((labels or {}).items()))


def _escape_label_value(v: str) -> str:
    """Prometheus text-exposition escaping: inside a label value, `\\`,
    `"` and newline must be escaped or the whole exposition is invalid
    (a scraper rejects every series, not just the bad one)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(key: LabelKey, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    def __init__(self, name: str, help_: str) -> None:
        self.name, self.help = name, help_
        self._values: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None):
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:  # concurrent inc() must not tear the snapshot
            values = sorted(self._values.items())
        for k, v in values:
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str) -> None:
        self.name, self.help = name, help_
        self._values: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, labels: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, amount: float, labels: Optional[Dict[str, str]] = None):
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:  # concurrent set()/add() must not tear the snapshot
            values = sorted(self._values.items())
        for k, v in values:
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Histogram:
    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS) -> None:
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sum: Dict[LabelKey, float] = {}
        self._total: Dict[LabelKey, int] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None):
        k = _label_key(labels)
        idx = bisect_right(self.buckets, value)
        with self._lock:
            if k not in self._counts:
                self._counts[k] = [0] * (len(self.buckets) + 1)
                self._sum[k] = 0.0
                self._total[k] = 0
            self._counts[k][idx] += 1
            self._sum[k] += value
            self._total[k] += 1

    def count(self, labels: Optional[Dict[str, str]] = None) -> int:
        return self._total.get(_label_key(labels), 0)

    def sum(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._sum.get(_label_key(labels), 0.0)

    def mean(self, labels: Optional[Dict[str, str]] = None) -> float:
        """NaN on an empty label set (never raises): 0.0 read as "zero
        latency" by the SLA planner's arithmetic; NaN propagates as
        "no data" and comparisons against it are False."""
        k = _label_key(labels)
        with self._lock:  # count and sum must come from one snapshot
            n = self._total.get(k, 0)
            s = self._sum.get(k, 0.0)
        return s / n if n else float("nan")

    # -- label-aggregated views (SLO burn-rate sources) --------------------

    def total_count(self) -> int:
        """Observations across ALL label sets."""
        with self._lock:
            return sum(self._total.values())

    def total_sum(self) -> float:
        with self._lock:
            return sum(self._sum.values())

    def total_mean(self) -> float:
        """Mean across all label sets; NaN when empty (same "no data"
        propagation contract as `mean`)."""
        with self._lock:
            n = sum(self._total.values())
            s = sum(self._sum.values())
        return s / n if n else float("nan")

    def count_le(self, value: float) -> int:
        """Observations known to be <= `value`, across all label sets —
        the cumulative count at the largest bucket bound <= `value`
        (matching the `le` cumulative the exposition prints).  Bucket
        granularity: observations in the bucket CONTAINING a mid-bucket
        `value` are excluded (conservative for SLO accounting — they
        count as bad); pick thresholds at bucket bounds for exactness."""
        idx = bisect_right(self.buckets, value)
        with self._lock:
            return sum(sum(c[:idx]) for c in self._counts.values())

    def quantile(self, q: float, labels: Optional[Dict[str, str]] = None) -> float:
        """Approximate quantile from bucket counts (upper bound of the
        bucket containing the q-th observation).  Edge behavior: NaN on
        an empty/unknown label set; q clamps to [0, 1]; q=0 returns the
        first non-empty bucket's bound (a single observation answers
        every quantile with its own bucket); +Inf past the last bucket.
        Never raises."""
        k = _label_key(labels)
        with self._lock:
            counts = list(self._counts.get(k, ()))
            total = self._total.get(k, 0)
        if not counts or total <= 0:
            return float("nan")
        q = min(max(q, 0.0), 1.0)
        # At least the first observation: q=0 must land in a non-empty
        # bucket, not the (possibly empty) first one.
        target = max(1, math.ceil(q * total))
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            # Snapshot under the lock: a concurrent observe() between
            # reading _counts and _sum would emit torn cumulative counts
            # (bucket cum > _count, or _sum missing the observation).
            snap = {k: (list(self._counts[k]), self._sum[k])
                    for k in self._counts}
        for k in sorted(snap):
            counts, total_sum = snap[k]
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                le = _fmt_labels(k, 'le="%s"' % b)
                out.append(f"{self.name}_bucket{le} {cum}")
            cum += counts[-1]
            le_inf = _fmt_labels(k, 'le="+Inf"')
            out.append(f"{self.name}_bucket{le_inf} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(k)} {total_sum}")
            out.append(f"{self.name}_count{_fmt_labels(k)} {cum}")
        return out


class EngineStepCounters:
    """Serving-loop overhead counters the engine increments in-line.

    The r5→r6 diagnosis needed exactly these and had none: the serving
    number halved and nothing could say whether the loss was host syncs,
    recompiles, or scheduler churn.  Counted on the engine thread only
    (no locking), cheap enough for the per-step hot path:

    - `host_syncs` — blocking device→host reads the step loop performed
      (window token fetches, single-step sample fetches, blocking
      first-token settles).  Steady-state window decode must pay at most
      ONE per window; anything above that is a pipeline bug.
    - `xla_cache_misses` — first-seen (program, shape-signature) pairs
      via `note_dispatch`.  jax's jit cache keys on exactly these, so a
      nonzero delta after warmup means the engine is churning shapes
      (bucket flapping) and recompiling.  It is a proxy: it counts what
      WOULD miss jax's in-process cache, including hits served by the
      persistent compilation cache on disk.
    - dispatch tallies (`window_dispatches`, `single_step_dispatches`,
      `prefill_dispatches`, `spec_dispatches`, `h2d_uploads`) —
      denominators for the two above (syncs *per window*, uploads *per
      dispatch*).
    - `kv_read_bytes_modeled` / `decode_tokens_emitted` (via
      `note_kv_read`) — the MODELED KV bytes decode attention swept from
      HBM and the tokens those sweeps emitted.  Their ratio,
      `effective_bytes_per_token`, is the decode-bandwidth-wall series
      (ISSUE 6): int8 KV roughly halves the numerator, speculative
      decoding grows the denominator per sweep — both show up here
      without a TPU in the loop.  Under a mesh the bytes are PER CHIP
      (the engine divides by its `kv_traffic_shards` = dp*tp on non-pp
      meshes, pp on pipelines — ISSUE 9): a tp2 engine sweeps half the
      cache bytes per chip, a dp2 engine half the ROWS per chip, and the
      per-chip mbu derived from this series must say so.  (Residency
      gauges divide by the distinct `kv_shard_count` — plain dp
      replicates storage while halving traffic.)
    """

    def __init__(self) -> None:
        self.host_syncs = 0
        self.xla_cache_misses = 0
        self.window_dispatches = 0
        self.window_syncs = 0
        self.single_step_dispatches = 0
        self.prefill_dispatches = 0
        self.packed_prefill_dispatches = 0
        self.spec_dispatches = 0
        self.h2d_uploads = 0
        self.kv_read_bytes_modeled = 0
        self.decode_tokens_emitted = 0
        # Modeled PER-CHIP ICI bytes the ring-SP prefill exchange moved
        # (ISSUE 12 satellite): each chip sends its resident K/V chunk on
        # (sp−1) of sp hops per layer, so the series halves when the
        # quantized cache halves the per-token ring payload
        # (KvCacheConfig.ring_payload_bytes_per_token) — the sp analog of
        # the kv_read_bytes_modeled honesty series.
        self.ring_exchange_bytes_modeled = 0
        # Prefills whose ring exchange ran the Pallas flash kernel
        # (ops/pallas/ring_attention.py) rather than the XLA ppermute
        # ring.  The byte series above is PATH-INDEPENDENT (both rings
        # move the same rows+scales over the same sp-1 hops — charged
        # before the dispatch split); this counter is the attribution:
        # kernel-path tests and bench_gate --smoke assert it went up.
        self.ring_kernel_prefills = 0
        # Mixed-prefill cost calibration (ISSUE 10 satellite): EWMAs of
        # engine-thread wall seconds per window-decode token (plain
        # windows) and per concurrently-dispatched prefill token (the
        # excess on windows with a chunk riding behind them).  Host
        # floats fed by note_window_interval at the window sync — the
        # engine's ONE existing blocking point — so calibration costs
        # zero extra syncs.  Deliberately NOT in to_dict(): delta-pinned
        # counter tests compare exact ints; wall-clock EWMAs would make
        # "byte-identical" assertions flaky.  None = no sample yet — a
        # measured cost of exactly 0.0 (zero-excess mixed window) is a
        # real sample and must seed/damp the EWMA, not restart it.
        self.decode_token_cost_ewma: Optional[float] = None
        self.prefill_token_cost_ewma: Optional[float] = None
        self.prefill_cost_samples = 0
        self._cost_ewma_alpha = 0.25
        self._seen_shapes: set = set()
        # Optional first-seen-shape hook (the engine points this at its
        # flight recorder so every recompile leaves a postmortem event);
        # called ONLY on cache misses, so the steady window never pays
        # for it.
        self.on_recompile: Optional[Callable] = None

    def note_dispatch(self, tag: str, *sig) -> bool:
        """Record a jitted-program dispatch; a first-seen (tag, sig)
        counts as an XLA cache miss (a new shape compiles).  Returns
        True exactly on first-seen — the dispatch site uses it to feed
        the device-profiler's compile-time cost-analysis harvest
        without any steady-state branch cost."""
        key = (tag,) + sig
        if key not in self._seen_shapes:
            self._seen_shapes.add(key)
            self.xla_cache_misses += 1
            cb = self.on_recompile
            if cb is not None:
                cb(key)
            return True
        return False

    def note_kv_read(self, nbytes: int, tokens: int) -> None:
        """Tally modeled decode KV traffic (bytes swept) and the tokens
        it emitted; host-int arithmetic only."""
        self.kv_read_bytes_modeled += int(nbytes)
        self.decode_tokens_emitted += int(tokens)

    def note_ring_exchange(self, nbytes: int) -> None:
        """Tally modeled per-chip ring-SP exchange bytes (sp prefill
        dispatches only); host-int arithmetic only."""
        self.ring_exchange_bytes_modeled += int(nbytes)

    def note_window_interval(self, wall_s: float, window_tokens: int,
                             prefill_tokens: int) -> None:
        """Wall time between consecutive steady window syncs.  Plain
        windows (no chunk behind them) calibrate the per-decode-token
        cost; windows with `prefill_tokens` dispatched behind them
        attribute the excess over the calibrated decode cost to the
        chunk.  In a pipelined steady state the sync interval tracks the
        device's window execution time, so the ratio of the two EWMAs is
        the measured `cost_ratio` the MixedPrefillController needs —
        without adding a single device sync."""
        if wall_s <= 0 or window_tokens <= 0:
            return
        a = self._cost_ewma_alpha
        if prefill_tokens <= 0:
            per = wall_s / window_tokens
            self.decode_token_cost_ewma = (
                per if self.decode_token_cost_ewma is None
                else (1.0 - a) * self.decode_token_cost_ewma + a * per)
        elif self.decode_token_cost_ewma is not None:
            excess = wall_s - window_tokens * self.decode_token_cost_ewma
            per = max(excess, 0.0) / prefill_tokens
            self.prefill_token_cost_ewma = (
                per if self.prefill_token_cost_ewma is None
                else (1.0 - a) * self.prefill_token_cost_ewma + a * per)
            self.prefill_cost_samples += 1

    @property
    def measured_prefill_cost_ratio(self):
        """Measured chunked-prefill-token / window-decode-token cost, or
        None before both EWMAs have samples.  Clamped at the consumer
        (MixedPrefillController.observe_cost_ratio)."""
        if (self.decode_token_cost_ewma is None
                or self.prefill_token_cost_ewma is None):
            return None
        return self.prefill_token_cost_ewma / self.decode_token_cost_ewma

    @property
    def effective_bytes_per_token(self) -> float:
        """Modeled KV HBM bytes per emitted decode token (0 before any
        decode work)."""
        if not self.decode_tokens_emitted:
            return 0.0
        return self.kv_read_bytes_modeled / self.decode_tokens_emitted

    def to_dict(self) -> Dict[str, int]:
        return {
            "host_syncs": self.host_syncs,
            "xla_cache_misses": self.xla_cache_misses,
            "window_dispatches": self.window_dispatches,
            "window_syncs": self.window_syncs,
            "single_step_dispatches": self.single_step_dispatches,
            "prefill_dispatches": self.prefill_dispatches,
            "packed_prefill_dispatches": self.packed_prefill_dispatches,
            "spec_dispatches": self.spec_dispatches,
            "h2d_uploads": self.h2d_uploads,
            "kv_read_bytes_modeled": self.kv_read_bytes_modeled,
            "decode_tokens_emitted": self.decode_tokens_emitted,
            "ring_exchange_bytes_modeled": self.ring_exchange_bytes_modeled,
            "ring_kernel_prefills": self.ring_kernel_prefills,
        }

    def snapshot(self) -> "EngineStepCounters":
        """Point-in-time copy (delta assertions across a step range)."""
        c = EngineStepCounters()
        c.__dict__.update({k: v for k, v in self.__dict__.items()
                           if k != "_seen_shapes"})
        c._seen_shapes = set()
        return c

    def delta(self, since: "EngineStepCounters") -> Dict[str, int]:
        now, then = self.to_dict(), since.to_dict()
        return {k: now[k] - then[k] for k in now}


class MetricsRegistry:
    """Named registry with hierarchical prefixes (reference
    `MetricsRegistry`, `lib/runtime/src/metrics.rs`)."""

    def __init__(self, prefix: str = "dynamo") -> None:
        self.prefix = prefix
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help_: str, **kw):
        full = f"{self.prefix}_{name}" if self.prefix else name
        with self._lock:
            m = self._metrics.get(full)
            if m is None:
                m = cls(full, help_, **kw)
                self._metrics[full] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {full} already registered as {type(m)}")
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help_, buckets=buckets)

    def expose(self) -> str:
        lines: List[str] = []
        with self._lock:
            for m in self._metrics.values():
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class RequestMetrics:
    """Per-request lifecycle histograms (`dynamo_request_*`): the series
    the distributed-tracing work surfaces on every process that touches a
    request — frontend `/metrics` observes TTFT / TPOT / queue wait,
    disagg decode workers observe KV-transfer time.  Distinct from
    FrontendMetrics (whose exact series names the SLA planner's queries
    key on): these are the triage-oriented family `/debug/traces`
    complements."""

    def __init__(self, registry: MetricsRegistry) -> None:
        self.ttft = registry.histogram(
            "request_ttft_seconds", "Request time to first token")
        self.tpot = registry.histogram(
            "request_tpot_seconds", "Per-output-token interval "
            "(time per output token after the first)")
        self.queue_wait = registry.histogram(
            "request_queue_wait_seconds",
            "Arrival to generation-stream start")
        self.kv_transfer = registry.histogram(
            "request_kv_transfer_seconds",
            "Disaggregated KV-block onboard time (remote prefill pull)")
        self.kv_transfer_overlap = registry.histogram(
            "kv_transfer_overlap",
            "Fraction of the disagg KV prefix streamed before "
            "prefill-done (eager-streaming overlap ratio, 0-1)",
            buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0))
        # status="ok"|"error" — the SLO monitor's error-rate objective
        # source (runtime/slo.py), observed where the stream finishes
        # (frontend token stream; worker engine_wire_handler).
        self.outcomes = registry.counter(
            "request_outcomes_total",
            "Finished requests by terminal status (ok|error)")

    def observe_outcome(self, ok: bool) -> None:
        self.outcomes.inc(labels={"status": "ok" if ok else "error"})


class FrontendMetrics:
    """The HTTP-service metric family the SLA planner consumes (reference
    `http/service/metrics.rs:61-65,139-142`)."""

    def __init__(self, registry: MetricsRegistry) -> None:
        self.requests_total = registry.counter(
            "frontend_requests_total", "Requests received")
        self.requests_in_flight = registry.gauge(
            "frontend_inflight_requests", "Requests currently being served")
        self.queued_requests = registry.gauge(
            "frontend_queued_requests", "Requests queued before engine entry")
        self.ttft = registry.histogram(
            "frontend_time_to_first_token_seconds", "Time to first token")
        self.itl = registry.histogram(
            "frontend_inter_token_latency_seconds", "Inter-token latency")
        self.request_duration = registry.histogram(
            "frontend_request_duration_seconds", "Full request duration")
        self.input_tokens = registry.histogram(
            "frontend_input_sequence_tokens", "Prompt tokens per request",
            buckets=(16, 64, 256, 1024, 4096, 16384, 65536))
        self.output_tokens = registry.histogram(
            "frontend_output_sequence_tokens", "Output tokens per request",
            buckets=(1, 4, 16, 64, 256, 1024, 4096))


class KvCacheMetrics:
    """Memory-plane telemetry: the capacity-side series KVCache-centric
    schedulers and SLO-driven autoscalers treat as first-class inputs.

    Series (labels `tier` = device|host|disk, `pool` = pool name):

    - `dynamo_kv_pool_{capacity,active,reusable,free}_blocks` — gauges
      sampled from `BlockPool` occupancy views;
    - `dynamo_kv_evictions_total` — LRU evictions per pool;
    - `dynamo_kv_prefix_cache_{hits,misses}_tokens` — prompt tokens
      served from / missed by the prefix cache at admission;
    - `dynamo_hbm_{used,limit}_bytes` (labels `device`, `kind`) —
      per-accelerator HBM occupancy, fed by `HbmPoller`.

    Pull-based: `observe_*` SAMPLES host-side integers the pools and
    scheduler already maintain — called at scrape/pump time off the
    engine thread, so the steady decode window pays zero added host
    syncs and zero dispatches for the telemetry existing (pinned by
    tests/test_kv_metrics.py and `bench_gate --smoke`)."""

    def __init__(self, registry: MetricsRegistry) -> None:
        self.pool_capacity = registry.gauge(
            "kv_pool_capacity_blocks", "KV pool slot capacity")
        self.pool_active = registry.gauge(
            "kv_pool_active_blocks", "KV slots pinned by live sequences")
        self.pool_reusable = registry.gauge(
            "kv_pool_reusable_blocks",
            "Allocatable slots (free + evictable inactive)")
        self.pool_free = registry.gauge(
            "kv_pool_free_blocks", "Slots on the free list")
        self.evictions = registry.counter(
            "kv_evictions_total", "Registered blocks LRU-evicted")
        self.prefix_hits = registry.counter(
            "kv_prefix_cache_hits_tokens",
            "Prompt tokens served from the prefix cache at admission")
        self.prefix_misses = registry.counter(
            "kv_prefix_cache_misses_tokens",
            "Prompt tokens that missed the prefix cache at admission")
        # Fleet-wide prefix reuse (block_manager/prefix_share.py):
        # peer-to-peer prefix pulls driven by router remote-prefix hints.
        self.prefix_remote_hits = registry.counter(
            "prefix_remote_hits_total",
            "Requests whose prefix was pulled from a peer worker")
        self.prefix_remote_pulled = registry.counter(
            "prefix_remote_pulled_blocks_total",
            "KV blocks injected from peer workers via prefix-share pulls")
        self.prefix_remote_fallbacks = registry.counter(
            "prefix_remote_fallbacks_total",
            "Remote-prefix pulls that failed or were refused "
            "(request fell back to local prefill)")
        # Which data plane bulk KV pulls rode (ISSUE 13): plane=device
        # batches crossed device-to-device (reason names the pull site:
        # eager|prefix|disagg); plane=host names WHY the device plane
        # was not used (no_plane, offer_cap, transport, not_resident,
        # pull_failed, quant_mismatch, ...) — a fleet silently degraded
        # to host staging is visible here and in `dynamo top`'s PLANE
        # column.
        self.transfer_plane_choices = registry.counter(
            "kv_transfer_plane_total",
            "Batched bulk-KV pull rounds by data plane (one increment "
            "per pull round on BOTH planes, so device/host reflects "
            "traffic; reason = pull site for device, fallback cause "
            "for host)")
        self.hbm_used = registry.gauge(
            "hbm_used_bytes", "Accelerator memory in use")
        self.hbm_limit = registry.gauge(
            "hbm_limit_bytes", "Accelerator memory capacity")
        # Decode-bandwidth-wall series (ISSUE 6): KV bytes per block as
        # actually stored (incl. int8 scales), modeled KV bytes swept per
        # emitted token, and the speculative-decoding accept telemetry.
        self.kv_bytes_per_block = registry.gauge(
            "kv_bytes_per_block",
            "True PER-CHIP bytes of one KV block across layers, "
            "including quantization scales in int8 mode and divided by "
            "the mesh's KV shard count on sharded pools")
        self.kv_effective_bytes_per_token = registry.gauge(
            "kv_effective_bytes_per_token",
            "Modeled decode-attention HBM bytes per emitted token, "
            "per chip under meshes")
        self.spec_drafted = registry.counter(
            "spec_decode_drafted_tokens_total",
            "Draft tokens proposed to the batched verify step")
        self.spec_accepted = registry.counter(
            "spec_decode_accepted_tokens_total",
            "Draft tokens the verify step accepted")
        self.spec_acceptance_rate = registry.gauge(
            "spec_decode_acceptance_rate",
            "Cumulative accepted/drafted ratio (0 when spec decode off)")
        # Cumulative-source high-water marks: counters can only inc, so
        # sampled monotonic ints (pool.evictions, scheduler token
        # counters) convert to increments by delta from the last sample.
        self._last: Dict[tuple, float] = {}

    def _inc_to(self, counter: Counter, labels: Dict[str, str],
                cum: float) -> None:
        key = (counter.name, _label_key(labels))
        prev = self._last.get(key, 0.0)
        if cum < prev:
            prev = 0.0  # source restarted (fresh pool/engine)
        if cum > prev:
            counter.inc(cum - prev, labels=labels)
        self._last[key] = cum

    @never_engine_thread
    def observe_prefix_share(self, fetcher) -> None:
        """Sample a PrefixFetcher's cumulative pull accounting into the
        dynamo_prefix_remote_* counters (same pull-style delta
        conversion as the pool counters)."""
        self._inc_to(self.prefix_remote_hits, {}, fetcher.remote_hits)
        self._inc_to(self.prefix_remote_pulled, {}, fetcher.pulled_blocks)
        self._inc_to(self.prefix_remote_fallbacks, {}, fetcher.fallbacks)

    @never_engine_thread
    def observe_transfer_plane(self, counts=None) -> None:
        """Sample the device-transfer plane-choice tallies
        (device_transfer.plane_counts — process-wide host ints) into the
        dynamo_kv_transfer_plane_total counter family.  `counts` may be
        passed explicitly (tests)."""
        if counts is None:
            from dynamo_tpu.llm.block_manager.device_transfer import (
                plane_counts)

            counts = plane_counts()
        for (plane, reason), n in counts.items():
            self._inc_to(self.transfer_plane_choices,
                         {"plane": plane, "reason": reason}, n)

    @never_engine_thread
    def observe_pool(self, pool, tier: str) -> None:
        """Sample one BlockPool's occupancy + eviction counters."""
        labels = {"tier": tier, "pool": pool.name}
        self.pool_capacity.set(pool.capacity, labels=labels)
        self.pool_active.set(pool.active_slots, labels=labels)
        self.pool_reusable.set(pool.reusable_slots, labels=labels)
        self.pool_free.set(pool.free_slots, labels=labels)
        self._inc_to(self.evictions, labels, pool.evictions)

    @never_engine_thread
    def observe_engine(self, core) -> None:
        """Sample an EngineCore's block source (all tiers) and the
        scheduler's admission prefix-match counters.  Reads host-side
        ints only — never device arrays — so it is safe to call from a
        scrape thread while the engine steps (and must never BE the
        engine thread: sampling on the step loop would charge the
        steady window for its own telemetry)."""
        alloc = core.allocator
        manager = getattr(alloc, "manager", None)
        if manager is not None:
            self.observe_pool(manager.device, "device")
            if manager.host is not None:
                self.observe_pool(manager.host, "host")
            if manager.disk is not None:
                self.observe_pool(manager.disk, "disk")
            device_pool = manager.device.name
        else:
            # Plain free-list allocator: no pool object, synthesize the
            # device-tier gauges from its counts (no reuse → active =
            # allocated, reusable = free).
            labels = {"tier": "device", "pool": "plain"}
            cap = alloc.num_blocks - 1
            free = alloc.free_blocks
            self.pool_capacity.set(cap, labels=labels)
            self.pool_active.set(cap - free, labels=labels)
            self.pool_reusable.set(free, labels=labels)
            self.pool_free.set(free, labels=labels)
            device_pool = "plain"
        sched = getattr(core, "scheduler", None)
        if sched is not None:
            labels = {"tier": "device", "pool": device_pool}
            self._inc_to(self.prefix_hits, labels,
                         getattr(sched, "prefix_hit_tokens", 0))
            self._inc_to(self.prefix_misses, labels,
                         getattr(sched, "prefix_miss_tokens", 0))
        cache_cfg = getattr(core, "cache_cfg", None)
        if cache_cfg is not None:
            # Per-CHIP bytes: a tp/dp-sharded pool splits every block
            # over kv_shard_count chips, and the HBM-residency math the
            # planner does against dynamo_hbm_* would double-count a
            # whole-block figure (ISSUE 9 satellite).
            shards = getattr(core, "kv_shard_count", 1)
            self.kv_bytes_per_block.set(
                cache_cfg.bytes_per_block / max(shards, 1),
                labels={"kv_quant": cache_cfg.kv_quant})
        counters = getattr(core, "counters", None)
        if counters is not None:
            self.kv_effective_bytes_per_token.set(
                counters.effective_bytes_per_token)
        stats = getattr(getattr(core, "metrics", None),
                        "spec_decode_stats", None)
        if stats is not None:
            self._inc_to(self.spec_drafted, {}, stats.num_drafts)
            self._inc_to(self.spec_accepted, {}, stats.num_accepted_tokens)
            self.spec_acceptance_rate.set(
                stats.num_accepted_tokens / stats.num_drafts
                if stats.num_drafts else 0.0)


class HbmPoller:
    """Slow-poll thread feeding `dynamo_hbm_{used,limit}_bytes` from
    `jax.local_devices()[i].memory_stats()`.

    Off the engine thread by construction (its own daemon thread), and
    `memory_stats()` is a PJRT host-side query — no device dispatch, no
    sync injected into the step loop.  Backends without memory stats
    (CPU) fall back to process RSS / system RAM under
    `device="host", kind="cpu"`, so the series family exists everywhere
    and `dynamo top` renders uniformly."""

    def __init__(self, metrics: KvCacheMetrics,
                 interval: float = 10.0) -> None:
        self.metrics = metrics
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @never_engine_thread
    def poll_once(self) -> int:
        """One sample of every local device; returns the number of
        devices that reported real memory stats (0 → fallback used)."""
        devices = []
        try:
            import jax

            devices = jax.local_devices()
        except Exception:  # pre-init failure / no backend: fallback below
            devices = []
        reported = 0
        used_total = limit_total = 0
        for i, dev in enumerate(devices):
            stats = None
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats or "bytes_in_use" not in stats:
                continue
            labels = {"device": str(i),
                      "kind": getattr(dev, "platform", "unknown")}
            self.metrics.hbm_used.set(stats["bytes_in_use"], labels=labels)
            used_total += int(stats["bytes_in_use"])
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            if limit:
                self.metrics.hbm_limit.set(limit, labels=labels)
                limit_total += int(limit)
            reported += 1
        if not reported:
            self._poll_host_fallback()
        else:
            # Flight-recorder HBM sample: one aggregate event per poll —
            # the "was HBM climbing before the death" postmortem series.
            flight_recorder.get_recorder().record(
                "hbm", devices=reported, used_bytes=used_total,
                limit_bytes=limit_total)
        return reported

    @staticmethod
    def _current_rss_bytes() -> Optional[int]:
        """CURRENT resident set, not getrusage's lifetime high-water
        mark (a gauge fed by ru_maxrss could never decrease — one model-
        load spike would read as a permanently full host; and ru_maxrss
        units are platform-dependent: KB on Linux, bytes on macOS)."""
        try:
            with open("/proc/self/statm") as f:
                pages = int(f.read().split()[1])
            import os

            return pages * os.sysconf("SC_PAGE_SIZE")
        except Exception:
            # dynamo-lint: disable=DL003 fallback chain continues below
            pass  # non-Linux: try getrusage next
        try:  # non-Linux fallback: the peak is better than nothing
            import resource
            import sys

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            return rss if sys.platform == "darwin" else rss * 1024
        except Exception:
            return None

    def _poll_host_fallback(self) -> None:
        labels = {"device": "host", "kind": "cpu"}
        rss = self._current_rss_bytes()
        if rss is None:
            return
        self.metrics.hbm_used.set(rss, labels=labels)
        try:
            import os

            total = (os.sysconf("SC_PHYS_PAGES")
                     * os.sysconf("SC_PAGE_SIZE"))
            self.metrics.hbm_limit.set(total, labels=labels)
        except (ValueError, OSError, AttributeError):
            pass

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="hbm-poll", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # telemetry must never kill the process
                warn_rate_limited(
                    _logger, "hbm_poll", 60.0,
                    "HBM poll failed (series go stale): %s", e)
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
