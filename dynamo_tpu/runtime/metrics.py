"""Prometheus-format metrics registry.

Role of the reference's `lib/runtime/src/metrics.rs` (hierarchical names
drt→namespace→component→endpoint) and `lib/llm/src/http/service/metrics.rs`
(the TTFT/ITL histograms the SLA planner scrapes —
`*_time_to_first_token_seconds`, `*_inter_token_latency_seconds`).  Those
exact series names are load-bearing: the planner's Prometheus queries key
on them (reference `planner/utils/prometheus.py`), so our planner does too.

Self-contained text-format exposition (no prometheus_client dependency);
thread-safe; histograms use fixed buckets chosen for LLM latencies.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_right
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Buckets tuned for token-level latencies (seconds): sub-ms resolution at
# the bottom (a routing decision or in-process TPOT at speedup is ~100 µs)
# through 60 s at the top (a cold-compile TTFT).
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    return tuple(sorted((labels or {}).items()))


def _fmt_labels(key: LabelKey, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    def __init__(self, name: str, help_: str) -> None:
        self.name, self.help = name, help_
        self._values: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None):
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str) -> None:
        self.name, self.help = name, help_
        self._values: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, labels: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, amount: float, labels: Optional[Dict[str, str]] = None):
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {v}")
        return out


class Histogram:
    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS) -> None:
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sum: Dict[LabelKey, float] = {}
        self._total: Dict[LabelKey, int] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None):
        k = _label_key(labels)
        idx = bisect_right(self.buckets, value)
        with self._lock:
            if k not in self._counts:
                self._counts[k] = [0] * (len(self.buckets) + 1)
                self._sum[k] = 0.0
                self._total[k] = 0
            self._counts[k][idx] += 1
            self._sum[k] += value
            self._total[k] += 1

    def count(self, labels: Optional[Dict[str, str]] = None) -> int:
        return self._total.get(_label_key(labels), 0)

    def sum(self, labels: Optional[Dict[str, str]] = None) -> float:
        return self._sum.get(_label_key(labels), 0.0)

    def mean(self, labels: Optional[Dict[str, str]] = None) -> float:
        """NaN on an empty label set (never raises): 0.0 read as "zero
        latency" by the SLA planner's arithmetic; NaN propagates as
        "no data" and comparisons against it are False."""
        n = self.count(labels)
        return self.sum(labels) / n if n else float("nan")

    def quantile(self, q: float, labels: Optional[Dict[str, str]] = None) -> float:
        """Approximate quantile from bucket counts (upper bound of the
        bucket containing the q-th observation).  Edge behavior: NaN on
        an empty/unknown label set; q clamps to [0, 1]; q=0 returns the
        first non-empty bucket's bound (a single observation answers
        every quantile with its own bucket); +Inf past the last bucket.
        Never raises."""
        k = _label_key(labels)
        with self._lock:
            counts = list(self._counts.get(k, ()))
            total = self._total.get(k, 0)
        if not counts or total <= 0:
            return float("nan")
        q = min(max(q, 0.0), 1.0)
        # At least the first observation: q=0 must land in a non-empty
        # bucket, not the (possibly empty) first one.
        target = max(1, math.ceil(q * total))
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        for k in sorted(self._counts):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[k][i]
                le = _fmt_labels(k, 'le="%s"' % b)
                out.append(f"{self.name}_bucket{le} {cum}")
            cum += self._counts[k][-1]
            le_inf = _fmt_labels(k, 'le="+Inf"')
            out.append(f"{self.name}_bucket{le_inf} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(k)} {self._sum[k]}")
            out.append(f"{self.name}_count{_fmt_labels(k)} {cum}")
        return out


class EngineStepCounters:
    """Serving-loop overhead counters the engine increments in-line.

    The r5→r6 diagnosis needed exactly these and had none: the serving
    number halved and nothing could say whether the loss was host syncs,
    recompiles, or scheduler churn.  Counted on the engine thread only
    (no locking), cheap enough for the per-step hot path:

    - `host_syncs` — blocking device→host reads the step loop performed
      (window token fetches, single-step sample fetches, blocking
      first-token settles).  Steady-state window decode must pay at most
      ONE per window; anything above that is a pipeline bug.
    - `xla_cache_misses` — first-seen (program, shape-signature) pairs
      via `note_dispatch`.  jax's jit cache keys on exactly these, so a
      nonzero delta after warmup means the engine is churning shapes
      (bucket flapping) and recompiling.  It is a proxy: it counts what
      WOULD miss jax's in-process cache, including hits served by the
      persistent compilation cache on disk.
    - dispatch tallies (`window_dispatches`, `single_step_dispatches`,
      `prefill_dispatches`, `h2d_uploads`) — denominators for the two
      above (syncs *per window*, uploads *per dispatch*).
    """

    def __init__(self) -> None:
        self.host_syncs = 0
        self.xla_cache_misses = 0
        self.window_dispatches = 0
        self.window_syncs = 0
        self.single_step_dispatches = 0
        self.prefill_dispatches = 0
        self.h2d_uploads = 0
        self._seen_shapes: set = set()

    def note_dispatch(self, tag: str, *sig) -> None:
        """Record a jitted-program dispatch; a first-seen (tag, sig)
        counts as an XLA cache miss (a new shape compiles)."""
        key = (tag,) + sig
        if key not in self._seen_shapes:
            self._seen_shapes.add(key)
            self.xla_cache_misses += 1

    def to_dict(self) -> Dict[str, int]:
        return {
            "host_syncs": self.host_syncs,
            "xla_cache_misses": self.xla_cache_misses,
            "window_dispatches": self.window_dispatches,
            "window_syncs": self.window_syncs,
            "single_step_dispatches": self.single_step_dispatches,
            "prefill_dispatches": self.prefill_dispatches,
            "h2d_uploads": self.h2d_uploads,
        }

    def snapshot(self) -> "EngineStepCounters":
        """Point-in-time copy (delta assertions across a step range)."""
        c = EngineStepCounters()
        c.__dict__.update({k: v for k, v in self.__dict__.items()
                           if k != "_seen_shapes"})
        c._seen_shapes = set()
        return c

    def delta(self, since: "EngineStepCounters") -> Dict[str, int]:
        now, then = self.to_dict(), since.to_dict()
        return {k: now[k] - then[k] for k in now}


class MetricsRegistry:
    """Named registry with hierarchical prefixes (reference
    `MetricsRegistry`, `lib/runtime/src/metrics.rs`)."""

    def __init__(self, prefix: str = "dynamo") -> None:
        self.prefix = prefix
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help_: str, **kw):
        full = f"{self.prefix}_{name}" if self.prefix else name
        with self._lock:
            m = self._metrics.get(full)
            if m is None:
                m = cls(full, help_, **kw)
                self._metrics[full] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {full} already registered as {type(m)}")
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help_, buckets=buckets)

    def expose(self) -> str:
        lines: List[str] = []
        with self._lock:
            for m in self._metrics.values():
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class RequestMetrics:
    """Per-request lifecycle histograms (`dynamo_request_*`): the series
    the distributed-tracing work surfaces on every process that touches a
    request — frontend `/metrics` observes TTFT / TPOT / queue wait,
    disagg decode workers observe KV-transfer time.  Distinct from
    FrontendMetrics (whose exact series names the SLA planner's queries
    key on): these are the triage-oriented family `/debug/traces`
    complements."""

    def __init__(self, registry: MetricsRegistry) -> None:
        self.ttft = registry.histogram(
            "request_ttft_seconds", "Request time to first token")
        self.tpot = registry.histogram(
            "request_tpot_seconds", "Per-output-token interval "
            "(time per output token after the first)")
        self.queue_wait = registry.histogram(
            "request_queue_wait_seconds",
            "Arrival to generation-stream start")
        self.kv_transfer = registry.histogram(
            "request_kv_transfer_seconds",
            "Disaggregated KV-block onboard time (remote prefill pull)")
        self.kv_transfer_overlap = registry.histogram(
            "kv_transfer_overlap",
            "Fraction of the disagg KV prefix streamed before "
            "prefill-done (eager-streaming overlap ratio, 0-1)",
            buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0))


class FrontendMetrics:
    """The HTTP-service metric family the SLA planner consumes (reference
    `http/service/metrics.rs:61-65,139-142`)."""

    def __init__(self, registry: MetricsRegistry) -> None:
        self.requests_total = registry.counter(
            "frontend_requests_total", "Requests received")
        self.requests_in_flight = registry.gauge(
            "frontend_inflight_requests", "Requests currently being served")
        self.queued_requests = registry.gauge(
            "frontend_queued_requests", "Requests queued before engine entry")
        self.ttft = registry.histogram(
            "frontend_time_to_first_token_seconds", "Time to first token")
        self.itl = registry.histogram(
            "frontend_inter_token_latency_seconds", "Inter-token latency")
        self.request_duration = registry.histogram(
            "frontend_request_duration_seconds", "Full request duration")
        self.input_tokens = registry.histogram(
            "frontend_input_sequence_tokens", "Prompt tokens per request",
            buckets=(16, 64, 256, 1024, 4096, 16384, 65536))
        self.output_tokens = registry.histogram(
            "frontend_output_sequence_tokens", "Output tokens per request",
            buckets=(1, 4, 16, 64, 256, 1024, 4096))
