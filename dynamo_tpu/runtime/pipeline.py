"""Declarative operator pipeline over the EngineClient contract.

Role of the reference's `lib/runtime/src/pipeline/nodes.rs` (351 LoC:
`Operator` / `ServiceFrontend` / `ServiceBackend` / `SegmentSource` with
forward/backward edges): the frontend assembles
Frontend→Preproc→Backend→Migration→Router as a LINKED graph rather than
hand-nested constructors (`entrypoint/input/common.rs:183,213`).

Here the streaming contract is `EngineClient.generate(PreprocessedRequest)
-> AsyncIterator[TokenDelta]` (llm/service.py — the AsyncEngine analog),
and an Operator is anything that wraps one EngineClient into another:

    pipeline = Pipeline([
        MigrationOp(limit=3),
        KvRouterOp(runtime, block_size=64),
    ])
    engine_client = await pipeline.attach(instance_client)

Operators compose right-to-left (the last op sits closest to the wire),
matching the reference's build_routed_pipeline ordering.  `FnOp` lifts a
plain `wrap(inner) -> EngineClient` callable, so a new operator is one
function, not bespoke plumbing through ModelWatcher (VERDICT r4 missing
#7).
"""

from __future__ import annotations

import inspect
from typing import Awaitable, Callable, List, Protocol, Union


class Operator(Protocol):
    """Wraps the downstream EngineClient; may return an awaitable when
    the wrapper needs async startup (e.g. the KV router's event
    subscriptions)."""

    def wrap(self, inner): ...


class FnOp:
    """Operator from a plain callable (sync or async)."""

    def __init__(self, fn: Callable) -> None:
        self._fn = fn

    def wrap(self, inner):
        return self._fn(inner)


class MigrationOp:
    """Retry/resume streams across worker death and planned drain
    (llm/migration.py; reference `migration.rs:27`).  `registry` counts
    `dynamo_migrations_total{reason}` on the frontend's /metrics."""

    def __init__(self, limit: int = 3, registry=None) -> None:
        self.limit = limit
        self.registry = registry

    def wrap(self, inner):
        from dynamo_tpu.llm.migration import MigrationClient

        return MigrationClient(inner, migration_limit=self.limit,
                               registry=self.registry)


class KvRouterOp:
    """KV-aware worker selection over the instance set (llm/kv_router/
    client.py; reference `kv_router.rs:304` KvPushRouter)."""

    def __init__(self, runtime, block_size: int = 64,
                 registry=None) -> None:
        self.runtime = runtime
        self.block_size = block_size
        self.registry = registry  # frontend MetricsRegistry (router series)

    async def wrap(self, inner):
        from dynamo_tpu.llm.kv_router.client import KvRoutedEngineClient

        routed = KvRoutedEngineClient(inner, self.runtime,
                                      block_size=self.block_size,
                                      registry=self.registry)
        await routed.start()
        return routed


class RemoteOp:
    """Instance-set Client → EngineClient (wire codec boundary;
    llm/discovery.RemoteEngineClient)."""

    def wrap(self, inner):
        from dynamo_tpu.llm.discovery import RemoteEngineClient

        return RemoteEngineClient(inner)


class Pipeline:
    """Ordered operator list; `attach(sink)` folds them around the sink
    right-to-left and returns the outermost EngineClient.

    `stages` records every built client (innermost first) so owners can
    reach a specific stage without knowing the wrapper nesting
    (`stage_of(SomeClientClass)`), and `stop()` tears down any stage
    that started background work (e.g. the KV router's event
    subscriptions)."""

    def __init__(self, operators: List[Union[Operator, Callable]]) -> None:
        self.operators = [op if hasattr(op, "wrap") else FnOp(op)
                          for op in operators]
        self.stages: List = []

    async def attach(self, sink):
        client = sink
        self.stages = [sink]
        for op in reversed(self.operators):
            client = op.wrap(client)
            if inspect.isawaitable(client):
                client = await client
            self.stages.append(client)
        return client

    def stage_of(self, cls):
        """The built stage of the given class, or None."""
        for st in self.stages:
            if isinstance(st, cls):
                return st
        return None

    async def stop(self) -> None:
        """Stop stages outermost-first (the reverse of data flow)."""
        for st in reversed(self.stages):
            stop = getattr(st, "stop", None)
            if stop is not None and st is not self.stages[0]:
                res = stop()
                if inspect.isawaitable(res):
                    await res

    def describe(self) -> str:
        return " -> ".join(type(op).__name__ for op in self.operators) \
            or "identity"
