"""Streaming RPC between processes: one request in → many responses out.

The reference implements this as a NATS publish to the instance's subject
plus a TCP "call-home" stream for responses (`egress/addressed_router.rs`,
`ingress/push_endpoint.rs:33`, `tcp/server.rs:74`).  Direct peer TCP does
both jobs here: the client connects to the worker's advertised address
(from control-plane discovery) and multiplexes request streams over that
connection — fewer hops, no broker on the data path.

Framing: 4-byte big-endian length + msgpack body.
  client → server: {t:"req", sid, ep, payload, trace?} | {t:"cancel", sid}
  server → client: {t:"delta"|"end"|"err", sid, payload|error}

The optional `trace` field carries a serialized TraceContext
(runtime/tracing.py): the client stamps its open span's context on the
request frame, the server extracts it, opens a server-side span parented
to the client span, and makes it the handler task's current span — so
worker-side spans stitch into the caller's trace (Dapper-style context
propagation over our own transport).  Absent or malformed trace fields
cost nothing and break nothing.

Cancellation propagates: client-side generator close sends `cancel`, the
server cancels the handler task (the reference's CancellationToken chain).
A vanished connection fails all its in-flight streams with ConnectionError
— the signal the migration operator retries on (`migration.rs:27-80`).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
from typing import AsyncIterator, Callable, Dict, Optional

import msgpack

from dynamo_tpu.runtime import tracing

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


_DRAIN_HIGH_WATER = 1 << 20  # 1 MiB of buffered frames before yielding


async def _send_frame(writer: asyncio.StreamWriter, obj: dict,
                      lock: asyncio.Lock) -> None:
    """One frame per message, but NOT one drain per message: write() is
    synchronous (the frame bytes go down in a single call, so no lock is
    needed for atomicity) and drain() only runs once the transport
    buffer passes the high-water mark.  A drain per token-delta awaited
    a lock + flow-control round per token and capped the worker's egress
    at ~2k msgs/s (frontend_bench); buffered writes let the event loop
    batch syscalls across every active stream."""
    body = msgpack.packb(obj, use_bin_type=True)
    writer.write(_LEN.pack(len(body)) + body)
    transport = writer.transport
    if (transport is not None
            and transport.get_write_buffer_size() > _DRAIN_HIGH_WATER):
        async with lock:
            await writer.drain()


async def _read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False)


# Handler: async generator taking a payload dict, yielding payload dicts.
Handler = Callable[[dict], AsyncIterator[dict]]


class RpcServer:
    """Hosts named endpoints; one instance per worker process."""

    def __init__(self) -> None:
        self._handlers: Dict[str, Handler] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._connections: set = set()
        self._conn_tasks: set = set()  # live per-connection handler tasks
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        self.active_streams = 0

    def register(self, endpoint: str, handler: Handler) -> None:
        self._handlers[endpoint] = handler

    def unregister(self, endpoint: str) -> None:
        self._handlers.pop(endpoint, None)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._server = await asyncio.start_server(self._handle, host, port)
        self.host = host
        self.port = self._server.sockets[0].getsockname()[1]
        return self.address

    async def stop(self) -> None:
        """Stop accepting AND sever live connections — a stopped server
        must look dead to clients (their in-flight streams fail with
        ConnectionError, triggering migration retries)."""
        if self._server:
            self._server.close()
            # Sever live connections BEFORE wait_closed(): on Python 3.12+
            # wait_closed blocks until every connection handler returns,
            # and handlers sit in blocking reads until their transport dies.
            for w in list(self._connections):
                w.close()
            await self._server.wait_closed()
        # Await per-connection handler tasks so none is destroyed pending
        # at loop close (asyncio teardown warnings in test fixtures).
        for t in list(self._conn_tasks):
            t.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        self._conn_tasks.clear()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        tasks: Dict[int, asyncio.Task] = {}
        lock = asyncio.Lock()
        self._connections.add(writer)
        me = asyncio.current_task()
        if me is not None:
            self._conn_tasks.add(me)

        async def run_stream(sid: int, ep: str, payload: dict,
                             trace=None) -> None:
            self.active_streams += 1
            # Server-side span parented to the client's span (the `trace`
            # frame field); made current for the handler task so any span
            # the handler opens nests under it.
            tracer = tracing.get_tracer()
            span: object = tracing.NULL_SPAN
            token = None
            if tracer.enabled and trace is not None:
                ctx = tracing.TraceContext.from_wire(trace)
                if ctx is not None:
                    span = tracer.start_span(f"rpc.server:{ep}", ctx,
                                             attrs={"endpoint": ep})
                    token = tracing.use_span(span)
            try:
                handler = self._handlers.get(ep)
                if handler is None:
                    await _send_frame(writer,
                                      {"t": "err", "sid": sid,
                                       "error": f"no such endpoint {ep!r}"},
                                      lock)
                    return
                async for delta in handler(payload):
                    await _send_frame(writer,
                                      {"t": "delta", "sid": sid,
                                       "payload": delta}, lock)
                await _send_frame(writer, {"t": "end", "sid": sid}, lock)
            except asyncio.CancelledError:
                raise
            except ConnectionResetError:
                pass
            except Exception as e:
                logger.exception("handler error on %s", ep)
                span.set_attr(error=type(e).__name__)
                try:
                    await _send_frame(writer, {"t": "err", "sid": sid,
                                               "error": str(e)}, lock)
                except ConnectionResetError:
                    pass
            finally:
                span.end()
                if token is not None:
                    tracing.restore(token)
                self.active_streams -= 1
                tasks.pop(sid, None)

        try:
            while True:
                msg = await _read_frame(reader)
                if msg is None:
                    break
                t = msg.get("t")
                if t == "req":
                    sid = msg["sid"]
                    tasks[sid] = asyncio.create_task(
                        run_stream(sid, msg["ep"], msg.get("payload", {}),
                                   msg.get("trace")))
                elif t == "cancel":
                    task = tasks.pop(msg["sid"], None)
                    if task:
                        task.cancel()
        finally:
            for task in tasks.values():
                task.cancel()
            if tasks:
                await asyncio.gather(*tasks.values(),
                                     return_exceptions=True)
            self._connections.discard(writer)
            if me is not None:
                self._conn_tasks.discard(me)
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                # dynamo-lint: disable=DL003 teardown: peer already gone
                pass  # nothing to salvage — the connection is history


class RpcClient:
    """Multiplexed client to one worker address.  Reconnects lazily; a dead
    connection fails in-flight streams (callers retry via migration)."""

    def __init__(self, address: str) -> None:
        self.address = address
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._rx: Optional[asyncio.Task] = None
        self._sid = itertools.count(1)
        self._streams: Dict[int, asyncio.Queue] = {}
        self._lock = asyncio.Lock()
        self._conn_lock = asyncio.Lock()

    async def _ensure_connected(self) -> None:
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            self._reader, self._writer = await asyncio.open_connection(
                self._host, self._port)
            self._rx = asyncio.create_task(self._rx_loop())

    async def close(self) -> None:
        if self._rx:
            self._rx.cancel()
            try:
                await self._rx
            except asyncio.CancelledError:
                pass
        if self._writer:
            self._writer.close()
            self._writer = None

    async def _rx_loop(self) -> None:
        assert self._reader is not None
        while True:
            msg = await _read_frame(self._reader)
            if msg is None:
                # Connection died: poison all in-flight streams.
                for q in self._streams.values():
                    q.put_nowait({"t": "err", "error": "connection lost",
                                  "_conn": True})
                self._streams.clear()
                if self._writer:
                    self._writer.close()
                    self._writer = None
                return
            q = self._streams.get(msg.get("sid"))
            if q is not None:
                q.put_nowait(msg)

    async def call(self, endpoint: str, payload: dict) -> AsyncIterator[dict]:
        """Issue a streaming request; yields response payloads."""
        await self._ensure_connected()
        sid = next(self._sid)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[sid] = q
        # Client-side span; its context rides the request frame so the
        # server span parents under it (see module docstring).
        span = tracing.get_tracer().start_span(
            f"rpc.client:{endpoint}",
            attrs={"endpoint": endpoint, "address": self.address})
        frame = {"t": "req", "sid": sid, "ep": endpoint, "payload": payload}
        if span.ctx is not None:
            frame["trace"] = span.ctx.to_wire()
        done = False
        try:
            # Inside the try: a send failure (peer died mid-write) must
            # still end the span and drop the stream entry in finally.
            await _send_frame(self._writer, frame, self._lock)
            while True:
                msg = await q.get()
                t = msg["t"]
                if t == "delta":
                    yield msg["payload"]
                elif t == "end":
                    done = True
                    return
                elif t == "err":
                    done = True
                    if msg.get("_conn"):
                        raise ConnectionError(msg["error"])
                    raise RpcError(msg["error"])
        finally:
            span.end(clean=done)
            self._streams.pop(sid, None)
            # Best-effort cancel only if the stream didn't finish cleanly
            # (client walked away mid-stream).
            if (not done and self._writer is not None
                    and not self._writer.is_closing()):
                try:
                    await _send_frame(self._writer,
                                      {"t": "cancel", "sid": sid}, self._lock)
                except (ConnectionError, ConnectionResetError):
                    pass


class RpcError(RuntimeError):
    """Remote handler raised; message carries the remote error string."""
