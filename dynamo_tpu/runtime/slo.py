"""Multi-window burn-rate SLO monitor.

Answers "are we meeting our TTFT/TPOT/error-rate SLOs right now?" —
fleet-wide input for the planner (AIBrix-style SLO-driven scaling) and
the `dynamo top` / `/debug/slo` operator surface.

Mechanics (Google SRE multiwindow multi-burn-rate alerting, specialised
to our self-contained Prometheus registry):

- An *objective* states a good-fraction target over an event stream:
  "99% of requests have TTFT <= 0.5 s" (latency objective over a
  `Histogram`), or "99% of requests finish ok" (error-rate objective
  over the `dynamo_request_outcomes_total` counter).
- Each tick samples the cumulative (total, bad) counts and appends them
  to a timestamped series; the *burn rate* over a window is the window's
  bad fraction divided by the error budget (1 - objective).  Burn 1.0 =
  exactly consuming budget; burn 14.4 over the fast window = an
  incident.
- Two windows (fast 5 m / slow 1 h, configurable): PAGE requires BOTH
  windows over the page threshold (the fast window confirms the problem
  is still happening, the slow one that it is significant); WARN
  likewise at the warn threshold.  No traffic burns no budget.

Everything is sampled host-side from counters the serving path already
maintains — zero cost on the engine thread.  `tick()` takes an explicit
`now` for deterministic tests.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Tuple

from dynamo_tpu.runtime import flight_recorder
from dynamo_tpu.runtime.contracts import never_engine_thread
from dynamo_tpu.runtime.logutil import warn_rate_limited
from dynamo_tpu.runtime.metrics import (
    Counter, Histogram, MetricsRegistry, RequestMetrics)

_logger = logging.getLogger(__name__)

OK, WARN, PAGE = "OK", "WARN", "PAGE"
_STATE_NUM = {OK: 0, WARN: 1, PAGE: 2}


def _num(x) -> Optional[float]:
    """JSON-safe float: NaN/inf (e.g. Histogram.mean on no data)
    propagate as None — `json.dumps(float('nan'))` emits invalid JSON
    and every /debug/slo consumer would choke on it."""
    if x is None:
        return None
    x = float(x)
    return None if (math.isnan(x) or math.isinf(x)) else x


@dataclass(frozen=True)
class SloObjective:
    """One service-level objective: `objective` fraction of events must
    be good.  For latency objectives `threshold_s` defines good
    (observation <= threshold); error-rate objectives take good/bad
    straight from their source."""

    name: str                       # "ttft_p99", "error_rate", ...
    objective: float = 0.99         # target good fraction
    threshold_s: Optional[float] = None

    @property
    def budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


def latency_source(hist: Histogram, threshold_s: float) -> Callable:
    """Cumulative (total, bad) over a latency histogram: bad =
    observations above `threshold_s` (bucket-granular, see
    Histogram.count_le — mid-bucket thresholds count the containing
    bucket as bad, the conservative direction)."""

    def read() -> Tuple[float, float]:
        total = hist.total_count()
        return float(total), float(total - hist.count_le(threshold_s))

    return read


def error_source(outcomes: Counter) -> Callable:
    """Cumulative (total, bad) over the request-outcome counter
    (RequestMetrics.outcomes: status="ok"|"error")."""

    def read() -> Tuple[float, float]:
        ok = outcomes.value({"status": "ok"})
        bad = outcomes.value({"status": "error"})
        return ok + bad, bad

    return read


class SloMonitor:
    """Evaluates objectives over fast/slow windows; exports
    `dynamo_slo_burn_rate{objective,window}`,
    `dynamo_slo_compliant{objective}` and `dynamo_slo_state`, and
    serves the `/debug/slo` payload."""

    def __init__(
        self,
        objectives: List[Tuple[SloObjective, Callable]],
        fast_window: float = 300.0,
        slow_window: float = 3600.0,
        warn_burn: float = 3.0,
        page_burn: float = 14.4,
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
        attribution_fn: Optional[Callable[[], Optional[str]]] = None,
    ) -> None:
        """`attribution_fn`: () -> dominant ledger phase over the recent
        window (LedgerSink.dominant_phase) — PAGE transitions then NAME
        the hop burning the budget instead of just reporting that budget
        burns."""
        self.objectives = list(objectives)
        self.attribution_fn = attribution_fn
        self.fast_window = fast_window
        self.slow_window = slow_window
        self.warn_burn = warn_burn
        self.page_burn = page_burn
        self._clock = clock
        # Per-objective ring of (ts, cum_total, cum_bad).
        self._series: Dict[str, Deque[Tuple[float, float, float]]] = {
            obj.name: deque() for obj, _ in self.objectives}
        self._g_burn = self._g_compliant = self._g_state = None
        if registry is not None:
            self._g_burn = registry.gauge(
                "slo_burn_rate",
                "Error-budget burn rate (bad fraction / budget) per "
                "objective and window")
            self._g_compliant = registry.gauge(
                "slo_compliant",
                "1 when the objective's slow-window bad fraction is "
                "within budget")
            self._g_state = registry.gauge(
                "slo_state", "Overall SLO state: 0 OK, 1 WARN, 2 PAGE")
        self.state = OK
        # Worst fast-window burn across objectives at the last tick —
        # a cheap attribute read for hot-path consumers (the block
        # pool's SLO eviction bias runs on the engine thread and must
        # not recompute windows per eviction).
        self.last_max_burn = 0.0
        self._task: Optional[asyncio.Task] = None

    # -- evaluation -------------------------------------------------------

    def _prune(self, dq: Deque, now: float) -> None:
        """Drop samples older than the slow window, KEEPING the newest
        such sample — it is the slow window's left-edge baseline (a
        series pruned flush to the window edge would shrink the window
        it claims to measure)."""
        cutoff = now - self.slow_window
        while len(dq) >= 2 and dq[1][0] <= cutoff:
            dq.popleft()

    def _window(self, dq: Deque, now: float,
                window: float) -> Tuple[float, Optional[float]]:
        """(events, bad_fraction) over [now - window, now].  Baseline is
        the newest sample at or before the window's left edge; a series
        younger than the window measures from its oldest sample (partial
        window).  bad_fraction None when the window saw no events or a
        source reset (counter went backwards)."""
        if len(dq) < 2:
            return 0.0, None
        edge = now - window
        base = dq[0]
        for sample in dq:
            if sample[0] <= edge:
                base = sample
            else:
                break
        newest = dq[-1]
        d_total = newest[1] - base[1]
        d_bad = newest[2] - base[2]
        if d_total <= 0 or d_bad < 0:
            return 0.0, None
        return d_total, d_bad / d_total

    @never_engine_thread
    def tick(self, now: Optional[float] = None) -> dict:
        """Sample every objective, update burn rates + state, return the
        /debug/slo payload.  Deterministic given explicit `now`.

        Never the engine thread: a tick walks every objective's sample
        ring — the step loop reads only the `last_max_burn` attribute
        this leaves behind (the eviction bias' cheap signal)."""
        now = self._clock() if now is None else now
        rows = []
        worst = OK
        worst_burn = 0.0
        for obj, source in self.objectives:
            total, bad = source()
            dq = self._series[obj.name]
            dq.append((now, float(total), float(bad)))
            self._prune(dq, now)
            n_fast, frac_fast = self._window(dq, now, self.fast_window)
            n_slow, frac_slow = self._window(dq, now, self.slow_window)
            burn_fast = (frac_fast / obj.budget) if frac_fast is not None \
                else 0.0
            burn_slow = (frac_slow / obj.budget) if frac_slow is not None \
                else 0.0
            # No events → vacuously compliant (an idle fleet is not out
            # of SLO; NaN-style unknowns must not page).
            compliant = frac_slow is None or frac_slow <= obj.budget
            if burn_fast >= self.page_burn and burn_slow >= self.page_burn:
                state = PAGE
            elif burn_fast >= self.warn_burn and burn_slow >= self.warn_burn:
                state = WARN
            else:
                state = OK
            if _STATE_NUM[state] > _STATE_NUM[worst]:
                worst = state
            worst_burn = max(worst_burn, burn_fast)
            if self._g_burn is not None:
                self._g_burn.set(burn_fast, labels={
                    "objective": obj.name, "window": "fast"})
                self._g_burn.set(burn_slow, labels={
                    "objective": obj.name, "window": "slow"})
                self._g_compliant.set(
                    1.0 if compliant else 0.0,
                    labels={"objective": obj.name})
            rows.append({
                "name": obj.name,
                "objective": obj.objective,
                "threshold_s": obj.threshold_s,
                "events_total": _num(total),
                "events_bad": _num(bad),
                "window_events_fast": _num(n_fast),
                "window_events_slow": _num(n_slow),
                "bad_frac_fast": _num(frac_fast),
                "bad_frac_slow": _num(frac_slow),
                "burn_fast": _num(burn_fast),
                "burn_slow": _num(burn_slow),
                "compliant": compliant,
                "state": state,
            })
        dominant = None
        if self.attribution_fn is not None:
            try:
                dominant = self.attribution_fn()
            except Exception:
                dominant = None     # attribution must never kill the tick
        if worst != self.state:
            # SLO state transition → flight-recorder event; a transition
            # INTO PAGE additionally dumps the ring — the black box's
            # "what led up to the page" trigger (throttled per reason so
            # a burn rate flapping at the threshold can't grind disk).
            # The ledger's dominant phase rides along, so the PAGE names
            # WHERE the budget went (queue, kv_transfer, migration, ...).
            rec = flight_recorder.get_recorder()
            rec.record("slo_state", prev=self.state, state=worst,
                       burn=round(worst_burn, 3),
                       dominant_phase=dominant)
            if worst == PAGE and rec.enabled:
                # Async: tick may run on the serving event loop, which
                # must not stall behind ring serialization + file I/O.
                rec.dump_async("slo_page")
        self.state = worst
        self.last_max_burn = worst_burn
        if self._g_state is not None:
            self._g_state.set(float(_STATE_NUM[worst]))
        return {
            "enabled": True,
            "state": worst,
            "dominant_phase": dominant,
            "windows": {"fast_s": self.fast_window,
                        "slow_s": self.slow_window},
            "thresholds": {"warn_burn": self.warn_burn,
                           "page_burn": self.page_burn},
            "objectives": rows,
        }

    def payload(self) -> dict:
        """Fresh /debug/slo payload (ticks on demand — a scrape is as
        good a sample point as a timer)."""
        return self.tick()

    # -- background ticking ------------------------------------------------

    def start(self, interval: float = 5.0) -> None:
        """Periodic ticking so the burn gauges stay fresh on /metrics
        even when nobody hits /debug/slo.  Call from a running loop."""
        if self._task is not None:
            return

        async def loop():
            while True:
                await asyncio.sleep(interval)
                try:
                    self.tick()
                except Exception as e:  # telemetry must never kill serving
                    warn_rate_limited(
                        _logger, "slo_tick", 60.0,
                        "SLO tick failed (burn gauges go stale): %s", e)

        self._task = asyncio.get_running_loop().create_task(loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


def disabled_payload() -> dict:
    return {"enabled": False, "state": OK, "objectives": []}


def max_burn(payload: Optional[dict]) -> float:
    """Worst fast-window burn rate across a /debug/slo payload's
    objectives (0.0 for missing/disabled payloads) — the planner's
    scale-up pressure signal."""
    if not payload or not payload.get("enabled"):
        return 0.0
    burns = [o.get("burn_fast") or 0.0
             for o in payload.get("objectives", [])]
    return max(burns) if burns else 0.0


# -- flag surface (worker + frontend) ------------------------------------


def add_slo_args(p) -> None:
    p.add_argument("--slo-ttft-p99", type=float, default=None,
                   help="TTFT objective threshold (seconds): "
                        "--slo-target fraction of requests must see "
                        "first token within this (None disables)")
    p.add_argument("--slo-tpot-p99", type=float, default=None,
                   help="TPOT objective threshold (seconds per output "
                        "token after the first)")
    p.add_argument("--slo-error-rate", type=float, default=None,
                   help="error budget fraction (0.01 = 99%% of requests "
                        "must finish ok)")
    p.add_argument("--slo-target", type=float, default=0.99,
                   help="good-fraction target for the latency "
                        "objectives (0.99 = p99)")
    p.add_argument("--slo-fast-window", type=float, default=300.0,
                   help="fast burn-rate window (seconds)")
    p.add_argument("--slo-slow-window", type=float, default=3600.0,
                   help="slow burn-rate window (seconds)")
    p.add_argument("--slo-warn-burn", type=float, default=3.0,
                   help="WARN when both windows burn at or above this")
    p.add_argument("--slo-page-burn", type=float, default=14.4,
                   help="PAGE when both windows burn at or above this")
    p.add_argument("--slo-tick", type=float, default=5.0,
                   help="background evaluation interval (seconds)")


def monitor_from_args(args, request_metrics: RequestMetrics,
                      registry: Optional[MetricsRegistry] = None,
                      attribution_fn: Optional[Callable] = None,
                      ) -> Optional[SloMonitor]:
    """Build the monitor the flags describe over the process's
    RequestMetrics histograms; None when no objective is configured
    (the /debug/slo route then reports enabled=false)."""
    objectives: List[Tuple[SloObjective, Callable]] = []
    if args.slo_ttft_p99 is not None:
        objectives.append((
            SloObjective("ttft_p99", objective=args.slo_target,
                         threshold_s=args.slo_ttft_p99),
            latency_source(request_metrics.ttft, args.slo_ttft_p99)))
    if args.slo_tpot_p99 is not None:
        objectives.append((
            SloObjective("tpot_p99", objective=args.slo_target,
                         threshold_s=args.slo_tpot_p99),
            latency_source(request_metrics.tpot, args.slo_tpot_p99)))
    if args.slo_error_rate is not None:
        objectives.append((
            SloObjective("error_rate",
                         objective=1.0 - args.slo_error_rate),
            error_source(request_metrics.outcomes)))
    if not objectives:
        return None
    return SloMonitor(
        objectives,
        fast_window=args.slo_fast_window,
        slow_window=args.slo_slow_window,
        warn_burn=args.slo_warn_burn,
        page_burn=args.slo_page_burn,
        registry=registry,
        attribution_fn=attribution_fn)
