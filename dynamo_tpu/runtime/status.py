"""Per-process system status server: /health, /live, /metrics.

Role of the reference's `system_status_server.rs` (axum; routes at
:155-176): every long-running process — worker, frontend, aggregator —
exposes liveness, readiness, and Prometheus text on its own port.  The
frontend embeds these in its OpenAI server; this module is the
standalone variant for processes without an HTTP ingress (workers).
"""

from __future__ import annotations

import logging
from typing import Awaitable, Callable, Optional

from aiohttp import web

from dynamo_tpu.runtime.metrics import MetricsRegistry

logger = logging.getLogger(__name__)


class StatusServer:
    def __init__(self,
                 registry: Optional[MetricsRegistry] = None,
                 ready_fn: Optional[Callable[[], bool]] = None,
                 extra_text_fn: Optional[Callable[[], str]] = None) -> None:
        """`ready_fn`: readiness probe (default: always ready once
        serving).  `extra_text_fn`: extra Prometheus text appended to the
        registry exposition (e.g. the worker's ForwardPassMetrics)."""
        self.registry = registry or MetricsRegistry()
        self.ready_fn = ready_fn or (lambda: True)
        self.extra_text_fn = extra_text_fn
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        logger.info("status server on %s:%d", host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _health(self, _req: web.Request) -> web.Response:
        ok = bool(self.ready_fn())
        return web.json_response({"status": "ready" if ok else "starting"},
                                 status=200 if ok else 503)

    async def _live(self, _req: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _metrics(self, _req: web.Request) -> web.Response:
        text = self.registry.expose()
        if self.extra_text_fn:
            text += self.extra_text_fn()
        return web.Response(text=text, content_type="text/plain")
