"""Per-process system status server: /health, /live, /metrics.

Role of the reference's `system_status_server.rs` (axum; routes at
:155-176): every long-running process — worker, frontend, aggregator —
exposes liveness, readiness, and Prometheus text on its own port.  The
frontend embeds these in its OpenAI server; this module is the
standalone variant for processes without an HTTP ingress (workers).
"""

from __future__ import annotations

import logging
from typing import Awaitable, Callable, Optional

from aiohttp import web

from dynamo_tpu.runtime.metrics import MetricsRegistry

logger = logging.getLogger(__name__)

# Control-plane prefix where processes advertise their status servers so
# the metrics_aggregator can scrape /metrics from components that are not
# workers (router_service, planner) — the reference's Prometheus
# service-discovery analog, over our own control plane.
STATUS_ENDPOINTS_PREFIX = "status_endpoints"


async def register_status_endpoint(cp, component: str, port: int,
                                   host: str = "127.0.0.1",
                                   extra: Optional[dict] = None) -> str:
    """Advertise a status server for aggregator scraping; returns the
    key written.  Unleased on purpose: the aggregator treats unreachable
    targets as gone — and since ISSUE 14 the registration carries the
    owning PID, so scrapers (`dynamo top`, metrics_aggregator) can REAP
    a kill -9'd worker's stale entry instead of rendering it
    unreachable forever.  `host` must be a cross-host-routable address
    when the aggregator runs on another machine (same rule as the
    worker's --rpc-host).

    `extra`: additional registration fields (ISSUE 16: workers attach
    their SliceSpec wire dict under "slice" so `dynamo top` can render
    a MESH column without scraping anything new).  Reserved keys
    (address/component/pid) cannot be overridden."""
    import os

    key = f"{STATUS_ENDPOINTS_PREFIX}/{component}/{os.getpid()}"
    entry = dict(extra or {})
    entry.update({"address": f"{host}:{port}", "component": component,
                  "pid": os.getpid()})
    await cp.put(key, entry)
    return key


def registration_pid_dead(entry) -> bool:
    """True only when a status-endpoint registration names a pid that is
    PROVABLY gone: the entry carries a pid, its address is loopback
    (pid liveness is only decidable same-host — a loopback address from
    another machine was never scrapeable by us anyway), and signal-0
    probing reports no such process.  Everything ambiguous — foreign
    hosts, permission errors, malformed entries — reads as alive, so
    reaping can never take down a live worker's discovery entry."""
    import os

    if not isinstance(entry, dict):
        return False
    pid = entry.get("pid")
    addr = entry.get("address") or ""
    host = addr.rsplit(":", 1)[0] if ":" in addr else ""
    if not pid or host not in ("127.0.0.1", "localhost", "::1", "[::1]"):
        return False
    try:
        os.kill(int(pid), 0)
        return False
    except ProcessLookupError:
        return True
    except (PermissionError, OSError, ValueError, TypeError):
        return False


def register_status_endpoint_task(cp, component: str, port: int,
                                  host: str = "127.0.0.1",
                                  retry_interval: float = 1.0,
                                  extra: Optional[dict] = None):
    """Best-effort registration as a background task: retries until the
    put lands (the control-plane client reconnects underneath), so a
    control plane that is briefly down at process startup neither
    crashes the process nor silently loses its discovery entry.  Returns
    the task (cancel at shutdown)."""
    import asyncio

    async def register():
        while True:
            try:
                await register_status_endpoint(cp, component, port,
                                               host=host, extra=extra)
                return
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # ANY failure retries (ConnectionError while down,
                # RuntimeError from an error reply mid-restart, …): a
                # dead registration task would silently drop this
                # process from fleet discovery forever.
                logger.warning(
                    "status-endpoint registration for %s failed (%s); "
                    "retrying", component, e)
                await asyncio.sleep(retry_interval)

    return asyncio.get_running_loop().create_task(register())


class StatusServer:
    def __init__(self,
                 registry: Optional[MetricsRegistry] = None,
                 ready_fn: Optional[Callable[[], bool]] = None,
                 extra_text_fn: Optional[Callable[[], str]] = None,
                 slo_fn: Optional[Callable[[], dict]] = None) -> None:
        """`ready_fn`: readiness probe (default: always ready once
        serving).  `extra_text_fn`: extra Prometheus text appended to the
        registry exposition (e.g. the worker's ForwardPassMetrics).
        `slo_fn`: /debug/slo payload provider (an SloMonitor's `payload`;
        None reports the monitor as disabled)."""
        self.registry = registry or MetricsRegistry()
        self.ready_fn = ready_fn or (lambda: True)
        self.extra_text_fn = extra_text_fn
        self.slo_fn = slo_fn
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/debug/traces", self._debug_traces)
        app.router.add_get("/debug/slo", self._debug_slo)
        app.router.add_get("/debug/flightrecorder",
                           self._debug_flightrecorder)
        app.router.add_get("/debug/deviceprofile",
                           self._debug_deviceprofile)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        logger.info("status server on %s:%d", host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _health(self, _req: web.Request) -> web.Response:
        ok = bool(self.ready_fn())
        return web.json_response({"status": "ready" if ok else "starting"},
                                 status=200 if ok else 503)

    async def _live(self, _req: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _metrics(self, _req: web.Request) -> web.Response:
        text = self.registry.expose()
        if self.extra_text_fn:
            text += self.extra_text_fn()
        return web.Response(text=text, content_type="text/plain")

    async def _debug_traces(self, req: web.Request) -> web.Response:
        """This process's completed traces (`?n=K`, default 32); same
        payload shape as the frontend's /debug/traces so
        tools/trace_merge.py treats every process uniformly."""
        from dynamo_tpu.runtime import tracing

        try:
            n = int(req.query.get("n", "32"))
        except ValueError:
            return web.json_response({"error": "n must be an integer"},
                                     status=400)
        return web.json_response(tracing.debug_traces_payload(n))

    async def _debug_flightrecorder(self, req: web.Request) -> web.Response:
        """This process's flight-recorder ring (`?n=K`, default 256) —
        same payload shape as the frontend's route, so chaos tooling and
        `tools/trace_merge.py --flight` treat every process uniformly."""
        from dynamo_tpu.runtime import flight_recorder

        try:
            n = int(req.query.get("n", "256"))
        except ValueError:
            return web.json_response({"error": "n must be an integer"},
                                     status=400)
        return web.json_response(
            flight_recorder.get_recorder().debug_payload(n))

    async def _debug_deviceprofile(self, req: web.Request) -> web.Response:
        """Device-truth plane (runtime/device_profiler.py).  Without
        `?ms=` it reports the plane's state (program registry, drift
        band states, capture history); with `?ms=N` it runs one bounded
        jax.profiler capture on this live process — off the event loop
        (asyncio.to_thread: the capture sleeps for its bound while the
        serving threads keep dispatching) — and returns what landed."""
        import asyncio

        from dynamo_tpu.runtime import device_profiler

        prof = device_profiler.get_profiler()
        ms_raw = req.query.get("ms")
        if ms_raw is None:
            return web.json_response(prof.debug_payload())
        try:
            ms = int(ms_raw)
            if ms <= 0:
                raise ValueError
        except ValueError:
            return web.json_response(
                {"error": "ms must be a positive integer"}, status=400)
        res = await asyncio.to_thread(prof.capture, ms)
        return web.json_response(res, status=200 if res.get("ok") else 503)

    async def _debug_slo(self, _req: web.Request) -> web.Response:
        """Current SLO burn-rate evaluation (runtime/slo.py) — same
        payload shape as the frontend's /debug/slo so `dynamo top`
        treats every process uniformly."""
        from dynamo_tpu.runtime import slo as slo_mod

        if self.slo_fn is None:
            return web.json_response(slo_mod.disabled_payload())
        return web.json_response(self.slo_fn())
