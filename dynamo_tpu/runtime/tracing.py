"""End-to-end distributed request tracing (Dapper-style, Perfetto export).

One request crossing frontend → KV-router → RPC → worker → engine gets ONE
trace id; every hop records spans that parent correctly across process
boundaries, so `tools/trace_merge.py` can stitch the per-process buffers
into a single timeline loadable in Perfetto / chrome://tracing.  The
reference stack leans on per-hop metrics plus a grep-able request id
(`logging.rs:73-79`); this module upgrades that id into a real trace
context carried on the RPC frame (`runtime/rpc.py` `trace` field).

Design constraints, in order:

1. **Zero cost when disabled** (the default).  `start_span` returns a
   shared no-op span; hot paths guard on `tracer.enabled`; nothing here
   ever touches a device or blocks.
2. **Bounded memory.**  Completed traces live in a ring buffer
   (`ring_size` traces); in-flight spans are capped per trace
   (`max_spans_per_trace`) and across traces (`max_pending`).
3. **Production triage at low sampling.**  Sampling is decided once at
   the root (deterministic hash of the trace id, so retries of the same
   id sample identically) and propagated on the wire.  A local root that
   finishes slower than `slow_ms` is force-kept and logged as one
   structured JSONL line even when unsampled.

Span model: a span is identified by (trace_id, span_id) with an optional
parent_id.  "Local roots" — spans whose parent is remote (a wire-extracted
TraceContext) or absent — own trace finalization in their process: when
the last open local root of a trace ends, the trace's spans move from the
pending buffer to the ring (or are dropped if unsampled and fast).

Timestamps: wall-clock (`time.time()`) for cross-process alignment in the
merged view, monotonic deltas for durations.
"""

from __future__ import annotations

import contextvars
import json
import logging
import threading
import time
import uuid
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)
slow_logger = logging.getLogger("dynamo_tpu.trace.slow")


def _gen_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """Propagatable identity of one span: inject with `to_wire`, extract
    with `from_wire`, derive a child span's context with `child`."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    sampled: bool = True

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _gen_id(), self.span_id,
                            self.sampled)

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled}

    @staticmethod
    def from_wire(d) -> Optional["TraceContext"]:
        """None on anything malformed — a bad peer must never break the
        request path for the sake of telemetry."""
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not tid or not sid:
            return None
        return TraceContext(str(tid), str(sid), None,
                            bool(d.get("sampled", True)))


class Span:
    """An open span; `end()` (or `with`) records it on its tracer."""

    __slots__ = ("tracer", "name", "ctx", "attrs", "local_root",
                 "start_wall", "start_mono", "_ended", "_cv_token")

    def __init__(self, tracer: "Tracer", name: str, ctx: TraceContext,
                 local_root: bool, attrs: Optional[dict] = None) -> None:
        self.tracer = tracer
        self.name = name
        self.ctx = ctx
        self.attrs = dict(attrs) if attrs else {}
        self.local_root = local_root
        self.start_wall = time.time()
        self.start_mono = time.monotonic()
        self._ended = False
        self._cv_token = None

    def set_attr(self, **attrs) -> None:
        self.attrs.update(attrs)

    @property
    def duration_s(self) -> float:
        return time.monotonic() - self.start_mono

    def end(self, **attrs) -> None:
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        self.tracer._finish_span(self)

    def __enter__(self) -> "Span":
        # `with` makes the span task-current, so spans opened inside the
        # block (including rpc.client spans several calls down) nest
        # under it rather than under whatever was current outside.
        self._cv_token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._cv_token is not None:
            _current_span.reset(self._cv_token)
            self._cv_token = None
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.end()


class _NullSpan:
    """Shared no-op span: the disabled/unsampled fast path."""

    __slots__ = ()
    ctx = None
    local_root = False
    name = ""
    attrs: dict = {}

    def set_attr(self, **attrs) -> None:
        pass

    @property
    def duration_s(self) -> float:
        return 0.0

    def end(self, **attrs) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass

    def __bool__(self) -> bool:
        return False


NULL_SPAN = _NullSpan()

# The task-local current span (set by the HTTP root and the RPC server
# span); asyncio.create_task snapshots it, so pump tasks spawned by a
# request handler inherit the request's context automatically.
_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "dynamo_trace_span", default=None)


def current_span():
    """The task's active span (a Span), or None."""
    return _current_span.get()


def use_span(span):
    """Make `span` the task-local current span; returns a token for
    `restore`."""
    return _current_span.set(span if span is not NULL_SPAN else None)


def restore(token) -> None:
    _current_span.reset(token)


def _sample_decision(trace_id: str, rate: float) -> bool:
    """Deterministic per-trace sampling: the same trace id samples the
    same way in every process and across retries."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    h = zlib.crc32(trace_id.encode("utf-8", "replace")) & 0xFFFFFFFF
    return (h / 2**32) < rate


class Tracer:
    """Process-local span collector: bounded pending buffer for in-flight
    traces, ring buffer of completed traces, per-request-id context
    binding for the engine thread."""

    def __init__(self, service: str = "dynamo", *, enabled: bool = False,
                 sampling: float = 1.0, ring_size: int = 256,
                 slow_ms: Optional[float] = None,
                 slow_log_path: Optional[str] = None,
                 max_spans_per_trace: int = 256,
                 max_pending: int = 1024) -> None:
        self.service = service
        self.enabled = enabled
        self.sampling = sampling
        self.slow_ms = slow_ms
        self.slow_log_path = slow_log_path
        self.max_spans_per_trace = max_spans_per_trace
        self.max_pending = max_pending
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring_size)
        self._pending: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._open_roots: Dict[str, int] = {}       # trace_id → open count
        self._dropped: Dict[str, int] = {}          # trace_id → span drops
        self._finalized: deque = deque(maxlen=512)  # recent trace ids
        self._finalized_set: set = set()
        # Open (sampled) spans per trace: spans still running when their
        # trace finalizes are materialized with partial duration and an
        # `unfinished` attr — an abandoned streaming generator's span
        # (whose `finally` only runs at async-gen GC) must not vanish
        # from the timeline.
        self._open: "OrderedDict[str, Dict[str, Span]]" = OrderedDict()
        self._bindings: "OrderedDict[str, TraceContext]" = OrderedDict()
        # Telemetry about the telemetry (tests + overhead accounting).
        self.spans_recorded = 0
        self.traces_dropped_unsampled = 0
        self.traces_forced_slow = 0

    # -- configuration -----------------------------------------------------

    def configure(self, *, service: Optional[str] = None,
                  enabled: Optional[bool] = None,
                  sampling: Optional[float] = None,
                  ring_size: Optional[int] = None,
                  slow_ms: Optional[float] = None,
                  slow_log_path: Optional[str] = None) -> "Tracer":
        """In-place reconfiguration (the module singleton is shared by
        reference; identity must survive)."""
        with self._lock:
            if service is not None:
                self.service = service
            if enabled is not None:
                self.enabled = enabled
            if sampling is not None:
                self.sampling = sampling
            if ring_size is not None:
                self._ring = deque(self._ring, maxlen=ring_size)
            if slow_ms is not None:
                self.slow_ms = slow_ms
            if slow_log_path is not None:
                self.slow_log_path = slow_log_path
        return self

    def reset(self) -> None:
        """Drop all state (test isolation)."""
        with self._lock:
            self._ring.clear()
            self._pending.clear()
            self._open_roots.clear()
            self._dropped.clear()
            self._finalized.clear()
            self._finalized_set.clear()
            self._open.clear()
            self._bindings.clear()
            self.spans_recorded = 0
            self.traces_dropped_unsampled = 0
            self.traces_forced_slow = 0

    # -- span creation -----------------------------------------------------

    def start_span(self, name: str, parent=None, *,
                   trace_id: Optional[str] = None,
                   attrs: Optional[dict] = None):
        """Open a span.

        `parent`: a Span (same-process child), a TraceContext (remote
        parent — this span becomes a local root), or None (parent from
        the task-local current span; if none, a NEW trace starts here,
        with `trace_id` reused if given — e.g. the request id).
        Returns NULL_SPAN when tracing is disabled or the trace is
        unsampled (local roots of unsampled traces stay real so the
        slow-request force-sample can fire)."""
        if not self.enabled:
            return NULL_SPAN
        if parent is None:
            parent = current_span()
        if isinstance(parent, _NullSpan):
            parent = None
        local_root = not isinstance(parent, Span)
        if parent is None:
            tid = trace_id or _gen_id()
            ctx = TraceContext(tid, _gen_id(), None,
                               _sample_decision(tid, self.sampling))
        else:
            pctx = parent.ctx if isinstance(parent, Span) else parent
            if pctx is None:
                return NULL_SPAN
            ctx = pctx.child()
        if not ctx.sampled and not local_root:
            return NULL_SPAN  # sub-spans of unsampled traces cost nothing
        span = Span(self, name, ctx, local_root, attrs)
        with self._lock:
            if local_root:
                self._open_roots[ctx.trace_id] = \
                    self._open_roots.get(ctx.trace_id, 0) + 1
            if ctx.sampled:
                per_trace = self._open.get(ctx.trace_id)
                if per_trace is None:
                    while len(self._open) >= self.max_pending:
                        self._open.popitem(last=False)
                    per_trace = self._open[ctx.trace_id] = {}
                per_trace[ctx.span_id] = span
        return span

    def record_span(self, name: str, parent, start_mono: float,
                    end_mono: Optional[float] = None,
                    attrs: Optional[dict] = None) -> None:
        """Record an already-measured span from monotonic timestamps (the
        engine thread's admission→first-token spans: the interval was
        measured before anyone knew it would be traced)."""
        if not self.enabled or parent is None:
            return
        pctx = parent.ctx if isinstance(parent, Span) else parent
        if pctx is None or not pctx.sampled:
            return
        ctx = pctx.child()
        now_mono = time.monotonic()
        end_mono = now_mono if end_mono is None else end_mono
        wall_start = time.time() - (now_mono - start_mono)
        self._record(ctx, name, wall_start, max(0.0, end_mono - start_mono),
                     dict(attrs) if attrs else {})

    # -- request-id binding (engine thread) --------------------------------

    def bind(self, request_id: str, ctx: Optional[TraceContext]) -> None:
        """Associate a request id with its serving span's context so
        engine-side spans (emitted on the engine thread, which has no
        contextvars from the serving task) parent correctly."""
        if not self.enabled or ctx is None:
            return
        with self._lock:
            self._bindings[request_id] = ctx
            self._bindings.move_to_end(request_id)
            while len(self._bindings) > self.max_pending:
                self._bindings.popitem(last=False)

    def unbind(self, request_id: str) -> None:
        with self._lock:
            self._bindings.pop(request_id, None)

    def ctx_for(self, request_id: str) -> Optional[TraceContext]:
        with self._lock:
            return self._bindings.get(request_id)

    # -- recording / finalization ------------------------------------------

    def _span_dict(self, ctx: TraceContext, name: str, wall_start: float,
                   dur_s: float, attrs: dict) -> dict:
        return {"name": name, "trace_id": ctx.trace_id,
                "span_id": ctx.span_id, "parent_id": ctx.parent_id,
                "service": self.service, "ts": wall_start, "dur": dur_s,
                "attrs": attrs}

    def _record(self, ctx: TraceContext, name: str, wall_start: float,
                dur_s: float, attrs: dict) -> None:
        d = self._span_dict(ctx, name, wall_start, dur_s, attrs)
        with self._lock:
            if ctx.trace_id in self._finalized_set:
                return  # late engine span after the trace shipped
            spans = self._pending.get(ctx.trace_id)
            if spans is None:
                if len(self._pending) >= self.max_pending:
                    # Evict the oldest in-flight trace wholesale: a leak
                    # here (crashed peers, abandoned streams) must never
                    # grow without bound.
                    self._pending.popitem(last=False)
                spans = self._pending[ctx.trace_id] = []
            if len(spans) >= self.max_spans_per_trace:
                self._dropped[ctx.trace_id] = \
                    self._dropped.get(ctx.trace_id, 0) + 1
                return
            spans.append(d)
            self.spans_recorded += 1

    def _finish_span(self, span: Span) -> None:
        dur = time.monotonic() - span.start_mono
        with self._lock:
            per_trace = self._open.get(span.ctx.trace_id)
            if per_trace is not None:
                per_trace.pop(span.ctx.span_id, None)
        slow = (self.slow_ms is not None
                and dur * 1000.0 > self.slow_ms)
        if span.ctx.sampled or (span.local_root and slow):
            if slow:
                span.attrs.setdefault("forced_slow_sample", True)
            self._record(span.ctx, span.name, span.start_wall, dur,
                         span.attrs)
        if not span.local_root:
            return
        tid = span.ctx.trace_id
        finalize = False
        with self._lock:
            n = self._open_roots.get(tid, 1) - 1
            if n <= 0:
                self._open_roots.pop(tid, None)
                finalize = True
            else:
                self._open_roots[tid] = n
        if finalize:
            self._finalize(tid, keep=span.ctx.sampled or slow, slow=slow,
                           root_span=span, dur_s=dur)

    def _finalize(self, trace_id: str, keep: bool, slow: bool,
                  root_span: Optional[Span] = None,
                  dur_s: float = 0.0) -> None:
        now_mono = time.monotonic()
        with self._lock:
            spans = self._pending.pop(trace_id, [])
            dropped = self._dropped.pop(trace_id, 0)
            # Still-open spans (abandoned streaming generators): ship
            # them with the duration they reached; their eventual end()
            # is a no-op against the finalized trace.
            for sp in (self._open.pop(trace_id, None) or {}).values():
                if keep and len(spans) < self.max_spans_per_trace:
                    attrs = dict(sp.attrs)
                    attrs["unfinished"] = True
                    spans.append(self._span_dict(
                        sp.ctx, sp.name, sp.start_wall,
                        max(0.0, now_mono - sp.start_mono), attrs))
                    self.spans_recorded += 1
            if len(self._finalized) == self._finalized.maxlen:
                # The deque is about to evict its oldest id; keep the
                # membership set in lockstep.
                self._finalized_set.discard(self._finalized[0])
            self._finalized.append(trace_id)
            self._finalized_set.add(trace_id)
            if not keep or not spans:
                if not keep:
                    self.traces_dropped_unsampled += 1
                spans = None
            else:
                trace = {"trace_id": trace_id, "service": self.service,
                         "spans": spans}
                if dropped:
                    trace["spans_dropped"] = dropped
                if slow:
                    trace["forced_slow_sample"] = True
                    self.traces_forced_slow += 1
                self._ring.append(trace)
        if slow and root_span is not None:
            self._log_slow(trace_id, root_span, dur_s)

    def _log_slow(self, trace_id: str, root_span: Span,
                  dur_s: float) -> None:
        """One structured JSONL line per slow request — the low-sampling
        triage hook (grep the trace_id, then pull /debug/traces)."""
        line = json.dumps({
            "event": "slow_request", "service": self.service,
            "trace_id": trace_id, "span": root_span.name,
            "duration_ms": round(dur_s * 1000.0, 3),
            "slow_ms": self.slow_ms, "ts": time.time(),
            "attrs": root_span.attrs,
        }, default=str)
        if self.slow_log_path:
            try:
                with open(self.slow_log_path, "a") as f:
                    f.write(line + "\n")
            except OSError:
                logger.exception("slow-trace JSONL write failed")
        slow_logger.warning("%s", line)
        # Slow-request force-sample is also a flight-recorder trigger
        # (ISSUE 14): the ring at the moment of the slow request is the
        # "what was the engine doing" half the trace alone can't show.
        # Per-reason throttled dump — sustained overload produces many
        # slow requests but the ring only needs snapshotting so often.
        from dynamo_tpu.runtime import flight_recorder

        rec = flight_recorder.get_recorder()
        if rec.enabled:
            rec.record("slow_request", trace_id=trace_id,
                       span=root_span.name,
                       duration_ms=round(dur_s * 1000.0, 3))
            # Async: _finish_span runs on whatever thread ended the
            # root span — often the serving event loop.
            rec.dump_async("slow_request")

    # -- export ------------------------------------------------------------

    def completed(self, n: Optional[int] = None) -> List[dict]:
        """Most recent completed traces, newest first."""
        with self._lock:
            traces = list(self._ring)
        traces.reverse()
        return traces if n is None else traces[:max(0, n)]


def chrome_trace(traces: List[dict]) -> dict:
    """Chrome trace-event JSON (the `traceEvents` array format Perfetto
    and chrome://tracing load): one complete ("ph":"X") event per span,
    one process per originating service, one thread lane per trace.

    Accepts the trace dicts `Tracer.completed` / `/debug/traces` return —
    possibly from several processes; spans duplicated across payloads
    (shared in-process tracers) dedupe by (trace_id, span_id)."""
    pids: Dict[str, int] = {}
    tids: Dict[str, int] = {}
    events: List[dict] = []
    seen: set = set()
    for trace in traces:
        for span in trace.get("spans", []):
            key = (span["trace_id"], span["span_id"])
            if key in seen:
                continue
            seen.add(key)
            service = span.get("service", "dynamo")
            pid = pids.setdefault(service, len(pids) + 1)
            tid = tids.setdefault(span["trace_id"], len(tids) + 1)
            args = dict(span.get("attrs", {}))
            args.update(trace_id=span["trace_id"],
                        span_id=span["span_id"],
                        parent_id=span.get("parent_id"))
            events.append({
                "name": span["name"], "cat": "dynamo", "ph": "X",
                "ts": round(span["ts"] * 1e6, 3),
                "dur": round(span["dur"] * 1e6, 3),
                "pid": pid, "tid": tid, "args": args,
            })
    for service, pid in pids.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": service}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def debug_traces_payload(n: int, tracer: Optional[Tracer] = None) -> dict:
    """The `/debug/traces` response body — ONE shape for every process
    (frontend HttpService, worker/router/planner StatusServer), so
    tools/trace_merge.py treats all sources uniformly."""
    t = tracer or get_tracer()
    return {"service": t.service, "enabled": t.enabled,
            "traces": t.completed(n)}


# ---------------------------------------------------------------------------
# Process singleton

_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def configure(**kwargs) -> Tracer:
    """Configure the process tracer (see Tracer.configure)."""
    return _tracer.configure(**kwargs)


def add_trace_args(parser) -> None:
    """The shared --trace* CLI surface (frontend, worker, router_service,
    planner)."""
    parser.add_argument("--trace", action="store_true",
                        help="enable distributed request tracing "
                             "(spans in a bounded ring, /debug/traces)")
    parser.add_argument("--trace-sample", type=float, default=1.0,
                        help="trace sampling rate in [0,1] (per trace id, "
                             "deterministic across processes)")
    parser.add_argument("--trace-slow-ms", type=float, default=None,
                        help="force-sample + JSONL-log any request slower "
                             "than this many ms, regardless of sampling")
    parser.add_argument("--trace-ring", type=int, default=256,
                        help="completed traces kept per process")
    parser.add_argument("--trace-slow-log", default=None,
                        help="append slow-request JSONL lines to this file "
                             "(default: python logging only)")


def configure_from_args(args, service: str) -> Tracer:
    """Apply the add_trace_args flags to the process tracer."""
    return configure(
        service=service, enabled=bool(getattr(args, "trace", False)),
        sampling=getattr(args, "trace_sample", 1.0),
        ring_size=getattr(args, "trace_ring", 256),
        slow_ms=getattr(args, "trace_slow_ms", None),
        slow_log_path=getattr(args, "trace_slow_log", None))
